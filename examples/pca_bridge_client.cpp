// Minimal non-Python PcaBackend bridge client.
//
// Proves the bridge protocol claim (spark_examples_tpu/bridge/backend.py:
// newline-JSON over TCP, init/calls/finish) from a foreign runtime — the
// role the reference's JVM driver plays when delegating its dense math
// (the RDD[Seq[Int]] stage boundary of VariantsPca.scala:153-168, shipped
// through the py4j seam in src/main/python/variants_pca.py:162-182).
//
// No JSON library: the protocol is line-delimited and the payload is
// integer index lists, so requests are assembled with printf-style
// formatting and the single response line is validated by substring
// checks plus a numeric parse of the first coordinate row. A real JVM/C++
// driver would link a JSON library; the wire bytes are identical.
//
// Usage: pca_bridge_client <port>
//   - sends a deterministic 6-sample cohort (3 variant batches)
//   - expects {"coords": [[...], ...], "eigvals": [...]}
//   - exits 0 iff coords parse as 6 rows of 2 finite doubles

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cmath>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

static bool send_line(int fd, const std::string& line) {
  std::string framed = line + "\n";
  size_t off = 0;
  while (off < framed.size()) {
    ssize_t n = ::send(fd, framed.data() + off, framed.size() - off, 0);
    if (n <= 0) return false;
    off += static_cast<size_t>(n);
  }
  return true;
}

static bool recv_line(int fd, std::string* out) {
  out->clear();
  char c;
  while (true) {
    ssize_t n = ::recv(fd, &c, 1, 0);
    if (n <= 0) return !out->empty();
    if (c == '\n') return true;
    out->push_back(c);
  }
}

int main(int argc, char** argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: %s <port>\n", argv[0]);
    return 2;
  }
  int port = std::atoi(argv[1]);

  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    std::perror("socket");
    return 1;
  }
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  ::inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    std::perror("connect");
    return 1;
  }

  // A 6-sample cohort: samples {0,1,2} co-vary and {3,4,5} co-vary, so the
  // first principal coordinate must separate the two groups.
  const char* init = "{\"cmd\": \"init\", \"n_samples\": 6, \"num_pc\": 2}";
  const char* batches[] = {
      "{\"cmd\": \"calls\", \"batch\": [[0, 1, 2], [0, 1], [1, 2]]}",
      "{\"cmd\": \"calls\", \"batch\": [[3, 4, 5], [3, 4]]}",
      "{\"cmd\": \"calls\", \"batch\": [[4, 5], [0, 1, 2], [3, 4, 5]]}",
  };
  if (!send_line(fd, init)) return 1;
  for (const char* b : batches) {
    if (!send_line(fd, b)) return 1;
  }
  if (!send_line(fd, "{\"cmd\": \"finish\"}")) return 1;

  std::string resp;
  if (!recv_line(fd, &resp)) {
    std::fprintf(stderr, "no response\n");
    return 1;
  }
  ::close(fd);

  if (resp.find("\"error\"") != std::string::npos) {
    std::fprintf(stderr, "server error: %s\n", resp.c_str());
    return 1;
  }
  if (resp.find("\"coords\"") == std::string::npos ||
      resp.find("\"eigvals\"") == std::string::npos) {
    std::fprintf(stderr, "malformed response: %s\n", resp.c_str());
    return 1;
  }

  // Parse every coordinate row: after "coords": [[r0], [r1], ...],
  // stopping at the "]]" that closes the coords array so a short row
  // count can never be padded out by parsing into eigvals.
  size_t pos = resp.find("\"coords\"");
  pos = resp.find('[', pos);
  size_t coords_end = resp.find("]]", pos);
  if (coords_end == std::string::npos) {
    std::fprintf(stderr, "unterminated coords array\n");
    return 1;
  }
  std::vector<std::vector<double>> rows;
  size_t cursor = pos + 1;
  while (rows.size() < 6) {
    size_t open = resp.find('[', cursor);
    size_t close = resp.find(']', open);
    if (open == std::string::npos || close == std::string::npos ||
        open > coords_end) {
      break;
    }
    std::string body = resp.substr(open + 1, close - open - 1);
    std::vector<double> row;
    const char* p = body.c_str();
    char* end = nullptr;
    while (true) {
      double v = std::strtod(p, &end);
      if (end == p) break;
      row.push_back(v);
      p = end;
      while (*p == ',' || *p == ' ') ++p;
    }
    rows.push_back(row);
    cursor = close + 1;
  }
  if (rows.size() != 6) {
    std::fprintf(stderr, "expected 6 coordinate rows, got %zu\n",
                 rows.size());
    return 1;
  }
  for (const auto& row : rows) {
    if (row.size() != 2 || !std::isfinite(row[0]) || !std::isfinite(row[1])) {
      std::fprintf(stderr, "bad coordinate row\n");
      return 1;
    }
  }
  // Group structure check: PC1 separates {0,1,2} from {3,4,5}.
  double lo = (rows[0][0] + rows[1][0] + rows[2][0]) / 3.0;
  double hi = (rows[3][0] + rows[4][0] + rows[5][0]) / 3.0;
  if ((lo > 0) == (hi > 0)) {
    std::fprintf(stderr, "PC1 did not separate the two sample groups\n");
    return 1;
  }
  std::printf("bridge ok: 6x2 coords, group separation %.4f vs %.4f\n", lo,
              hi);
  return 0;
}
