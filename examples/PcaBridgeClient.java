// Minimal JVM PcaBackend bridge client — dependency-free Java.
//
// Proves the bridge protocol claim (spark_examples_tpu/bridge/backend.py:
// newline-JSON over TCP, init/calls/finish) from the runtime the seam
// exists for: the reference's cross-language twin is a *Spark driver on a
// JVM* delegating the dense math through py4j
// (src/main/python/variants_pca.py:162-182; the RDD[Seq[Int]] stage
// boundary of VariantsPca.scala:153-168). A real Spark integration would
// ship partitions through foreachPartition writes; the wire bytes are
// identical to what this client sends.
//
// No JSON library: the protocol is line-delimited and the payload is
// integer index lists, so requests are string literals and the single
// response line is validated by substring checks plus a numeric parse of
// the coordinate rows — the same discipline as the C++ twin
// (pca_bridge_client.cpp).
//
// Usage: java PcaBridgeClient <port>
//   - sends a deterministic 6-sample cohort (3 variant batches)
//   - expects {"coords": [[...], ...], "eigvals": [...]}
//   - exits 0 iff coords parse as 6 rows of 2 finite doubles and PC1
//     separates samples {0,1,2} from {3,4,5}

import java.io.BufferedReader;
import java.io.InputStreamReader;
import java.io.OutputStreamWriter;
import java.io.Writer;
import java.net.Socket;
import java.nio.charset.StandardCharsets;
import java.util.ArrayList;
import java.util.List;

public final class PcaBridgeClient {
  public static void main(String[] args) throws Exception {
    if (args.length != 1) {
      System.err.println("usage: java PcaBridgeClient <port>");
      System.exit(2);
    }
    int port = Integer.parseInt(args[0]);
    String resp;
    try (Socket sock = new Socket("127.0.0.1", port)) {
      Writer w =
          new OutputStreamWriter(sock.getOutputStream(), StandardCharsets.UTF_8);
      BufferedReader r =
          new BufferedReader(
              new InputStreamReader(sock.getInputStream(), StandardCharsets.UTF_8));
      // Same 6-sample cohort as the C++ twin: samples {0,1,2} co-vary and
      // {3,4,5} co-vary, so PC1 must separate the groups.
      String[] lines = {
        "{\"cmd\": \"init\", \"n_samples\": 6, \"num_pc\": 2}",
        "{\"cmd\": \"calls\", \"batch\": [[0, 1, 2], [0, 1], [1, 2]]}",
        "{\"cmd\": \"calls\", \"batch\": [[3, 4, 5], [3, 4]]}",
        "{\"cmd\": \"calls\", \"batch\": [[4, 5], [0, 1, 2], [3, 4, 5]]}",
        "{\"cmd\": \"finish\"}",
      };
      for (String line : lines) {
        w.write(line);
        w.write('\n');
      }
      w.flush();
      resp = r.readLine();
    }
    if (resp == null) {
      System.err.println("no response");
      System.exit(1);
    }
    if (resp.contains("\"error\"")) {
      System.err.println("server error: " + resp);
      System.exit(1);
    }
    int coordsAt = resp.indexOf("\"coords\"");
    int eigvalsAt = resp.indexOf("\"eigvals\"");
    if (coordsAt < 0 || eigvalsAt < 0) {
      System.err.println("malformed response: " + resp);
      System.exit(1);
    }
    // Parse rows strictly inside the coords array ("]]" closes it), so a
    // short row count can never be padded out by parsing into eigvals.
    int open = resp.indexOf('[', coordsAt);
    int coordsEnd = resp.indexOf("]]", open);
    if (coordsEnd < 0) {
      System.err.println("unterminated coords array");
      System.exit(1);
    }
    List<double[]> rows = new ArrayList<>();
    int cursor = open + 1;
    while (rows.size() < 6) {
      int rowOpen = resp.indexOf('[', cursor);
      int rowClose = resp.indexOf(']', rowOpen + 1);
      if (rowOpen < 0 || rowClose < 0 || rowOpen > coordsEnd) {
        break;
      }
      String[] parts = resp.substring(rowOpen + 1, rowClose).split(",");
      double[] row = new double[parts.length];
      for (int i = 0; i < parts.length; i++) {
        row[i] = Double.parseDouble(parts[i].trim());
      }
      rows.add(row);
      cursor = rowClose + 1;
    }
    if (rows.size() != 6) {
      System.err.println("expected 6 coordinate rows, got " + rows.size());
      System.exit(1);
    }
    for (double[] row : rows) {
      if (row.length != 2
          || !Double.isFinite(row[0])
          || !Double.isFinite(row[1])) {
        System.err.println("bad coordinate row");
        System.exit(1);
      }
    }
    double lo = (rows.get(0)[0] + rows.get(1)[0] + rows.get(2)[0]) / 3.0;
    double hi = (rows.get(3)[0] + rows.get(4)[0] + rows.get(5)[0]) / 3.0;
    if ((lo > 0) == (hi > 0)) {
      System.err.println("PC1 did not separate the two sample groups");
      System.exit(1);
    }
    System.out.printf(
        "bridge ok (jvm): 6x2 coords, group separation %.4f vs %.4f%n", lo, hi);
  }
}
