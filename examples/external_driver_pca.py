"""External-driver example: delegate the dense math over the bridge.

The role the reference's PySpark twin plays (``variants_pca.py``: drive the
ingest elsewhere, hand the per-variant call data to the math backend). Any
process — a Spark/Scala driver, a workflow engine — speaks the same
newline-JSON protocol; this script is the minimal client: it generates a
cohort locally (standing in for the external ingest), streams the
``RDD[Seq[Int]]``-shaped call lists to a running ``pca-bridge`` server, and
prints the returned principal coordinates.

Usage:
    python -m spark_examples_tpu.cli.main pca-bridge --port 18717 &
    python examples/external_driver_pca.py --port 18717
"""

import argparse

from spark_examples_tpu.bridge import PcaBridgeClient
from spark_examples_tpu.genomics.callsets import CallsetIndex
from spark_examples_tpu.genomics.datasets import calls_stream
from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.genomics.shards import shards_for_references


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=18717)
    p.add_argument("--samples", type=int, default=50)
    p.add_argument("--variants", type=int, default=500)
    p.add_argument("--num-pc", type=int, default=2)
    args = p.parse_args()

    # "External" ingest: any system that can produce per-variant lists of
    # carrying-sample indices.
    source = synthetic_cohort(args.samples, args.variants)
    index = CallsetIndex.from_source(source, [DEFAULT_VARIANT_SET_ID])
    shards = shards_for_references("17:41196311:41277499")
    variants = (
        v
        for s in shards
        for v in source.stream_variants(DEFAULT_VARIANT_SET_ID, s)
    )
    calls = calls_stream([variants], index.indexes)

    client = PcaBridgeClient(port=args.port)
    coords, eigvals = client.compute(calls, index.size, args.num_pc)
    client.close()

    names = index.name_of_index()
    for name, row in sorted(zip(names, coords.tolist())):
        print(name + "\t" + "\t".join(f"{c:.6f}" for c in row))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
