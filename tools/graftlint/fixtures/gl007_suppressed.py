"""Golden pragma-suppressed case for GL007 lock-discipline."""

import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def _drain_locked(self):
        self._items.clear()

    def drain(self):
        with self._lock:
            self._drain_locked()

    def single_threaded_shutdown(self):
        # Sound only because shutdown joins every worker first:
        self._drain_locked()  # graftlint: disable=lock-discipline
