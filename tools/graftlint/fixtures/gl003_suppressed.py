"""Golden pragma-suppressed case for GL003 span-contract: a session
root span whose open and close straddle a lifecycle boundary."""


class Session:
    def __enter__(self):
        # Mirrors the surrounding object's lifecycle on purpose:
        self._root = self.tracer.span("run")  # graftlint: disable=span-contract
        self._root.__enter__()
        return self

    def __exit__(self, *exc):
        self._root.__exit__(*exc)
