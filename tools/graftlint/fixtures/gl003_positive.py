"""Golden positive for GL003 span-contract: bare (non-context-manager)
span opens."""

from spark_examples_tpu import obs
from spark_examples_tpu.obs.tracer import get_tracer


def leaky_stage(tracer):
    s = tracer.span("stage")  # bare open: leaks on any exception path
    do_work()
    s.__exit__(None, None, None)


def leaky_ambient():
    handle = obs.span("ambient_stage")  # bare open again
    return handle


def do_work():
    pass
