"""Readers that treat every optional key as optional."""


def fold(path, replay_events):
    jobs = {}
    for e in replay_events(path):
        jobs[e["id"]] = e.get("trace")
    return jobs
