"""Fixture key registry (journal_schema stand-in)."""

JOURNAL_EVENT_KINDS = ("submit", "done")
JOURNAL_REQUIRED_KEYS = {"e", "id"}
JOURNAL_OPTIONAL_KEYS = {"trace"}
JOURNAL_KEYS = JOURNAL_REQUIRED_KEYS | JOURNAL_OPTIONAL_KEYS
JOB_RECORD_KEYS = {"id", "state", "error"}
