"""Writers that cover the registry exactly — no drift either way."""


def append_submit(journal, job_id, trace_id):
    event = {"e": "submit", "id": job_id, "trace": trace_id}
    journal.append(event)


def append_done(journal, job_id):
    journal.append({"e": "done", "id": job_id})


def record_of(job):
    rec = {"id": job.id, "state": job.state}
    if job.error is not None:
        rec["error"] = job.error
    return rec
