"""Golden negative for GL002 dtype-discipline: the exact-dtype idiom."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def kernel_exact(g, x):
    prod = jnp.einsum(
        "nv,mv->nm",
        x.astype(jnp.int8),
        x.astype(jnp.int8),
        preferred_element_type=jnp.int32,
    )
    return g + prod.astype(g.dtype)


def densify_exact(idx, n):
    x = np.zeros((n, 8), dtype=np.int8)
    x[idx, 0] = 1
    return x
