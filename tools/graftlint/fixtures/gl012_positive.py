"""Golden positive for GL012 retrace-discipline: raw per-window
geometry reaching executable-keyed arguments — every distinct value
mints a fresh XLA executable."""

from functools import lru_cache, partial

import jax


@partial(jax.jit, static_argnames=("width",))
def _panel_jit(x, width):
    return x[:, :width]


@lru_cache(maxsize=8)
def _tile_kernels(n_padded, tile_rows, path):
    return (n_padded, tile_rows, path)


def per_window_static(x, windows):
    out = []
    for idx, lens in windows:
        # Raw per-window variant count as a static arg: one compile
        # per distinct window size.
        out.append(_panel_jit(x, int(lens.size)))
    return out


def raw_factory_geometry(windows):
    kernels = []
    for idx, lens in windows:
        # Executable-cache factory keyed on unrounded stream geometry.
        kernels.append(_tile_kernels(int(lens.size), 4, "scan"))
    return kernels


def raw_carrier_rows(idx, windows, n_padded):
    mats = []
    for window_idx, lens in windows:
        # Shape-bearing helper fed unbucketed rows: the scatter
        # executable re-traces per window.
        mats.append(
            padded_carrier_matrix(
                window_idx, lens, sentinel=n_padded, n_rows=lens.size
            )
        )
    return mats


def padded_carrier_matrix(window_idx, lens, sentinel, n_rows=None):
    return (window_idx, lens, sentinel, n_rows)
