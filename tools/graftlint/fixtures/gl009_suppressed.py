"""Golden pragma-suppressed case for GL009 guarded-fields: the
intentional lock-free fast-path read, documented and counted."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def peek_relaxed(self):
        # Monotonic progress gauge: a stale read is acceptable, the
        # GIL makes the single int load atomic.
        return self._n  # graftlint: disable=guarded-fields
