"""Golden negative for GL007 lock-discipline: every shape the real
tree uses — with-blocks, sibling *_locked calls, the bounded
acquire/try/finally-release journal-flush idiom, branches that
re-join with the lock held on all paths."""

import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def _drain_locked(self):
        self._items.clear()

    def _rebalance_locked(self):
        self._drain_locked()  # sibling: caller's lock covers both

    def guarded(self):
        with self._lock:
            self._drain_locked()

    def guarded_in_branch(self, flag):
        with self._lock:
            if flag:
                self._drain_locked()
            else:
                self._rebalance_locked()

    def bounded_flush(self):
        # The serving/jobs.py journal-flush shape: bounded acquire,
        # release on every path via finally.
        if not self._lock.acquire(timeout=2.0):
            return
        try:
            self._drain_locked()
        finally:
            self._lock.release()

    def loop_guarded(self, n):
        for _ in range(n):
            with self._lock:
                self._drain_locked()
