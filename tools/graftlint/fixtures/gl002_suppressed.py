"""Golden pragma-suppressed case for GL002 dtype-discipline."""

import numpy as np


def host_f64_eig_input(g):
    # The --precise host eigendecomposition legitimately runs f64 —
    # outside the accumulation, declared as visible debt here:
    return np.asarray(g, dtype=np.float64)  # graftlint: disable=dtype-discipline
