"""Golden positive for GL008 deadlock-order: two code paths acquire
the same pair of locks in opposite orders — the textbook ABBA
deadlock."""

import threading

_ingest_lock = threading.Lock()
_journal_lock = threading.Lock()


def flush_then_ingest():
    with _journal_lock:
        with _ingest_lock:  # journal → ingest
            pass


def ingest_then_flush():
    with _ingest_lock:
        with _journal_lock:  # ingest → journal: the cycle
            pass
