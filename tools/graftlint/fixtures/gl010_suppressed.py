"""Golden pragma-suppressed case for GL010 collective-congruence."""

import jax


def single_process_only(x, flag_from_local_probe):
    # Sound only because this path is gated to process_count() == 1
    # by the caller; the pragma records the debt.
    if flag_from_local_probe and jax.process_index() == 0:
        x = jax.lax.psum(x, "data")  # graftlint: disable=collective-congruence
    return x
