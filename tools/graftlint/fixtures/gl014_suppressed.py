"""Golden pragma-suppressed case for GL014 fencing-discipline."""

JOB_PREFIX = "jobs/"


class LeaseManager:
    def __init__(self, store):
        self.store = store

    def bootstrap(self, job_id, data):
        # Single-replica bootstrap runs before any peer exists, so the
        # fence CAS has no contender to reject yet:
        self.store.put(JOB_PREFIX + job_id, data)  # graftlint: disable=fencing-discipline
