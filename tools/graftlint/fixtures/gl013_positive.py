"""Golden positive case for GL013 atomic-commit."""

import json
import os


def persist_doc(path, doc):
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    # No fsync and no torn-write seam before the publish: a crash can
    # surface a torn file under the committed name.
    os.replace(tmp, path)


def persist_blob(path, data):
    # No rename and no blessed commit helper: non-atomic by construction.
    with open(path, "wb") as f:
        f.write(data)
