"""Golden positive case for GL014 fencing-discipline."""

import threading

JOB_PREFIX = "jobs/"


class LeaseManager:
    def __init__(self, store, peers):
        self.store = store
        self._peers = peers
        self._lease = None
        self._lock = threading.Lock()

    def clobber(self, job_id, data):
        # Raw put into the fenced namespace bypasses the fence CAS.
        self.store.put(JOB_PREFIX + job_id, data)

    def stale_token(self, key, data):
        # Attribute lease: the heartbeat thread may have replaced it.
        self.store.put_fenced(key, data, self._lease)

    def maybe_fresh(self, key, data, flag):
        if flag:
            lease = self._peers.lease()
        # On the flag=False path the fence-token read never happened.
        self.store.put_fenced(key, data, lease)

    def io_under_lock(self, key):
        with self._lock:
            # Store I/O while the lease lock is held stalls heartbeats.
            return self.store.get(key)
