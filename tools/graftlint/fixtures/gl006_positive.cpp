// Golden positive for GL006 native-gil: CPython touches in a core that
// runs with the GIL released under ctypes.
#include <Python.h>
#include <cstdint>

extern "C" int64_t count_calls(const int64_t* idx, int64_t n) {
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject* list = PyList_New(0);
    Py_DECREF(list);
    PyGILState_Release(st);
    return n;
}
