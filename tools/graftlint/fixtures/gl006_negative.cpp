// Golden negative for GL006 native-gil: pure C++, raw pointers in and
// out — the contract the real core holds. Mentions of Py_Anything in
// comments or strings must not trip the rule:
// e.g. "never call PyGILState_Ensure here".
#include <cstdint>
#include <cstring>

static const char* kDoc = "pure C++: no PyObject anywhere";

extern "C" int64_t scatter_bits(
    const int64_t* idx, int64_t n, uint8_t* out, int64_t stride) {
    for (int64_t i = 0; i < n; ++i) {
        out[idx[i] * stride] |= 1;
    }
    return kDoc ? 0 : 1;
}
