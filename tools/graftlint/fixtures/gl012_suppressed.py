"""Golden pragma-suppressed case for GL012 retrace-discipline."""

from functools import partial

import jax


@partial(jax.jit, static_argnames=("width",))
def _panel_jit(x, width):
    return x[:, :width]


def one_shot_probe(x, windows):
    # Sound only because this probe runs ONCE per process at startup;
    # the pragma records the debt.
    idx, lens = next(iter(windows))
    return _panel_jit(x, int(lens.size))  # graftlint: disable=retrace-discipline
