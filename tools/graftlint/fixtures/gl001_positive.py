"""Golden positive for GL001 jit-purity: every classic host-sync and
trace-time side effect inside a jitted body."""

from functools import partial

import jax
import numpy as np

from spark_examples_tpu import obs


@partial(jax.jit, static_argnames=("k",))
def bad_kernel(x, k):
    host = jax.device_get(x)  # host sync
    np.asarray(x)  # host materialization
    v = float(x)  # implicit device_get
    print(v)  # trace-time-only side effect
    with obs.span("bad_span"):  # trace-time-only telemetry
        y = x * k
    y.block_until_ready()  # host sync
    return y


def fine_host_helper(x):
    # Outside any jit: all of this is legal host code.
    arr = np.asarray(x)
    print(float(arr[0]))
    return arr


inline_bad = jax.jit(lambda x: float(x))


def _named_body(x):
    return np.asarray(x)  # traced via the jax.jit(f) call form below


named_bad = jax.jit(_named_body)
