"""Golden negative for GL003 span-contract: context-managed spans."""

from spark_examples_tpu import obs
from spark_examples_tpu.obs.tracer import get_tracer


def timed_stage(tracer):
    with tracer.span("stage", shard="s1"):
        do_work()


def timed_ambient():
    with obs.span("ambient_stage"):
        with get_tracer().span("nested"):
            do_work()


def do_work():
    pass
