"""Golden positive for GL010 collective-congruence: lockstep
collectives governed by host-local state — each shape deadlocks or
strands peers on a real pod."""

import jax
import numpy as np
from jax.experimental import multihost_utils


def drained_stream_skips_header(windows, exchange, step):
    gang = next(windows, None)  # host-local stream data
    if gang is None:
        return None  # one process exits here...
    # ...while peers with live streams block in the gather forever.
    exchange.post_header(step, np.asarray(gang, np.int64))
    return exchange.gather_headers(step, 1)


def collective_in_handler(x):
    try:
        x = x * 2
    except ValueError:
        # Peers that did not raise never reach this psum.
        x = jax.lax.psum(x, "data")
    return x


def per_window_allgather(stream):
    out = []
    for window in stream:  # per-process stream: trip counts diverge
        out.append(
            multihost_utils.process_allgather(np.asarray(window))
        )
    return out


def collective_under_traced_branch(x, flag):
    # The traced predicate selects the branch per DEVICE.
    return jax.lax.cond(
        flag,
        lambda v: jax.lax.psum(v, "data"),
        lambda v: v,
        x,
    )


def one_sided_rank_branch(x):
    if jax.process_index() == 0:  # host-local by definition
        x = jax.lax.all_gather(x, "data")
    return x
