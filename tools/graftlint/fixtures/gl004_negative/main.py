"""GL004 negative CLI module: its one extra flag is read off args."""

import argparse


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--api-url", default=None)
    args = p.parse_args()
    return args.api_url
