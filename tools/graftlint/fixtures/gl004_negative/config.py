"""GL004 negative: every flag binds a field and is documented."""

import argparse
from dataclasses import dataclass


@dataclass
class GenomicsConfig:
    block_size: int = 8192


def add_genomics_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--block-size", type=int, default=8192)
