"""Golden positive for GL011 donation-aliasing: live host aliases of
donated device buffers — every shape reads recycled memory."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, donate_argnums=(0,))
def _accum(g, xb):
    return g + xb @ xb.T


class CachingServer:
    def __init__(self):
        self._g = jnp.zeros((4, 4))
        self._snapshot = None

    def step(self, xb):
        # Donating a stored attribute: every other method still holds
        # a reference to the DEAD buffer.
        out = _accum(self._g, xb)
        return out

    def read(self):
        return self._g


def donated_view(g, xb):
    # Donating a subscript view: the base stays live in the caller.
    return _accum(g[:4, :4], xb)


def snapshot_dies(g, xb):
    snapshot = np.asarray(g)  # zero-copy view of the device buffer
    g = _accum(g, xb)
    return snapshot  # reads recycled memory after the donation


def use_after_donation(g, xb):
    g2 = _accum(g, xb)
    return g2 + g  # `g` was donated; this read is a dead-buffer read


def stored_view_then_donated(cache, g, xb):
    cache.entry = np.asarray(g)  # stored zero-copy view...
    g2 = _accum(g, xb)  # ...dies when g is donated here
    return g2
