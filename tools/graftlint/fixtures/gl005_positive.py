"""Golden positive for GL005 resilience-routing: bare retry sleeps and
raw transport I/O outside any fault-seam-marked attempt function."""

import time
from urllib.request import urlopen


def fetch_with_bare_retry(url):
    for attempt in range(3):
        try:
            with urlopen(url) as resp:  # raw transport, no seam
                return resp.read()
        except OSError:
            time.sleep(2**attempt)  # bare backoff, no policy
    raise IOError(url)


def raw_keepalive_roundtrip(conn, target):
    conn.request("GET", target)  # raw transport, no seam
    return conn.getresponse()  # and again
