"""Golden negative case for GL014 fencing-discipline."""

import threading

JOB_PREFIX = "jobs/"


class LeaseManager:
    def __init__(self, store, peers):
        self.store = store
        self._peers = peers
        self._snapshot = None
        self._lock = threading.Lock()

    def publish(self, job_id, data):
        # The fence-token read dominates the write on every path.
        lease = self._peers.lease()
        self.store.put_fenced(JOB_PREFIX + job_id, data, lease)

    def publish_inline(self, key, data):
        self.store.put_fenced(key, data, self._peers.lease())

    def scratch(self, data):
        # Not a fenced prefix: raw put is fine outside jobs/, adopted/.
        self.store.put("scratch/probe", data)

    def snapshot(self):
        # The lock guards in-memory snapshot state only.
        with self._lock:
            return self._snapshot

    def read_outside_lock(self, key):
        with self._lock:
            pending = self._snapshot
        if pending is not None:
            return self.store.get(key)
        return None
