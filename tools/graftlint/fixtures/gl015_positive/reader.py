"""Readers that subscript keys old journals do not carry."""


def fold(path, replay_events):
    jobs = {}
    for e in replay_events(path):
        jobs[e["id"]] = e["trace"]  # optional key, unguarded subscript
        kind = e["unknown"]  # unregistered key
        del kind
    return jobs
