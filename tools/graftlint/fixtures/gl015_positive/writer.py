"""Writers that drift from the registry (every direction)."""


def append_submit(journal, job_id):
    event = {"e": "submit", "id": job_id, "shard": 3}  # unregistered key
    journal.append(event)


def append_retry(journal, job_id):
    journal.append({"e": "retry", "id": job_id})  # unregistered kind


def record_of(job):
    rec = {"id": job.id, "state": job.state}
    rec["attempts"] = job.attempts  # unregistered job-record key
    return rec
