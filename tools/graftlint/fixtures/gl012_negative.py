"""Golden negative for GL012 retrace-discipline: geometry routed
through the registered bucket helpers, parameters, and constants —
executable counts stay O(log N)."""

from functools import lru_cache, partial

import jax

_DEF_WIDTH = 64


@partial(jax.jit, static_argnames=("width",))
def _panel_jit(x, width):
    return x[:, :width]


@lru_cache(maxsize=8)
def _tile_kernels(n_padded, tile_rows, path):
    return (n_padded, tile_rows, path)


def bucketed_windows(x, windows, block_variants):
    out = []
    for idx, lens in windows:
        # The pow2-panel discipline: per-window geometry rounds through
        # the registered bucket helper.
        out.append(_panel_jit(x, dense_panel_width(int(lens.size), block_variants)))
    return out


def param_geometry(x, n_samples, mesh):
    # Parameters and mesh config are the caller's contract; constants
    # are compile-time geometry.
    n_padded = round_up_multiple(n_samples, mesh.shape["data"])
    tile_rows = n_padded // mesh.shape["data"]
    _tile_kernels(n_padded, tile_rows, "scan")
    return _panel_jit(x, _DEF_WIDTH)


def bucketed_carrier(window_idx, lens, n_padded):
    return padded_carrier_matrix(
        window_idx,
        lens,
        sentinel=n_padded,
        n_rows=_pad_rows_for_scan(int(lens.size)),
        k_bucket=_carrier_bucket(int(lens.max())),
    )


def dense_panel_width(rows, block_variants):
    return max(rows, block_variants)


def round_up_multiple(n, m):
    return ((n + m - 1) // m) * m


def _pad_rows_for_scan(rows):
    return max(rows, 256)


def _carrier_bucket(k):
    return max(k, 8)


def padded_carrier_matrix(
    window_idx, lens, sentinel, n_rows=None, k_bucket=None
):
    return (window_idx, lens, sentinel, n_rows, k_bucket)
