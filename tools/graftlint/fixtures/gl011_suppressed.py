"""Golden pragma-suppressed case for GL011 donation-aliasing."""

from functools import partial

import jax
import numpy as np


@partial(jax.jit, donate_argnums=(0,))
def _accum(g, xb):
    return g + xb @ xb.T


def debug_probe(g, xb):
    # Sound only on the CPU interpret path where the harness pins the
    # buffer; the pragma records the debt.
    snap = np.asarray(g)
    g = _accum(g, xb)  # graftlint: disable=donation-aliasing
    print(snap.sum())  # graftlint: disable=donation-aliasing
    return g
