"""Golden negative case for GL013 atomic-commit."""

import json
import os

from myproj.genomics.mirror import _commit_tmp
from myproj.resilience import faults


def persist_doc(path, doc):
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
        faults.inject_write("doc.write", tmp)
    os.replace(tmp, path)


def persist_blob(path, data):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    _commit_tmp(tmp, path)


def append_event(path, line):
    # Append-mode journals are torn-tail-tolerant by design — exempt.
    with open(path, "a", encoding="utf-8") as f:
        f.write(line)
