// Golden pragma-suppressed case for GL006 native-gil.
#include <cstdint>

extern "C" int64_t with_declared_debt(int64_t n) {
    // A hypothetical GIL-reacquiring region, declared as visible debt:
    PyGILState_Ensure();  // graftlint: disable=native-gil
    return n;
}
