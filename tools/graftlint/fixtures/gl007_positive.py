"""Golden positive for GL007 lock-discipline: *_locked calls at
unprotected program points and unpaired manual acquire/release."""

import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def _drain_locked(self):
        self._items.clear()

    def unguarded_call(self):
        self._drain_locked()  # no lock held here

    def branch_leak(self, flag):
        if flag:
            self._lock.acquire()  # also: acquire with no finally-release
        self._drain_locked()  # held on ONE branch only: not proven

    def released_too_early(self):
        with self._lock:
            pass
        self._drain_locked()  # the with block already released

    def manual_no_finally(self):
        self._lock.acquire()  # no release in a finally
        self._items.append(1)
        self._lock.release()  # and the release is exception-unsafe


class Other:
    def __init__(self, worker):
        self._worker = worker
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            self._worker._drain_locked()  # cross-object *_locked call
