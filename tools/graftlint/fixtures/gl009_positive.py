"""Golden positive for GL009 guarded-fields: a field written under the
class lock, then read and mutated lock-free elsewhere."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._pending = []

    def bump(self):
        with self._lock:
            self._n += 1

    def enqueue(self, item):
        with self._lock:
            self._pending.append(item)

    def peek(self):
        return self._n  # unguarded read of a guarded field

    def drain_fast(self):
        out = list(self._pending)  # unguarded read
        self._pending.clear()  # unguarded mutation
        return out
