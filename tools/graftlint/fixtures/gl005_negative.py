"""Golden negative for GL005 resilience-routing: the policy-routed
attempt-function idiom the real transports use."""

import time
from urllib.request import urlopen

from spark_examples_tpu.resilience import call_with_retry, classify_http, faults


def fetch_routed(url, policy):
    def attempt():
        faults.inject("transport.http.request", key=url)
        with urlopen(url) as resp:
            return resp.read()

    return call_with_retry(
        attempt, policy, classify_http, transport="http", method="GET"
    )


def policy_paced_wait(policy, failures, budget):
    time.sleep(min(policy.backoff_delay(failures), budget.remaining()))
