"""Golden pragma-suppressed case for GL013 atomic-commit."""


def write_boot_marker(path):
    # One-shot boot marker: rewritten from scratch on every start and
    # never trusted across a crash — atomicity buys nothing here.
    with open(path, "w") as f:  # graftlint: disable=atomic-commit
        f.write("ready\n")
