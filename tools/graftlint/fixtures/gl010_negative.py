"""Golden negative for GL010 collective-congruence: the sanctioned
protocol shapes — unconditional collectives, predicates derived from
prior agreement steps, failure codes riding the next exchange."""

import numpy as np
from jax.experimental import multihost_utils


def synced_step(windows, exchange, step, world):
    """The all-raise-together protocol shape: host-local failures are
    encoded into the header; every later predicate reads gathered
    (agreed) data."""
    exc = None
    try:
        gang = next(windows, None)
    except Exception as e:  # noqa: BLE001 — synced below
        exc, gang = e, None
    if exc is not None:
        code = -2
    elif gang is None:
        code = -1
    else:
        code = 0
    exchange.post_header(step, np.array([code], np.int64))
    peers = exchange.gather_headers(step, 1)
    failed = [i for i, row in enumerate(peers) if int(row[0]) == -2]
    if failed:
        # Agreed predicate: every process raises together.
        raise RuntimeError(f"failed on {failed}") from exc
    live = peers[peers[:, 0] >= 0]
    if live.size == 0:
        return None  # agreed: every stream drained everywhere
    exchange.post_confirm(step, True)
    return exchange.gather_confirms(step)


def config_gated_sync(blocks, mesh, spans_processes):
    """Collectives under parameter (config-contract) predicates are
    congruent: every process calls with the same arguments."""
    first = next(iter(blocks), None)
    local = -1 if first is None else int(np.asarray(first).shape[1])
    if spans_processes:
        widths = np.asarray(
            multihost_utils.process_allgather(
                np.array([local], np.int64)
            )
        ).ravel()
        live = sorted({int(w) for w in widths if w >= 0})
        if len(live) > 1:
            raise ValueError(f"widths diverged: {live}")
    return first


def bounded_rounds(g, total_rounds):
    for _ in range(total_rounds):  # agreed bound: congruent iteration
        g = multihost_utils.process_allgather(g)
    return g
