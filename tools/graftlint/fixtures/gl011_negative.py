"""Golden negative for GL011 donation-aliasing: the blessed shapes —
rebinding through the donating call, explicit copies before any alias
escapes, views of the RESULT."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, donate_argnums=(0,))
def _accum(g, xb):
    return g + xb @ xb.T


def accumulate(blocks, n):
    """The accumulator-loop idiom: rebind through the call — the next
    iteration (and the final read) sees the FRESH buffer."""
    g = jnp.zeros((n, n), dtype=jnp.float32)
    for xb in blocks:
        g = _accum(g, xb)
    return np.asarray(g)  # view of the final result: never donated again


def copy_before_store(cache, g, xb):
    # The DeltaEntry discipline: an explicit self-owned copy, then the
    # donating dispatch — nothing aliases the donated buffer.
    cache.entry = np.array(g, copy=True)
    g = _accum(g, xb)
    return g


def forwarding_wrapper(g, xb):
    """Public donating entry point: the parameter forwards into the
    donated position and is never read again here — its own call sites
    carry the contract."""
    return _accum(g, xb)
