"""Registry-complete writers plus one deliberate, pragma'd escape."""


def append_submit(journal, job_id, trace_id):
    journal.append({"e": "submit", "id": job_id, "trace": trace_id})


def append_done(journal, job_id):
    journal.append({"e": "done", "id": job_id})


def append_debug(journal, job_id):
    # Local debug-only event; a bench harness strips it before replay.
    journal.append({"e": "done", "id": job_id, "scratch": 1})  # graftlint: disable=journal-compat


def record_of(job):
    rec = {"id": job.id, "state": job.state}
    rec["error"] = job.error
    return rec
