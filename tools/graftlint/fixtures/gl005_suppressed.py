"""Golden pragma-suppressed case for GL005 resilience-routing."""

import time


def fixture_pacing_only(delay):
    # Deterministic test-fixture pacing, not a retry backoff:
    time.sleep(delay)  # graftlint: disable=resilience-routing
