"""Golden pragma-suppressed case for GL001 jit-purity."""

import jax


@jax.jit
def debug_kernel(x):
    # A knowingly-impure debug hook, declared as visible debt:
    v = float(x)  # graftlint: disable=jit-purity
    return x + v
