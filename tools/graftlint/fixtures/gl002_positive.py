"""Golden positive for GL002 dtype-discipline: float64 leaks into the
integer-exact accumulation path."""

import jax
import jax.numpy as jnp
import numpy as np


def accumulate_wrong(g, x):
    xf = x.astype(np.float64)  # f64 reference
    return g + xf @ xf.T


def densify_wrong(idx, n):
    x = np.zeros((n, 8), dtype=float)  # builtin float IS float64
    x[idx, 0] = 1
    return x.astype(float)  # and again on the way out


@jax.jit
def kernel_weak_promotion(g, x):
    return g + (x * 0.5)  # float literal weak-type-promotes g
