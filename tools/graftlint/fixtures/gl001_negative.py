"""Golden negative for GL001 jit-purity: pure traced bodies, host work
kept outside the trace."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_examples_tpu import obs


@partial(jax.jit, static_argnames=("k",))
def pure_kernel(x, k):
    y = jnp.einsum("nv,mv->nm", x, x, preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * k


def host_driver(blocks):
    with obs.span("drive"):
        for b in blocks:
            arr = np.asarray(b)
            yield pure_kernel(jnp.asarray(arr), 2)
