"""GL004 positive CLI module: defines a flag it never reads."""

import argparse


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--unused-cli-flag", default=None)
    args = p.parse_args()
    return args
