"""GL004 positive: flags, fields, and docs out of sync in every way."""

import argparse
from dataclasses import dataclass


@dataclass
class GenomicsConfig:
    block_size: int = 8192
    orphan_field: str = "x"  # no flag can set this


def add_genomics_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--block-size", type=int, default=8192)
    p.add_argument("--dead-flag", default=None)  # no field, never read
