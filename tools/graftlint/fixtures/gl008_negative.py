"""Golden negative for GL008 deadlock-order: consistent global order
(journal before ingest, everywhere), including an edge derived through
a typed-attribute call — nesting is fine as long as it is one-way."""

import threading

_ingest_lock = threading.Lock()
_journal_lock = threading.Lock()


class Journal:
    def __init__(self):
        self._lock = threading.Lock()

    def append(self, event):
        with self._lock:
            return event


class Tier:
    def __init__(self):
        self._lock = threading.Lock()
        self._journal = Journal()

    def submit(self, event):
        with self._lock:
            # Tier._lock → Journal._lock: an edge, not a cycle.
            return self._journal.append(event)


def flush_then_ingest():
    with _journal_lock:
        with _ingest_lock:
            pass


def flush_then_ingest_again():
    with _journal_lock:
        with _ingest_lock:
            pass
