"""Golden pragma-suppressed case for GL008 deadlock-order: both sides
of the cycle carry a pragma (e.g. a transition window where one side is
provably never reached concurrently)."""

import threading

_ingest_lock = threading.Lock()
_journal_lock = threading.Lock()


def flush_then_ingest():
    with _journal_lock:
        # graftlint: disable=deadlock-order
        with _ingest_lock:
            pass


def ingest_then_flush():
    with _ingest_lock:
        # graftlint: disable=deadlock-order
        with _journal_lock:
            pass
