"""Golden negative for GL009 guarded-fields: guarded everywhere it
must be — construction writes exempt, *_locked methods inherit the
caller's lock, never-guarded fields stay unconstrained."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._pending = []
        self._label = "counter"  # never lock-guarded: unconstrained

    def bump(self):
        with self._lock:
            self._n += 1

    def _drain_locked(self):
        out = list(self._pending)
        self._pending.clear()
        return out

    def drain(self):
        with self._lock:
            return self._drain_locked()

    def enqueue(self, item):
        with self._lock:
            self._pending.append(item)

    def peek(self):
        with self._lock:
            return self._n

    def rename(self, label):
        self._label = label  # fine: _label has no guarded writes
