"""GL007 lock-discipline: the ``*_locked`` convention, proven by dataflow.

The serving tier (PR 6) adopted the convention ``serving/queue.py``
established: a method named ``*_locked`` asserts nothing and acquires
nothing — it REQUIRES its owning lock to already be held by the caller.
The convention is only as good as every call site, and a miss is a
silent data race that no tier-1 test deterministically exercises.
This rule makes it a review-time proof:

1. **held-at-call-site** — a call to ``self.<m>_locked(...)`` may only
   appear at program points where the must-held lock set (computed by
   the reaching-locks dataflow over the function's CFG, through
   ``with`` blocks, ``try/finally``, branches and loops) contains at
   least one of the class's locks. A ``*_locked`` method's own body is
   seeded with the class locks — the convention IS its precondition —
   so sibling ``_locked`` → ``_locked`` calls verify.
2. **cross-object privacy** — calling *another* object's ``*_locked``
   method (``self._queue._push_locked(...)``) is flagged outright: no
   intraprocedural analysis can prove a foreign lock is held, and the
   underscore says it was never API.
3. **manual acquire/release pairing** — an explicit ``X.acquire(...)``
   must have a matching ``X.release()`` inside a ``finally`` block of
   the same function (the only shape that releases on *every* path,
   exceptions included — the discipline ``serving/jobs.py``'s bounded
   journal-flush acquire models); a manual ``release()`` outside any
   ``finally`` is flagged for the same reason.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional, Set, Tuple

from tools.graftlint.astutil import dotted_name
from tools.graftlint.dataflow import (
    Resolver,
    build_cfg,
    class_lock_keys,
    held_at_nodes,
    make_resolver,
    module_lock_keys,
    node_scan_roots,
    scan_calls,
    walk_skip_nested,
)
from tools.graftlint.engine import Finding, Project

NAME = "lock-discipline"
CODE = "GL007"

DEFAULT_PATHS = (
    "spark_examples_tpu/serving",
    "spark_examples_tpu/arrays",
    "spark_examples_tpu/utils",
    "spark_examples_tpu/parallel",
)


def _functions_with_context(
    tree: ast.AST,
) -> Iterable[Tuple[Optional[ast.ClassDef], ast.AST]]:
    """(enclosing class | None, function) for module-level functions
    and direct class methods. Functions nested inside functions run on
    the same stack as their builder — analyzed opaquely as part of it."""
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in ast.iter_child_nodes(node):
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield node, sub


def _finally_release_keys(fn: ast.AST, resolve: Resolver) -> Set[str]:
    """Lock keys released inside any ``finally`` body of ``fn``."""
    keys: Set[str] = set()
    for node in walk_skip_nested(fn, skip_self=True):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        for stmt in node.finalbody:
            for call in scan_calls(stmt):
                if (
                    isinstance(call.func, ast.Attribute)
                    and call.func.attr == "release"
                ):
                    key = resolve(call.func.value)
                    if key is not None:
                        keys.add(key)
    return keys


def _finally_node_ids(fn: ast.AST) -> Set[int]:
    ids: Set[int] = set()
    for node in walk_skip_nested(fn, skip_self=True):
        if isinstance(node, ast.Try) and node.finalbody:
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    ids.add(id(sub))
    return ids


class LockDisciplineRule:
    name = NAME
    code = CODE
    summary = (
        "*_locked methods are only called where their owning lock is "
        "provably held; manual acquire() pairs with release() in a "
        "finally"
    )
    project_wide = False

    def check(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for top in project.rule_paths(NAME, DEFAULT_PATHS):
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                stem = os.path.splitext(os.path.basename(rel))[0]
                mod_locks = module_lock_keys(ctx.tree, stem)
                for cls, fn in _functions_with_context(ctx.tree):
                    findings.extend(
                        self._check_function(
                            rel, stem, cls, fn, mod_locks
                        )
                    )
        return findings

    def _check_function(
        self,
        rel: str,
        stem: str,
        cls: Optional[ast.ClassDef],
        fn: ast.AST,
        mod_locks: frozenset,
    ) -> List[Finding]:
        findings: List[Finding] = []
        cls_name = cls.name if cls is not None else None
        resolve = make_resolver(cls_name, stem)
        own_locks = (
            class_lock_keys(cls, stem) if cls is not None else mod_locks
        )
        seed = (
            own_locks
            if fn.name.endswith("_locked") and own_locks
            else frozenset()
        )
        cfg = build_cfg(fn, resolve)
        states = held_at_nodes(cfg, resolve, seed=seed, must=True)

        for node in cfg.nodes:
            held = states.get(node)
            if held is None:
                continue  # unreachable
            for root in node_scan_roots(node):
                for call in scan_calls(root):
                    findings.extend(
                        self._check_locked_call(
                            rel, call, own_locks, mod_locks, held
                        )
                    )

        # Manual acquire/release pairing (lexical over the function:
        # the only exception-safe release shape is a finally).
        fin_keys = _finally_release_keys(fn, resolve)
        fin_ids = _finally_node_ids(fn)
        for sub in walk_skip_nested(fn, skip_self=True):
            if not isinstance(sub, ast.Call) or not isinstance(
                sub.func, ast.Attribute
            ):
                continue
            key = (
                resolve(sub.func.value)
                if sub.func.attr in ("acquire", "release")
                else None
            )
            if key is None:
                continue
            if sub.func.attr == "acquire" and key not in fin_keys:
                findings.append(
                    Finding(
                        NAME,
                        CODE,
                        rel,
                        sub.lineno,
                        f"manual {key}.acquire() without a matching "
                        "release() in a finally block of this function "
                        "— an exception between acquire and release "
                        "leaks the lock forever; use `with` or the "
                        "acquire/try/finally-release shape",
                    )
                )
            elif sub.func.attr == "release" and id(sub) not in fin_ids:
                findings.append(
                    Finding(
                        NAME,
                        CODE,
                        rel,
                        sub.lineno,
                        f"manual {key}.release() outside a finally "
                        "block — any exception on the path to it "
                        "skips the release and leaks the lock",
                    )
                )
        return findings

    def _check_locked_call(
        self,
        rel: str,
        call: ast.Call,
        own_locks: frozenset,
        mod_locks: frozenset,
        held: frozenset,
    ) -> List[Finding]:
        func = call.func
        callee: Optional[str] = None
        required: frozenset = frozenset()
        if isinstance(func, ast.Attribute) and func.attr.endswith(
            "_locked"
        ):
            recv = dotted_name(func.value)
            if recv == "self":
                callee = f"self.{func.attr}"
                required = own_locks
            else:
                return [
                    Finding(
                        NAME,
                        CODE,
                        rel,
                        call.lineno,
                        f"call to another object's *_locked method "
                        f"(`{recv or '<expr>'}.{func.attr}`): its "
                        "owning lock cannot be proven held from here "
                        "— route through a public method that takes "
                        "the lock itself",
                    )
                ]
        elif isinstance(func, ast.Name) and func.id.endswith("_locked"):
            # A bare name resolves to a module-level *_locked function;
            # its contract is the module's lock(s), when it has any.
            callee = func.id
            required = mod_locks
        if callee is None or not required:
            return []
        if held & required:
            return []
        lock_list = ", ".join(sorted(required))
        return [
            Finding(
                NAME,
                CODE,
                rel,
                call.lineno,
                f"`{callee}(...)` called at a point where none of its "
                f"owning lock(s) ({lock_list}) is provably held on "
                "every path — take the lock (or call from a *_locked "
                "context)",
            )
        ]


RULE = LockDisciplineRule()
