"""GL002 dtype-discipline: integer-exact Gramian path stays float64-free.

The Gramian accumulation is *exact* arithmetic dressed as float matmul:
0/1 indicator blocks ride the int8 MXU and the int32 counts are cast
into an f32 accumulator, exact below 2^24 co-occurrences per pair
(ops/gramian.py module docstring; the same integer-exact discipline the
genotype-PCA kernels in Lange et al. arXiv:1808.03374 rely on). A
float64 literal or an implicit weak-type promotion in this path is never
a precision *upgrade* — on TPU f64 silently demotes or falls off the
MXU, and a Python float scalar leaking into a jitted body weak-type-
promotes the whole accumulator, changing the dtype the bit-identity
tests pin.

Flags, in the configured files (default: ops/gramian.py and
arrays/blocks.py):

- any ``float64`` reference (``np.float64``/``jnp.float64``/dtype
  strings) and ``astype(float)``/``dtype=float`` (Python ``float`` IS
  float64 as a dtype);
- bare float literals inside jit-traced bodies (weak-type promotion of
  the accumulator).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from tools.graftlint.astutil import jitted_functions
from tools.graftlint.engine import Finding, Project

NAME = "dtype-discipline"
CODE = "GL002"

DEFAULT_PATHS = (
    "spark_examples_tpu/ops/gramian.py",
    "spark_examples_tpu/ops/sparse.py",
    "spark_examples_tpu/arrays/blocks.py",
)


def _dtype_kwarg_is_builtin_float(call: ast.Call) -> bool:
    for kw in call.keywords:
        if (
            kw.arg == "dtype"
            and isinstance(kw.value, ast.Name)
            and kw.value.id == "float"
        ):
            return True
    return False


def _astype_float(call: ast.Call) -> bool:
    return (
        isinstance(call.func, ast.Attribute)
        and call.func.attr == "astype"
        and len(call.args) == 1
        and isinstance(call.args[0], ast.Name)
        and call.args[0].id == "float"
    )


class DtypeDisciplineRule:
    name = NAME
    code = CODE
    summary = (
        "no float64 literals / builtin-float dtypes / weak-type float "
        "promotion in the integer-exact Gramian accumulation path"
    )
    project_wide = False

    def check(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for top in project.rule_paths(NAME, DEFAULT_PATHS):
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                jit_nodes: Set[ast.AST] = set()
                for fn in jitted_functions(ctx.tree):
                    jit_nodes.update(ast.walk(fn))
                for node in ast.walk(ctx.tree):
                    if (
                        isinstance(node, ast.Attribute)
                        and node.attr == "float64"
                    ) or (
                        isinstance(node, ast.Name)
                        and node.id == "float64"
                    ):
                        findings.append(
                            Finding(
                                NAME,
                                CODE,
                                rel,
                                node.lineno,
                                "float64 in the integer-exact Gramian "
                                "path: counts are exact in int32/f32 "
                                "below 2^24; f64 is slower on the MXU "
                                "and changes the pinned accumulator "
                                "dtype",
                            )
                        )
                    elif isinstance(node, ast.Constant) and isinstance(
                        node.value, str
                    ):
                        if node.value == "float64" and node in jit_nodes:
                            findings.append(
                                Finding(
                                    NAME,
                                    CODE,
                                    rel,
                                    node.lineno,
                                    "'float64' dtype string inside a "
                                    "jit-traced Gramian body",
                                )
                            )
                    elif isinstance(node, ast.Call):
                        if _dtype_kwarg_is_builtin_float(
                            node
                        ) or _astype_float(node):
                            findings.append(
                                Finding(
                                    NAME,
                                    CODE,
                                    rel,
                                    node.lineno,
                                    "builtin `float` as a dtype is "
                                    "float64 — use an explicit exact "
                                    "dtype (int8/int32/float32)",
                                )
                            )
                    elif (
                        isinstance(node, ast.Constant)
                        and isinstance(node.value, float)
                        and node in jit_nodes
                    ):
                        findings.append(
                            Finding(
                                NAME,
                                CODE,
                                rel,
                                node.lineno,
                                f"float literal {node.value!r} inside a "
                                "jit-traced Gramian body weak-type-"
                                "promotes the exact integer "
                                "accumulation",
                            )
                        )
        return findings


RULE = DtypeDisciplineRule()
