"""GL005 resilience-routing: genomics transport I/O rides the policy engine.

PR 2-3 centralized every retry/backoff/deadline/breaker decision into
``spark_examples_tpu.resilience`` — and taught every transport seam to
carry a ``faults.inject("transport...")`` marker so the deterministic
fault plane can reach it. The contract rots one convenience call at a
time: a quick ``time.sleep(1)`` before a retry, a bare ``urlopen`` in a
new helper. Each bypasses classification (retryable vs served error),
the breaker, the deadline budget, the retry metrics, AND the fault
seams the chaos suite drives. Statically enforced instead:

- ``time.sleep`` in ``genomics/`` must compute its delay from the
  policy engine (``backoff_delay``/``remaining``/``retry_after`` in the
  argument expression) — anything else is a bare retry sleep;
- raw transport primitives (``urlopen``, connection ``.request`` /
  ``.getresponse``, ``socket.create_connection``) may only appear
  inside a function that carries a ``faults.inject("transport...")``
  seam — the marker every policy-routed attempt function in the tree
  already carries (service._one_attempt, oauth's attempt, the gRPC
  request seams).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from tools.graftlint.astutil import call_name, literal_str
from tools.graftlint.engine import Finding, Project

NAME = "resilience-routing"
CODE = "GL005"

DEFAULT_PATHS = ("spark_examples_tpu/genomics",)

# Identifiers that mark a sleep as policy-derived.
_POLICY_DELAY_MARKERS = ("backoff_delay", "remaining", "retry_after")


def _sleep_is_policy_routed(call: ast.Call) -> bool:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Attribute) and sub.attr in (
                _POLICY_DELAY_MARKERS
            ):
                return True
            if isinstance(sub, ast.Name) and sub.id in (
                _POLICY_DELAY_MARKERS
            ):
                return True
    return False


def _is_raw_transport_call(call: ast.Call) -> Optional[str]:
    cname = call_name(call) or ""
    last = cname.rsplit(".", 1)[-1]
    if last == "urlopen" or cname == "urlopen":
        return "urlopen"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr == "getresponse":
            return ".getresponse()"
        if attr == "request" and not (
            isinstance(call.func.value, ast.Name)
            and call.func.value.id == "self"
        ):
            return ".request()"
        if attr == "create_connection":
            return "socket.create_connection"
    return None


def _has_transport_seam(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        cname = call_name(node) or ""
        if cname.rsplit(".", 1)[-1] != "inject":
            continue
        site = literal_str(node.args[0]) if node.args else None
        if site is not None and site.startswith("transport."):
            return True
    return False


class ResilienceRoutingRule:
    name = NAME
    code = CODE
    summary = (
        "genomics/ transport calls route through the resilience policy "
        "engine: no bare sleeps, raw I/O only inside fault-seam-marked "
        "attempt functions"
    )
    project_wide = False

    def check(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for top in project.rule_paths(NAME, DEFAULT_PATHS):
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                # Map every node to its innermost enclosing functions.
                enclosing = {}
                for fn in ast.walk(ctx.tree):
                    if isinstance(
                        fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        for sub in ast.walk(fn):
                            enclosing.setdefault(id(sub), []).append(fn)
                for node in ast.walk(ctx.tree):
                    if not isinstance(node, ast.Call):
                        continue
                    cname = call_name(node) or ""
                    if cname.rsplit(".", 1)[-1] == "sleep":
                        if not _sleep_is_policy_routed(node):
                            findings.append(
                                Finding(
                                    NAME,
                                    CODE,
                                    rel,
                                    node.lineno,
                                    "bare sleep in genomics/: backoff "
                                    "must come from the resilience "
                                    "policy engine (RetryPolicy."
                                    "backoff_delay / deadline budget / "
                                    "Retry-After), which this delay "
                                    "expression does not reference",
                                )
                            )
                        continue
                    prim = _is_raw_transport_call(node)
                    if prim is None:
                        continue
                    fns = enclosing.get(id(node), [])
                    if not any(_has_transport_seam(fn) for fn in fns):
                        findings.append(
                            Finding(
                                NAME,
                                CODE,
                                rel,
                                node.lineno,
                                f"raw transport call {prim} outside a "
                                "fault-seam-marked attempt function: "
                                "wrap it in a function carrying "
                                "faults.inject('transport...') and "
                                "route it through call_with_retry so "
                                "classification, breaker, deadline, "
                                "and chaos seams all apply",
                            )
                        )
        return findings


RULE = ResilienceRoutingRule()
