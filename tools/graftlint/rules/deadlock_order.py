"""GL008 deadlock-order: derive the global lock-acquisition graph, reject cycles.

A deadlock needs two threads taking the same two locks in opposite
orders — a property of the WHOLE tree, invisible to any single diff.
This rule derives the global acquisition graph statically:

- **nodes** are canonical lock keys (``AnalysisJobTier._lock``,
  ``AdmissionQueue._cv``, ``watchdog._flush_lock``, ...);
- **edges** ``A → B`` exist where some program point provably *may*
  hold ``A`` while acquiring ``B`` — directly (nested ``with`` /
  manual acquire, via the may-held reaching-locks dataflow) or through
  a call whose callee acquires ``B``: calls onto ``self`` methods and
  onto attributes whose class is inferred from constructor assignments
  (``self._queue = AdmissionQueue(...)`` types ``self._queue``), with
  per-method lock summaries closed transitively over those same edges.

Any cycle in the graph is a finding at each participating acquisition
site. The acyclic graph itself is the machine-readable lock hierarchy:
``python -m tools.graftlint --lock-graph`` emits it as JSON, and
``docs/CONCURRENCY.md`` embeds that JSON verbatim — a drift test pins
doc to derivation, so the documented hierarchy can never silently rot.

The rule is ``project_wide``: a cycle between two files is never out of
scope just because the CLI was pointed at one of them.
"""

from __future__ import annotations

import ast
from typing import (
    Dict,
    FrozenSet,
    Iterable,
    Iterator,
    List,
    Set,
    Tuple,
)

from tools.graftlint.astutil import dotted_name
from tools.graftlint.classmodel import ScopeModel, scan_scope
from tools.graftlint.dataflow import (
    Resolver,
    build_cfg,
    held_at_nodes,
    make_resolver,
    manual_lock_ops,
    node_scan_roots,
    scan_calls,
)
from tools.graftlint.engine import Finding, Project

NAME = "deadlock-order"
CODE = "GL008"

DEFAULT_PATHS = (
    "spark_examples_tpu/serving",
    "spark_examples_tpu/arrays",
    "spark_examples_tpu/utils",
)

Edge = Tuple[str, str]


def _direct_locks(fn: ast.AST, resolve: Resolver) -> FrozenSet[str]:
    """Locks a function acquires lexically (with-items + manual)."""
    keys: Set[str] = set()
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
        ):
            continue
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                key = resolve(item.context_expr)
                if key is not None:
                    keys.add(key)
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr == "acquire":
                key = resolve(node.func.value)
                if key is not None:
                    keys.add(key)
        stack.extend(ast.iter_child_nodes(node))
    return frozenset(keys)


def _summaries(model: ScopeModel) -> Dict[Tuple[str, str], FrozenSet[str]]:
    """Per (class, method): every lock the method may acquire,
    transitively through self-calls and typed-attribute calls."""
    direct: Dict[Tuple[str, str], FrozenSet[str]] = {}
    calls: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
    for cname, info in model.classes.items():
        resolve = make_resolver(cname, info.stem)
        for mname, fn in info.methods.items():
            key = (cname, mname)
            direct[key] = _direct_locks(fn, resolve)
            out: Set[Tuple[str, str]] = set()
            for call in scan_calls(fn):
                func = call.func
                if not isinstance(func, ast.Attribute):
                    continue
                recv = dotted_name(func.value)
                if recv == "self" and func.attr in info.methods:
                    out.add((cname, func.attr))
                elif recv is not None and recv.startswith("self."):
                    attr = recv.split(".", 2)[1]
                    for tname in info.attr_types.get(attr, ()):
                        tinfo = model.classes.get(tname)
                        if tinfo and func.attr in tinfo.methods:
                            out.add((tname, func.attr))
            calls[key] = out
    summary = dict(direct)
    changed = True
    while changed:
        changed = False
        for key, callees in calls.items():
            cur = summary[key]
            for callee in callees:
                cur = cur | summary.get(callee, frozenset())
            if cur != summary[key]:
                summary[key] = cur
                changed = True
    return summary


def _derive_edges(
    model: ScopeModel,
    summary: Dict[Tuple[str, str], FrozenSet[str]],
) -> Dict[Edge, Tuple[str, int]]:
    """Edge → first (file, line) acquisition site, deterministically."""
    edges: Dict[Edge, Tuple[str, int]] = {}

    def note(a: str, b: str, rel: str, line: int) -> None:
        if a == b:
            return  # re-entrant self-acquire is the RLock's business
        site = (rel, line)
        if (a, b) not in edges or site < edges[(a, b)]:
            edges[(a, b)] = site

    for rel, stem, cname, fn in model.functions:
        info = model.classes.get(cname) if cname else None
        resolve = make_resolver(cname, stem)
        seed = (
            info.locks
            if info is not None
            and fn.name.endswith("_locked")
            and info.locks
            else frozenset()
        )
        cfg = build_cfg(fn, resolve)
        states = held_at_nodes(cfg, resolve, seed=seed, must=False)
        for node in cfg.nodes:
            held = states.get(node)
            if not held:
                continue
            if node.kind == "acquire" and node.lock is not None:
                for a in held:
                    note(a, node.lock, rel, node.line)
                continue
            for root in node_scan_roots(node):
                acq, _ = manual_lock_ops(root, resolve)
                for b in acq:
                    for a in held:
                        note(a, b, rel, node.line)
                for call in scan_calls(root):
                    func = call.func
                    if not isinstance(func, ast.Attribute):
                        continue
                    targets: FrozenSet[str] = frozenset()
                    recv = dotted_name(func.value)
                    if (
                        recv == "self"
                        and info is not None
                        and func.attr in info.methods
                    ):
                        targets = summary.get(
                            (info.node.name, func.attr), frozenset()
                        )
                    elif (
                        recv is not None
                        and recv.startswith("self.")
                        and info is not None
                    ):
                        attr = recv.split(".", 2)[1]
                        for tname in info.attr_types.get(attr, ()):
                            tinfo = model.classes.get(tname)
                            if tinfo and func.attr in tinfo.methods:
                                targets = targets | summary.get(
                                    (tname, func.attr), frozenset()
                                )
                    for b in targets:
                        for a in held:
                            note(a, b, rel, call.lineno)
    return edges


def _cycle_edges(edges: Iterable[Edge]) -> Set[Edge]:
    """Edges participating in any cycle: both endpoints in one SCC."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    comp: Dict[str, int] = {}
    counter = [0]
    comp_id = [0]
    stack: List[str] = []
    on_stack: Set[str] = set()

    def strongconnect(v: str) -> None:
        # Iterative Tarjan (recursion depth is unbounded on big graphs).
        work: List[Tuple[str, Iterator[str]]] = [
            (v, iter(sorted(graph[v])))
        ]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp[w] = comp_id[0]
                    if w == node:
                        break
                comp_id[0] += 1
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    comp_sizes: Dict[int, int] = {}
    for v, c in comp.items():
        comp_sizes[c] = comp_sizes.get(c, 0) + 1
    return {
        (a, b)
        for a, b in edges
        if comp[a] == comp[b] and comp_sizes[comp[a]] > 1
    }


def lock_graph(project: Project) -> Dict[str, object]:
    """The derived hierarchy as stable JSON-ready data (no line
    numbers: the doc embedding must not churn on unrelated edits)."""
    rule_paths = project.rule_paths(NAME, DEFAULT_PATHS)
    model = scan_scope(project, rule_paths)
    edges = _derive_edges(model, _summaries(model))
    return {
        "locks": sorted(model.all_locks),
        "edges": sorted([list(e) for e in edges]),
    }


class DeadlockOrderRule:
    name = NAME
    code = CODE
    summary = (
        "the derived global lock-acquisition graph (nested with/"
        "acquire + typed-attribute call summaries) must stay acyclic"
    )
    project_wide = True

    def check(self, project: Project) -> Iterable[Finding]:
        model = scan_scope(
            project, project.rule_paths(NAME, DEFAULT_PATHS)
        )
        edges = _derive_edges(model, _summaries(model))
        bad = _cycle_edges(edges.keys())
        findings: List[Finding] = []
        for a, b in sorted(bad):
            rel, line = edges[(a, b)]
            others = sorted(
                f"{x} → {y}" for x, y in bad if (x, y) != (a, b)
            )
            findings.append(
                Finding(
                    NAME,
                    CODE,
                    rel,
                    line,
                    f"lock-order cycle: acquiring {b} while holding "
                    f"{a} conflicts with the opposite ordering "
                    f"elsewhere ({'; '.join(others)}) — two threads "
                    "taking these paths concurrently deadlock; pick "
                    "one global order (docs/CONCURRENCY.md) and "
                    "restructure the latecomer",
                )
            )
        return findings


RULE = DeadlockOrderRule()
