"""GL011 donation-aliasing: donated device buffers must not have live aliases.

Every hot accumulator in this tree rides ``jax.jit(...,
donate_argnums=(0,))``: the caller's buffer is handed to XLA, which
reuses it for the output — accumulation stays in place in HBM, the win
every blockwise path depends on. The contract is brutal on the host
side: after the dispatch the donated buffer is DEAD, and on CPU
``np.asarray`` over a jax array is a zero-copy read-only view of that
same buffer (the exact hazard the ``DeltaEntry`` copy in
``serving/deltas.py`` documents and defuses by hand). A surviving
alias reads recycled memory — silent corruption the checksum guard
catches at best and a wrong Gramian serves at worst.

This rule indexes every donating callable in scope — ``@partial(jax.jit,
donate_argnums=...)`` decorated defs, ``name = jax.jit(f,
donate_argnums=...)`` assignment forms, and (one transitive level)
plain functions that forward a parameter into a donated position, so
the public wrappers ``gramian_accumulate``/``sparse_gramian_accumulate``/
``signed_scatter_pairs`` gate their call sites too — then checks each
call site's donated argument:

1. **stored attribute** — donating ``self.x`` / ``obj.attr`` leaves the
   object holding a dead buffer for every other method (the classmodel
   attr index names the other accessors in the finding);
2. **view expression** — donating ``x[...]`` donates a view whose base
   stays live in the caller;
3. **view alias** — a ``v = np.asarray(x)`` / ``v = x[...]`` /
   ``v = x.reshape/ravel/view/T`` alias taken before the call (with no
   rebind of ``x`` between) dies with the donation if it is read,
   returned, or stored afterwards — and an alias taken *after* the
   call aliases the dead buffer unless the call rebound ``x``;
4. **use after donation** — reading ``x`` after the donating call
   without rebinding. The blessed shape is ``x = donating(x, ...)``:
   rebinding through the call is what every accumulator loop here does,
   and it makes the loop's next iteration read the fresh buffer.

Function parameters forwarded into a donated position are not findings
at the forwarding site (the wrapper inherits the donating contract and
its own call sites are checked instead).
"""

from __future__ import annotations

import ast
from typing import (
    Callable,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from tools.graftlint.astutil import dotted_name, last_component
from tools.graftlint.classmodel import scan_scope
from tools.graftlint.engine import Finding, Project

NAME = "donation-aliasing"
CODE = "GL011"

DEFAULT_PATHS = (
    "spark_examples_tpu/ops",
    "spark_examples_tpu/parallel",
    "spark_examples_tpu/serving",
)

# View-producing numpy entry points: zero-copy over a jax array.
_VIEW_CALLS = frozenset({"asarray", "frombuffer"})
# Methods returning views of their receiver.
_VIEW_METHODS = frozenset({"reshape", "ravel", "view", "transpose", "swapaxes"})


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Donated argument positions from a ``jax.jit``/``pjit``/``partial``
    call carrying ``donate_argnums``, else None."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            val = kw.value
            if isinstance(val, ast.Constant) and isinstance(val.value, int):
                return (val.value,)
            if isinstance(val, (ast.Tuple, ast.List)):
                out = []
                for elt in val.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, int
                    ):
                        out.append(elt.value)
                return tuple(out)
    return None


def _jit_like(call: ast.Call) -> bool:
    last = last_component(dotted_name(call.func))
    return last in ("jit", "pjit", "partial")


class _Donators:
    """name -> donated positions, indexed over the whole scope."""

    def __init__(self) -> None:
        self.by_name: Dict[str, Tuple[int, ...]] = {}

    def scan_tree(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call) and _jit_like(dec):
                        pos = _donated_positions(dec)
                        if pos:
                            self.by_name[node.name] = pos
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if _jit_like(node.value):
                    pos = _donated_positions(node.value)
                    if pos:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                self.by_name[tgt.id] = pos

    def close_wrappers(self, trees: Sequence[ast.AST]) -> None:
        """One transitive level: a plain function forwarding a parameter
        into a donated position donates that parameter itself."""
        for tree in trees:
            for node in ast.walk(tree):
                if not isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if node.name in self.by_name:
                    continue
                params = [a.arg for a in node.args.args]
                donated: Set[int] = set()
                for call in ast.walk(node):
                    if not isinstance(call, ast.Call):
                        continue
                    positions = self.positions_for(call.func)
                    if not positions:
                        continue
                    for p in positions:
                        if p < len(call.args):
                            arg = call.args[p]
                            if (
                                isinstance(arg, ast.Name)
                                and arg.id in params
                            ):
                                donated.add(params.index(arg.id))
                if donated:
                    self.by_name[node.name] = tuple(sorted(donated))

    def positions_for(self, func: ast.AST) -> Optional[Tuple[int, ...]]:
        name = last_component(dotted_name(func))
        if name is None:
            return None
        return self.by_name.get(name)


def _is_view_of(expr: ast.AST, name: str) -> bool:
    """True when ``expr`` is a zero-copy view of variable ``name``."""
    if isinstance(expr, ast.Subscript):
        base = expr.value
        return isinstance(base, ast.Name) and base.id == name
    if isinstance(expr, ast.Call):
        last = last_component(dotted_name(expr.func))
        if last in _VIEW_CALLS and expr.args:
            # np.array(x) copies by default, so it is deliberately NOT
            # in _VIEW_CALLS; asarray/frombuffer are zero-copy.
            a = expr.args[0]
            return isinstance(a, ast.Name) and a.id == name
        if (
            isinstance(expr.func, ast.Attribute)
            and expr.func.attr in _VIEW_METHODS
            and isinstance(expr.func.value, ast.Name)
            and expr.func.value.id == name
        ):
            return True
    if (
        isinstance(expr, ast.Attribute)
        and expr.attr == "T"
        and isinstance(expr.value, ast.Name)
        and expr.value.id == name
    ):
        return True
    return False


def _own_statements(fn: ast.AST) -> List[ast.stmt]:
    """Function statements in source order, compound bodies flattened,
    nested defs/classes opaque."""
    out: List[ast.stmt] = []

    def visit(body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(
                stmt,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            out.append(stmt)
            for field in ("body", "orelse", "finalbody"):
                inner = getattr(stmt, field, None)
                if inner:
                    visit(inner)
            for handler in getattr(stmt, "handlers", ()):
                visit(handler.body)

    visit(fn.body)
    out.sort(key=lambda s: (s.lineno, s.col_offset))
    return out


def _stmt_own_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions a flattened statement evaluates ITSELF: header
    expressions for compound statements (their bodies are separate list
    entries), the whole node for simple ones. Mirrors
    ``dataflow.node_scan_roots`` — double-attributing a compound body's
    calls to the header would double every finding."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Try):
        return []
    return [stmt]


def _stmt_reads(
    stmt: ast.stmt, name: str, skip: Optional[ast.AST] = None
) -> bool:
    for root in _stmt_own_exprs(stmt):
        for sub in ast.walk(root):
            if sub is skip:
                continue
            if (
                isinstance(sub, ast.Name)
                and sub.id == name
                and isinstance(sub.ctx, ast.Load)
            ):
                return True
    return False


def _stmt_rebinds(stmt: ast.stmt, name: str) -> bool:
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name) and sub.id == name:
                return True
    return False


def _enclosing_loops(fn: ast.AST) -> List[Tuple[ast.stmt, Set[int]]]:
    """(loop stmt, line numbers of its body) for every loop in ``fn``."""
    loops: List[Tuple[ast.stmt, Set[int]]] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            lines = {
                sub.lineno
                for stmt in node.body
                for sub in ast.walk(stmt)
                if hasattr(sub, "lineno")
            }
            loops.append((node, lines))
    return loops


class _FnChecker:
    def __init__(
        self,
        rel: str,
        fn: ast.AST,
        donators: _Donators,
        attr_note: Callable[[ast.Attribute], str],
    ) -> None:
        self.rel = rel
        self.fn = fn
        self.donators = donators
        self.attr_note = attr_note
        self.stmts = _own_statements(fn)
        self.loops = _enclosing_loops(fn)
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        params = {a.arg for a in self.fn.args.args}
        for i, stmt in enumerate(self.stmts):
            for root in _stmt_own_exprs(stmt):
                for call in ast.walk(root):
                    if not isinstance(call, ast.Call):
                        continue
                    positions = self.donators.positions_for(call.func)
                    if not positions:
                        continue
                    callee = (
                        last_component(dotted_name(call.func)) or "<callable>"
                    )
                    for p in positions:
                        if p >= len(call.args):
                            continue
                        self._check_arg(
                            i, stmt, call, callee, call.args[p], params
                        )
        return self.findings

    def _check_arg(
        self,
        idx: int,
        stmt: ast.stmt,
        call: ast.Call,
        callee: str,
        arg: ast.AST,
        params: Set[str],
    ) -> None:
        if isinstance(arg, ast.Attribute):
            owner = dotted_name(arg.value) or "<expr>"
            note = self.attr_note(arg) if owner == "self" else ""
            self.findings.append(
                Finding(
                    NAME,
                    CODE,
                    self.rel,
                    call.lineno,
                    f"`{callee}(...)` donates the stored attribute "
                    f"`{owner}.{arg.attr}`: after the dispatch the "
                    "object still holds a reference to the DEAD buffer"
                    f"{note} — donate a local and store the fresh "
                    "result, or pass a copy",
                )
            )
            return
        if isinstance(arg, ast.Subscript):
            self.findings.append(
                Finding(
                    NAME,
                    CODE,
                    self.rel,
                    call.lineno,
                    f"`{callee}(...)` donates a subscript view: the "
                    "view's base array stays live in the caller and "
                    "reads recycled memory after the dispatch — "
                    "materialize a copy before donating",
                )
            )
            return
        if not isinstance(arg, ast.Name):
            return  # a call expression: fresh value, nothing retained
        name = arg.id
        rebinds_self = _stmt_rebinds(stmt, name)
        self._check_view_aliases(idx, stmt, call, callee, name, rebinds_self)
        if rebinds_self:
            return  # `x = donating(x, ...)` — the blessed shape
        if name in params and not self._read_after(idx, stmt, name, call):
            # Forwarding wrapper: its own call sites carry the check.
            return
        if self._read_after(idx, stmt, name, call):
            self.findings.append(
                Finding(
                    NAME,
                    CODE,
                    self.rel,
                    call.lineno,
                    f"`{name}` is read after `{callee}(...)` donated "
                    "it: the buffer was handed to XLA and may be "
                    "recycled under the reader — rebind through the "
                    f"call (`{name} = {callee}(...)`) or copy first",
                )
            )

    def _read_after(
        self, idx: int, stmt: ast.stmt, name: str, call: ast.Call
    ) -> bool:
        """Is ``name`` read after the donating call before any rebind —
        including earlier statements of an enclosing loop body (the next
        iteration runs them after the call)?"""
        for later in self.stmts[idx + 1 :]:
            if _stmt_reads(later, name):
                return True
            if _stmt_rebinds(later, name):
                return False
        for loop, lines in self.loops:
            if call.lineno in lines:
                for other in self.stmts:
                    if other is stmt or other.lineno not in lines:
                        continue
                    if _stmt_reads(other, name, skip=call):
                        return True
        return False

    def _check_view_aliases(
        self,
        idx: int,
        stmt: ast.stmt,
        call: ast.Call,
        callee: str,
        name: str,
        rebinds_self: bool,
    ) -> None:
        # Aliases taken BEFORE the call (no rebind of `name` between):
        # they die at donation; flag when read/stored afterwards.
        alias_names: Set[str] = set()
        for before in self.stmts[:idx]:
            if _stmt_rebinds(before, name):
                alias_names.clear()
                continue
            if isinstance(before, ast.Assign) and _is_view_of(
                before.value, name
            ):
                for t in before.targets:
                    if isinstance(t, ast.Name):
                        alias_names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        self.findings.append(
                            Finding(
                                NAME,
                                CODE,
                                self.rel,
                                before.lineno,
                                f"a zero-copy view of `{name}` is "
                                "stored on an attribute and `"
                                f"{name}` is later donated by "
                                f"`{callee}(...)` (line {call.lineno})"
                                " — the stored view reads recycled "
                                "memory; store an explicit copy "
                                "(np.array(x, copy=True), the "
                                "DeltaEntry discipline)",
                            )
                        )
        for v in sorted(alias_names):
            for later in self.stmts[idx:]:
                if later is stmt:
                    continue
                if _stmt_reads(later, v):
                    self.findings.append(
                        Finding(
                            NAME,
                            CODE,
                            self.rel,
                            later.lineno,
                            f"`{v}` is a zero-copy view of `{name}`, "
                            f"which `{callee}(...)` donated at line "
                            f"{call.lineno}: the view reads recycled "
                            "memory — take an explicit copy before "
                            "the donating dispatch",
                        )
                    )
                    break
                if _stmt_rebinds(later, v):
                    break
        # Aliases taken AFTER the call view the dead buffer unless the
        # call rebound the name.
        if rebinds_self:
            return
        for later in self.stmts[idx + 1 :]:
            if _stmt_rebinds(later, name):
                break
            if isinstance(later, ast.Assign) and _is_view_of(
                later.value, name
            ):
                self.findings.append(
                    Finding(
                        NAME,
                        CODE,
                        self.rel,
                        later.lineno,
                        f"zero-copy view of `{name}` taken after "
                        f"`{callee}(...)` donated it (line "
                        f"{call.lineno}): the buffer is dead — view "
                        "the call's RESULT instead",
                    )
                )
                break


class DonationAliasingRule:
    name = NAME
    code = CODE
    summary = (
        "arguments donated to jit (donate_argnums) must have no live "
        "host alias: no stored attributes, no np.asarray/slice views, "
        "no reads after the dispatch"
    )
    project_wide = False

    def check(self, project: Project) -> Iterable[Finding]:
        paths = project.rule_paths(NAME, DEFAULT_PATHS)
        donators = _Donators()
        trees = []
        files: List[Tuple[str, ast.AST]] = []
        for top in paths:
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                donators.scan_tree(ctx.tree)
                trees.append(ctx.tree)
                files.append((rel, ctx.tree))
        donators.close_wrappers(trees)
        model = scan_scope(project, paths)

        def attr_note(attr: ast.Attribute) -> str:
            # Cross-method escape context from the classmodel index:
            # name the OTHER methods touching this attribute, so the
            # finding shows who reads the dead buffer.
            holders = []
            for info in model.classes.values():
                for mname, m in info.methods.items():
                    for sub in ast.walk(m):
                        if (
                            isinstance(sub, ast.Attribute)
                            and sub.attr == attr.attr
                            and isinstance(sub.value, ast.Name)
                            and sub.value.id == "self"
                            and sub is not attr
                        ):
                            holders.append(f"{info.name}.{mname}")
                            break
            if not holders:
                return ""
            return (
                " (also accessed in "
                + ", ".join(sorted(set(holders))[:4])
                + ")"
            )

        findings: List[Finding] = []
        for rel, tree in files:
            for node in ast.walk(tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    findings.extend(
                        _FnChecker(rel, node, donators, attr_note).run()
                    )
        return findings


RULE = DonationAliasingRule()
