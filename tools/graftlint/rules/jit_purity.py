"""GL001 jit-purity: no host syncs or Python side effects inside jit.

A ``@jax.jit``/``pjit`` body is a *traced program*: anything that pulls a
traced value back to the host (``jax.device_get``, ``float(x)``,
``np.asarray(x)``, ``.block_until_ready()``, ``.item()``) either crashes
under tracing or — worse — silently forces a device sync on every call,
the exact silent-host-sync rot the streaming-feed literature warns
overlap pipelines about. Python side effects (prints, tracer spans,
metric increments) run once at trace time and then never again, so they
lie: a span inside jit times the *trace*, not the execution.

The dynamic contract this front-runs: the transfer/compute overlap that
PR 3-4 measured (double-buffered feed, completion-order ingest) only
holds while the accumulation kernels stay dispatch-async; one stray
host sync serializes the pipeline and no tier-1 test asserts wall-clock.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tools.graftlint.astutil import (
    call_name,
    jitted_functions,
    walk_calls,
)
from tools.graftlint.engine import Finding, Project

NAME = "jit-purity"
CODE = "GL001"

DEFAULT_PATHS = (
    "spark_examples_tpu/ops",
    "spark_examples_tpu/parallel",
    "spark_examples_tpu/arrays/feed.py",
)

# Callee dotted-name suffixes that mean "host sync" inside a trace.
_HOST_SYNC_SUFFIXES = (
    "device_get",
    "block_until_ready",
    "item",
    "tolist",
)
# numpy host-materialization entry points (np.prod over a static shape
# is fine and common; materializing an *array* is not).
_NUMPY_MATERIALIZE = ("asarray", "array", "copyto", "save", "frombuffer")
# Telemetry/obs surfaces: side effects that run at trace time only.
_SIDE_EFFECT_SUFFIXES = (
    "span",
    "instant",
    "get_registry",
    "observe_rpc",
    "count_retry",
    "rpc_timer",
    "inc",
    "observe",
)


def _violation(call: ast.Call) -> str:
    name = call_name(call) or ""
    last = name.rsplit(".", 1)[-1]
    root = name.split(".", 1)[0]
    if last in _HOST_SYNC_SUFFIXES:
        return (
            f"host sync `{name}(...)` inside a jit-traced body: forces a "
            "device round-trip (or crashes under tracing)"
        )
    if root in ("np", "numpy") and last in _NUMPY_MATERIALIZE:
        return (
            f"`{name}(...)` inside a jit-traced body materializes on "
            "host — a silent per-call device sync"
        )
    if last == "print" or name == "print":
        return (
            "print inside a jit-traced body runs at trace time only "
            "(use jax.debug.print for runtime prints)"
        )
    if last in _SIDE_EFFECT_SUFFIXES or root == "obs":
        return (
            f"telemetry side effect `{name}(...)` inside a jit-traced "
            "body fires once at trace time, then never again — it times "
            "the trace, not the execution"
        )
    return ""


class JitPurityRule:
    name = NAME
    code = CODE
    summary = (
        "no host syncs (device_get/float()/np.asarray/.item) or Python "
        "side effects (print, spans, metrics) inside @jax.jit/pjit bodies"
    )
    project_wide = False

    def check(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for top in project.rule_paths(NAME, DEFAULT_PATHS):
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                for fn in jitted_functions(ctx.tree):
                    for call in walk_calls(fn):
                        msg = _violation(call)
                        if not msg:
                            # float(x) on a non-constant: the classic
                            # implicit device_get.
                            cname = call_name(call)
                            if (
                                cname == "float"
                                and len(call.args) == 1
                                and not isinstance(
                                    call.args[0], ast.Constant
                                )
                            ):
                                msg = (
                                    "float(...) on a traced value is an "
                                    "implicit device_get inside jit"
                                )
                        if msg:
                            findings.append(
                                Finding(
                                    NAME, CODE, rel, call.lineno, msg
                                )
                            )
        return findings


RULE = JitPurityRule()
