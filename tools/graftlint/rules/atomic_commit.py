"""GL013 atomic-commit: every persisted file lands tmp→fsync→rename,
with the torn-write seam on the path.

Five independent persistence surfaces now hand-enforce the same
commit discipline — the durable store (``store/local.py``), the job
journal + delta cache (``serving/jobs.py``, ``serving/deltas.py``),
the cohort mirror (``genomics/mirror.py``), and the crash flight
recorder (``obs/flightrec.py``). The convention: a write targeting a
persistence root is visible to readers only through an atomic rename
of a fully-fsynced tmp file, and the write path carries the
``faults.inject_write`` torn-write seam so the deterministic chaos
suite (and crashsim) can reach it. A write that skips the fsync can
surface TORN under its final name after a crash — the rename is
journaled metadata, the data pages are not — and a write without the
seam is invisible to every torn-write chaos scenario.

Per function in a configured persistence root that performs a write —
``open(..., "w"/"wb"/"x"...)`` (append-mode journals are exempt: they
are torn-tail-tolerant by design, not rename-committed), ``np.save*``,
or ``json.dump`` — the rule checks, flow-sensitively on the CFG:

1. if the function renames (``os.replace``/``os.rename``): at every
   rename node, an ``os.fsync`` must have occurred on EVERY path from
   entry (must-event dataflow — this IS the fsync-before-rename order
   check), and so must a ``faults.inject_write`` seam, unless a
   blessed commit helper call (which owns both) dominates instead;
2. if the function never renames: the write must flow through a
   blessed commit helper (``_commit_tmp``, ``LocalDirStore.put`` —
   the ``commit_helpers`` config key extends the set), else the write
   is non-atomic by construction.

Blessed helpers are blessed because they are themselves in scope and
checked by (1) — the discipline bottoms out in a function this rule
proves, not in a registry of trust.
"""

from __future__ import annotations

import ast
from typing import FrozenSet, Iterable, List, Optional, Tuple

from tools.graftlint.astutil import call_name, last_component, literal_str
from tools.graftlint.dataflow import (
    build_cfg,
    must_events,
    node_scan_roots,
    scan_calls,
    walk_skip_nested,
)
from tools.graftlint.engine import Finding, Project

NAME = "atomic-commit"
CODE = "GL013"

DEFAULT_PATHS = (
    "spark_examples_tpu/store",
    "spark_examples_tpu/serving/jobs.py",
    "spark_examples_tpu/serving/deltas.py",
    "spark_examples_tpu/genomics/mirror.py",
    "spark_examples_tpu/obs/flightrec.py",
)

# Commit helpers that own the fsync + seam + rename internally. Their
# own bodies are checked by this rule (they live in scope), so a call
# to one blesses the caller's write without weakening the proof.
DEFAULT_COMMIT_HELPERS = ("_commit_tmp", "LocalDirStore.put")

_NP_WRITERS = frozenset({"save", "savez", "savez_compressed"})


def _write_mode(call: ast.Call) -> Optional[str]:
    """The literal mode of an ``open(...)`` call, when it writes a new
    file image ('w'/'x' modes). Read, append, and update-in-place
    modes return None — append-mode journals are torn-tail-tolerant by
    design and never rename-committed."""
    if last_component(call_name(call)) != "open":
        return None
    mode_node: Optional[ast.AST] = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if mode_node is None:
        return None
    mode = literal_str(mode_node)
    if mode is None:
        return None
    return mode if ("w" in mode or "x" in mode) else None


def _is_write_call(call: ast.Call) -> bool:
    if _write_mode(call) is not None:
        return True
    name = call_name(call)
    last = last_component(name)
    if last in _NP_WRITERS and name and name.split(".")[0] in (
        "np",
        "numpy",
        "jnp",
    ):
        return True
    if last == "dump" and name and name.split(".")[0] == "json":
        return True
    return False


def _is_rename_call(call: ast.Call) -> bool:
    return call_name(call) in ("os.replace", "os.rename")


def _is_fsync_call(call: ast.Call) -> bool:
    return last_component(call_name(call)) == "fsync"


def _is_seam_call(call: ast.Call) -> bool:
    return last_component(call_name(call)) == "inject_write"


def _is_helper_call(call: ast.Call, helpers: FrozenSet[str]) -> bool:
    last = last_component(call_name(call))
    return last is not None and last in helpers


class AtomicCommitRule:
    name = NAME
    code = CODE
    summary = (
        "persistence-root writes commit tmp→fsync→atomic-rename with "
        "the faults.inject_write torn seam on the path (or flow "
        "through a blessed commit helper)"
    )
    project_wide = False

    def check(self, project: Project) -> Iterable[Finding]:
        helpers = frozenset(
            last_component(h) or h
            for h in self._helpers(project)
        )
        findings: List[Finding] = []
        for top in project.rule_paths(NAME, DEFAULT_PATHS):
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                for fn in _functions(ctx.tree):
                    findings.extend(
                        self._check_function(rel, fn, helpers)
                    )
        return findings

    def _helpers(self, project: Project) -> Tuple[str, ...]:
        cfg = project.config.get("rules", {}).get(NAME, {})
        return tuple(cfg.get("commit_helpers", DEFAULT_COMMIT_HELPERS))

    def _check_function(
        self, rel: str, fn: ast.AST, helpers: FrozenSet[str]
    ) -> List[Finding]:
        writes: List[ast.Call] = []
        renames = False
        helper_called = False
        for node in walk_skip_nested(fn, skip_self=True):
            if not isinstance(node, ast.Call):
                continue
            if _is_write_call(node):
                writes.append(node)
            elif _is_rename_call(node):
                renames = True
            elif _is_helper_call(node, helpers):
                helper_called = True
        if not writes:
            return []
        if not renames:
            if helper_called:
                return []
            return [
                Finding(
                    NAME,
                    CODE,
                    rel,
                    w.lineno,
                    "write to a persistence root with no atomic commit: "
                    "no os.replace/os.rename in this function and no "
                    "blessed commit helper call "
                    f"({', '.join(sorted(helpers))}) — a crash here "
                    "leaves a partial file readers will trust",
                )
                for w in writes
            ]
        return self._check_rename_paths(rel, fn, helpers)

    def _check_rename_paths(
        self, rel: str, fn: ast.AST, helpers: FrozenSet[str]
    ) -> List[Finding]:
        cfg = build_cfg(fn, lambda expr: None)

        def events_at(node) -> FrozenSet[str]:
            tags = set()
            for root in node_scan_roots(node):
                for call in scan_calls(root):
                    if _is_fsync_call(call):
                        tags.add("fsync")
                    if _is_seam_call(call):
                        tags.add("seam")
                    if _is_helper_call(call, helpers):
                        tags.update(("fsync", "seam"))
            return frozenset(tags)

        in_states = must_events(cfg, events_at)
        findings: List[Finding] = []
        for node in cfg.nodes:
            rename_line = None
            for root in node_scan_roots(node):
                for call in scan_calls(root):
                    if _is_rename_call(call):
                        rename_line = call.lineno
            if rename_line is None:
                continue
            state = in_states.get(node)
            if state is None:
                continue  # unreachable rename
            if "fsync" not in state:
                findings.append(
                    Finding(
                        NAME,
                        CODE,
                        rel,
                        rename_line,
                        "atomic rename without fsync on every path from "
                        "entry: the rename is journaled metadata but the "
                        "data pages are not — a crash can surface a TORN "
                        "file under the committed name",
                    )
                )
            if "seam" not in state:
                findings.append(
                    Finding(
                        NAME,
                        CODE,
                        rel,
                        rename_line,
                        "commit path without the faults.inject_write torn-"
                        "write seam: the deterministic chaos suite (and "
                        "crashsim) cannot reach this write — add the seam "
                        "before the rename",
                    )
                )
        return findings


def _functions(tree: ast.AST) -> Iterable[ast.AST]:
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        elif isinstance(node, ast.ClassDef):
            for sub in ast.iter_child_nodes(node):
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield sub


RULE = AtomicCommitRule()
