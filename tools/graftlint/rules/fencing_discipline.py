"""GL014 fencing-discipline: fenced namespaces, fresh tokens, no store
I/O under the lease lock.

Replicated serving (PR 17) owes its kill-any-replica safety to three
conventions that nothing machine-checked until now:

1. **fenced namespaces are written fenced** — keys under a shared
   prefix (``jobs/``, ``adopted/``) are contended between replicas;
   writing one with raw ``.put(...)`` / deleting with ``.delete(...)``
   bypasses the fence-token CAS and lets a zombie replica clobber the
   rightful owner's state. Any store write whose key is (or is built
   from a module constant bound to) a fenced prefix must go through
   ``put_fenced``.
2. **the fence-token read dominates the write** — the ``lease``
   argument handed to ``put_fenced`` must be provably fresh on EVERY
   CFG path: assigned from a ``.lease()`` / ``lease_acquire(...)``
   call earlier in the same function (must-event dataflow), or be the
   call itself inline. Passing a lease held in an attribute
   (``self._lease``) is a stale-token hazard — the snapshot the
   heartbeat thread replaces is not the snapshot you fenced with.
3. **no store I/O while ``LeaseManager._lock`` is must-held** — the
   CONCURRENCY.md non-edge: the lease lock guards in-memory snapshot
   state only; store calls block on disk (and on the store's own
   dir-mutex), and holding the lease lock across one stalls the
   heartbeat thread into lease expiry — the outage it exists to
   prevent. This rule machine-checks the documented non-edge, so the
   CONCURRENCY.md lock graph stays edge-free by proof, not by prose.

Config (``[tool.graftlint.rules.fencing-discipline]``):
``fenced_prefixes`` (default ``["jobs/", "adopted/"]``) and
``no_store_io_locks`` (default ``["LeaseManager._lock"]``).
"""

from __future__ import annotations

import ast
import os
from typing import FrozenSet, Iterable, List, Optional, Set, Tuple

from tools.graftlint.astutil import call_name, last_component, literal_str
from tools.graftlint.dataflow import (
    build_cfg,
    class_lock_keys,
    held_at_nodes,
    make_resolver,
    must_events,
    node_scan_roots,
    scan_calls,
)
from tools.graftlint.engine import Finding, Project

NAME = "fencing-discipline"
CODE = "GL014"

DEFAULT_PATHS = ("spark_examples_tpu/serving",)

DEFAULT_FENCED_PREFIXES = ("jobs/", "adopted/")
DEFAULT_NO_STORE_IO_LOCKS = ("LeaseManager._lock",)

# Calls that acquire/refresh a fence token. ``.lease()`` is the
# LeaseManager snapshot read; ``lease_acquire`` is the store CAS.
_LEASE_SOURCES = frozenset({"lease", "lease_acquire"})

# The DurableStore surface: any of these on a store-like receiver is
# I/O that blocks on disk (and the store's dir-mutex).
_STORE_IO = frozenset(
    {
        "put",
        "put_fenced",
        "get",
        "delete",
        "list_keys",
        "check_fence",
        "lease_acquire",
        "lease_renew",
        "lease_release",
        "lease_get",
        "lease_list",
        "now",
    }
)


def _store_like(expr: ast.AST) -> bool:
    """True when the receiver reads as a durable store: its trailing
    name word-contains "store" (``self.store``, ``replica.store``,
    ``self._store``)."""
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    if name is None:
        return False
    return "store" in name.lower().split("_") or name.lower() == "store"


def _fenced_constants(
    project: Project, tops: Iterable[str], prefixes: Tuple[str, ...]
) -> Set[str]:
    """Module-level ``NAME = "jobs/"``-style constants across the scope
    whose literal value starts with a fenced prefix. Matched by bare
    name at use sites — imports re-bind the same name."""
    consts: Set[str] = set()
    for top in tops:
        for rel in project.walk(top):
            ctx = project.file(rel)
            if ctx is None or ctx.tree is None:
                continue
            for node in ast.iter_child_nodes(ctx.tree):
                if not isinstance(node, ast.Assign):
                    continue
                value = literal_str(node.value)
                if value is None:
                    continue
                if not any(value.startswith(p) for p in prefixes):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        consts.add(tgt.id)
    return consts


def _key_is_fenced(
    key: ast.AST, prefixes: Tuple[str, ...], consts: Set[str]
) -> bool:
    """Does this key expression target a fenced namespace? Literal
    prefix match, a fenced constant by name, or ``CONST + <expr>`` /
    ``"jobs/" + <expr>`` concatenation."""
    lit = literal_str(key)
    if lit is not None:
        return any(lit.startswith(p) for p in prefixes)
    if isinstance(key, ast.Name):
        return key.id in consts
    if isinstance(key, ast.BinOp) and isinstance(key.op, ast.Add):
        return _key_is_fenced(key.left, prefixes, consts)
    if isinstance(key, ast.JoinedStr) and key.values:
        return _key_is_fenced(key.values[0], prefixes, consts)
    return False


def _lease_arg(call: ast.Call) -> Optional[ast.AST]:
    """The lease argument of a ``put_fenced(key, data, lease)`` call."""
    for kw in call.keywords:
        if kw.arg == "lease":
            return kw.value
    if len(call.args) >= 3:
        return call.args[2]
    return None


class FencingDisciplineRule:
    name = NAME
    code = CODE
    summary = (
        "fenced-namespace writes go through put_fenced with a fence "
        "token read that dominates the write; no store I/O while the "
        "lease lock is held"
    )
    # Fenced-prefix constants are defined in one module and used from
    # another — the constant map must see the whole scope even when the
    # CLI restricts paths.
    project_wide = True

    def check(self, project: Project) -> Iterable[Finding]:
        cfg = project.config.get("rules", {}).get(NAME, {})
        prefixes = tuple(
            cfg.get("fenced_prefixes", DEFAULT_FENCED_PREFIXES)
        )
        io_locks = frozenset(
            cfg.get("no_store_io_locks", DEFAULT_NO_STORE_IO_LOCKS)
        )
        tops = tuple(project.rule_paths(NAME, DEFAULT_PATHS))
        consts = _fenced_constants(project, tops, prefixes)
        findings: List[Finding] = []
        for top in tops:
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                stem = os.path.splitext(os.path.basename(rel))[0]
                for cls, fn in _functions_with_context(ctx.tree):
                    findings.extend(
                        self._check_function(
                            rel, stem, cls, fn, prefixes, consts, io_locks
                        )
                    )
        return findings

    def _check_function(
        self,
        rel: str,
        stem: str,
        cls: Optional[ast.ClassDef],
        fn: ast.AST,
        prefixes: Tuple[str, ...],
        consts: Set[str],
        io_locks: FrozenSet[str],
    ) -> List[Finding]:
        findings: List[Finding] = []
        cls_name = cls.name if cls is not None else None
        resolve = make_resolver(cls_name, stem)
        cfg = build_cfg(fn, resolve)

        # (b) setup: which names hold a provably-fresh fence token at
        # each point — gen at assignments from a lease-source call.
        def events_at(node) -> FrozenSet[str]:
            tags: Set[str] = set()
            for root in node_scan_roots(node):
                if not isinstance(root, ast.Assign):
                    continue
                fresh = any(
                    last_component(call_name(c)) in _LEASE_SOURCES
                    for c in scan_calls(root.value)
                )
                if not fresh:
                    continue
                for tgt in root.targets:
                    if isinstance(tgt, ast.Name):
                        tags.add(f"lease:{tgt.id}")
            return frozenset(tags)

        fresh_at = must_events(cfg, events_at)

        # (c) setup: must-held lock state, seeded per the *_locked
        # convention so LeaseManager's own _locked helpers verify.
        own_locks = (
            class_lock_keys(cls, stem) if cls is not None else frozenset()
        )
        seed = (
            own_locks
            if fn.name.endswith("_locked") and own_locks
            else frozenset()
        )
        held = held_at_nodes(cfg, resolve, seed=seed, must=True)

        for node in cfg.nodes:
            fresh = fresh_at.get(node)
            held_here = held.get(node)
            for root in node_scan_roots(node):
                for call in scan_calls(root):
                    func = call.func
                    if not isinstance(func, ast.Attribute):
                        continue
                    # (a) raw put/delete into a fenced namespace.
                    if (
                        func.attr in ("put", "delete")
                        and call.args
                        and _key_is_fenced(call.args[0], prefixes, consts)
                    ):
                        verb = (
                            "written" if func.attr == "put" else "deleted"
                        )
                        findings.append(
                            Finding(
                                NAME,
                                CODE,
                                rel,
                                call.lineno,
                                f"fenced namespace {verb} with raw "
                                f"`.{func.attr}(...)`: keys under "
                                f"{', '.join(prefixes)} are contended "
                                "between replicas — route through "
                                "put_fenced so a zombie's stale token "
                                "is rejected by the CAS",
                            )
                        )
                    # (b) put_fenced with a non-dominating token read.
                    if func.attr == "put_fenced":
                        findings.extend(
                            self._check_token(rel, call, fresh)
                        )
                    # (c) store I/O under the lease lock.
                    if (
                        func.attr in _STORE_IO
                        and _store_like(func.value)
                        and held_here is not None
                        and held_here & io_locks
                    ):
                        locks = ", ".join(sorted(held_here & io_locks))
                        findings.append(
                            Finding(
                                NAME,
                                CODE,
                                rel,
                                call.lineno,
                                f"store I/O (`.{func.attr}(...)`) while "
                                f"{locks} is held on every path — the "
                                "lease lock guards in-memory snapshots "
                                "only; blocking on the store under it "
                                "stalls the heartbeat into lease expiry "
                                "(the CONCURRENCY.md non-edge)",
                            )
                        )
        return findings

    def _check_token(
        self,
        rel: str,
        call: ast.Call,
        fresh: Optional[FrozenSet[str]],
    ) -> List[Finding]:
        arg = _lease_arg(call)
        if arg is None:
            return []  # arity error — not this rule's problem
        if isinstance(arg, ast.Call):
            if last_component(call_name(arg)) in _LEASE_SOURCES:
                return []  # token read inline at the write — fresh
        if isinstance(arg, ast.Name):
            if fresh is not None and f"lease:{arg.id}" in fresh:
                return []
            return [
                Finding(
                    NAME,
                    CODE,
                    rel,
                    call.lineno,
                    f"put_fenced with lease `{arg.id}` whose fence-token "
                    "read does not dominate the write: on some path "
                    "from entry it was never assigned from .lease() / "
                    "lease_acquire(...) in this function — read the "
                    "token on every path that reaches the write",
                )
            ]
        return [
            Finding(
                NAME,
                CODE,
                rel,
                call.lineno,
                "put_fenced with a stored lease (attribute/expression) "
                "instead of a locally-read token: the snapshot the "
                "heartbeat thread replaces is not the snapshot you "
                "fenced with — assign `lease = <mgr>.lease()` at the "
                "write site",
            )
        ]


def _functions_with_context(
    tree: ast.AST,
) -> Iterable[Tuple[Optional[ast.ClassDef], ast.AST]]:
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in ast.iter_child_nodes(node):
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield node, sub


RULE = FencingDisciplineRule()
