"""GL010 collective-congruence: every process issues the same collectives.

The pod-sparse engine (PRs 10-12) lives and dies by one SPMD rule:
every process of a pod must issue the same lockstep operations in the
same order. A collective (device `psum`/`all_gather`/`ppermute`, a host
`process_allgather`, or a podstream header/confirm exchange whose
gather blocks on every peer's post) that one process skips — because
its local stream drained, its ingest raised, or a per-process config
differed — strands every peer in that collective forever (or, on real
hardware, segfaults the pod). The protocol modules defend this at
runtime with the all-raise-together discipline: host-local failures are
encoded into the NEXT agreement step (width −1/−2 codes, the
payload-confirm exchange) so the raise lands on every process from
identical gathered data. This rule proves the structural half at review
time:

1. **host-local branch governance** — a lockstep collective may not be
   governed by a predicate derived from host-local state. Per function,
   a taint pass classifies every name: *host-local* values are stream
   data (``next(...)`` results, ``for`` targets over non-``range``
   iterables), caught exceptions, and ``jax.process_index()``/
   ``local_devices()``; *agreed* values are constants, function
   parameters (the cross-process config contract every protocol entry
   documents), free/module names, and — the heart of the protocol —
   anything derived from a prior agreement step
   (``gather_headers``/``gather_confirms``/``process_allgather``
   results). A collective inside an ``if``/``while`` on a tainted test,
   or lexically after a tainted branch that can ``return``/``raise``/
   ``break``/``continue`` (one side exits, the other proceeds into the
   collective), is a finding.
2. **except-handler collectives** — a lockstep collective inside an
   ``except`` body is governed by a local exception by definition: the
   peers did not take that handler. Always a finding.
3. **traced-branch collectives** — a collective inside a
   ``jax.lax.cond``/``lax.switch`` branch callable executes only on
   devices where the traced predicate selects that branch — the classic
   SPMD deadlock. The pod dense program keeps its ``all_gather``
   unconditional for exactly this reason (``_tile_dense_pod``).

The derived per-function collective order is the machine-readable
protocol sequence: ``python -m tools.graftlint --collective-order``
emits it as JSON, ``docs/CONCURRENCY.md`` embeds it verbatim, and a
drift test pins doc to derivation — the GL008 lock-graph discipline
applied to the SPMD dispatch surface.

Point-to-point payload moves (``post_payload``/``get_payload``/raw
``post``/``recv``) are deliberately NOT in the lockstep set: the framed
exchange consumes them according to the agreed headers (a drained
peer's payload is synthesized locally), and the runtime frame check
plus the ``SPARK_EXAMPLES_TPU_COLLECTIVE_CHECK=1`` backstop own that
half of the contract.
"""

from __future__ import annotations

import ast
from typing import (
    Any,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from tools.graftlint.astutil import dotted_name, last_component
from tools.graftlint.engine import Finding, Project

NAME = "collective-congruence"
CODE = "GL010"

DEFAULT_PATHS = (
    "spark_examples_tpu/parallel",
    "spark_examples_tpu/ops",
)

# Lockstep operations: every process must reach these together. The
# last dotted component is matched, so `jax.lax.psum`, `lax.psum` and
# a bare `psum` all count.
LOCKSTEP_OPS = frozenset(
    {
        # device collectives
        "psum",
        "psum_scatter",
        "all_gather",
        "all_to_all",
        "ppermute",
        "pmean",
        "pmax",
        "pmin",
        # host-side agreement collectives
        "process_allgather",
        "sync_global_devices",
        # podstream lockstep steps: every peer's gather blocks on every
        # peer's post, so posts are as congruence-critical as gathers.
        "post_header",
        "gather_headers",
        "post_confirm",
        "gather_confirms",
        "post_check",
        "gather_checks",
    }
)

# Results of these calls are agreement values: identical on every
# process by protocol construction — predicates derived from them are
# congruent branches, the sanctioned way to make a collective
# conditional.
AGREEMENT_SOURCES = frozenset(
    {
        "process_allgather",
        "gather_headers",
        "gather_confirms",
        "gather_checks",
    }
)

# Always host-local, whatever their arguments.
_TAINT_CALLS = frozenset({"next", "process_index", "local_devices"})

# Traced-branch primitives whose callables run per-device.
_TRACED_BRANCH_CALLS = frozenset({"cond", "switch"})


def _call_last(call: ast.Call) -> Optional[str]:
    return last_component(dotted_name(call.func))


def _iter_own_statements(body: Sequence[ast.stmt]) -> Iterator[ast.stmt]:
    """Statements of a function body, recursing into compound bodies
    but never into nested def/class/lambda (other call stacks)."""
    for stmt in body:
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        yield stmt
        for field in ("body", "orelse", "finalbody"):
            inner = getattr(stmt, field, None)
            if inner:
                yield from _iter_own_statements(inner)
        for handler in getattr(stmt, "handlers", ()):
            yield from _iter_own_statements(handler.body)


def _expr_calls(expr: ast.AST) -> Iterator[ast.Call]:
    """Calls inside one expression, lambda bodies excluded — a lambda
    runs later, on whatever stack calls it (the traced-branch check
    inspects `lax.cond` callables explicitly)."""
    stack: List[ast.AST] = [expr]
    while stack:
        sub = stack.pop()
        if isinstance(sub, ast.Lambda) and sub is not expr:
            continue
        if isinstance(sub, ast.Call):
            yield sub
        stack.extend(ast.iter_child_nodes(sub))


def _agreed_iterable(it: ast.AST, taint: "_Taint") -> bool:
    """True for bounded, congruent iteration: ``range`` over untainted
    bounds, or ``enumerate``/``sorted``/``reversed`` over values that
    PROVABLY derive from an agreement step. A parameter stream wrapped
    in ``enumerate(windows)`` is still per-process data whose length
    can diverge — the wrapper must not launder it."""
    if not isinstance(it, ast.Call) or taint.is_tainted_expr(it):
        return False
    last = _call_last(it)
    if last == "range":
        return True
    if last in ("enumerate", "sorted", "reversed"):
        return all(
            sub.id in taint.agreed
            for arg in it.args
            for sub in ast.walk(arg)
            if isinstance(sub, ast.Name)
        )
    return False


class _Taint:
    """Per-function name classification: tainted = host-local."""

    def __init__(self, fn: ast.AST) -> None:
        self.tainted: Set[str] = set()
        # Names PROVABLY derived from an agreement step (gathered
        # data): the only values sanctioned to bound a collective-
        # bearing loop through enumerate/sorted wrappers.
        self.agreed: Set[str] = set()
        # Parameters are implicitly agreed by NOT being tainted — the
        # config-contract default; no explicit set needed.
        # Two passes: simple forward propagation reaches fixpoint on
        # real protocol code (assignment chains, loop-carried names).
        for _ in range(2):
            self._scan(fn.body)

    def is_tainted_expr(self, expr: Optional[ast.AST]) -> bool:
        if expr is None:
            return False
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in self.tainted:
                return True
            if isinstance(sub, ast.Call):
                last = _call_last(sub)
                if last in _TAINT_CALLS:
                    return True
        return False

    def _taint_target(self, target: ast.AST) -> None:
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name):
                self.tainted.add(sub.id)

    def _scan(self, body: Sequence[ast.stmt]) -> None:
        for stmt in _iter_own_statements(body):
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = stmt.value
                if value is None:
                    continue
                targets = (
                    stmt.targets
                    if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                # Agreement results override taint — `rows =
                # gather_headers(...)`, including wrapped forms like
                # `np.asarray(process_allgather(...)).reshape(...)`:
                # tainted operands went INTO the collective, but the
                # gather IS the agreement step and its output is
                # identical everywhere.
                if any(
                    isinstance(sub, ast.Call)
                    and _call_last(sub) in AGREEMENT_SOURCES
                    for sub in ast.walk(value)
                ) or (
                    # Propagation: derived purely from agreed names
                    # (`live = peers[peers[:, 0] >= 0]`) stays agreed.
                    self.agreed
                    and all(
                        sub.id in self.agreed
                        for sub in ast.walk(value)
                        if isinstance(sub, ast.Name)
                    )
                ):
                    for t in targets:
                        for sub in ast.walk(t):
                            if isinstance(sub, ast.Name):
                                self.tainted.discard(sub.id)
                                self.agreed.add(sub.id)
                    continue
                if self.is_tainted_expr(value) or (
                    isinstance(stmt, ast.AugAssign)
                    and self.is_tainted_expr(stmt.target)
                ):
                    for t in targets:
                        self._taint_target(t)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                # Iterating a data stream yields host-local items; only
                # range/enumerate/sorted over agreed values stay agreed.
                if not _agreed_iterable(stmt.iter, self):
                    self._taint_target(stmt.target)
            elif isinstance(stmt, ast.Try):
                for handler in stmt.handlers:
                    if handler.name:
                        self.tainted.add(handler.name)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if item.optional_vars is not None and self.is_tainted_expr(
                        item.context_expr
                    ):
                        self._taint_target(item.optional_vars)


def _branch_terminates(body: Sequence[ast.stmt]) -> bool:
    """True when the branch body always exits the linear flow (its last
    reachable statement is return/raise/break/continue)."""
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Return, ast.Raise, ast.Break, ast.Continue)):
        return True
    if isinstance(last, ast.If):
        return (
            bool(last.orelse)
            and _branch_terminates(last.body)
            and _branch_terminates(last.orelse)
        )
    return False


def _src(ctx: Any, node: ast.AST) -> str:
    try:
        text = ast.get_source_segment(ctx.text, node)
    except Exception:  # pragma: no cover — best-effort label
        text = None
    if not text:
        return "<predicate>"
    text = " ".join(text.split())
    return text if len(text) <= 60 else text[:57] + "..."


class _FnWalker:
    """Lexical governance walk over one function body."""

    def __init__(self, rel: str, ctx: Any, fn: ast.AST, qual: str) -> None:
        self.rel = rel
        self.ctx = ctx
        self.fn = fn
        self.qual = qual
        self.taint = _Taint(fn)
        self.findings: List[Finding] = []
        self.order: List[Tuple[int, str]] = []  # (line, op)
        self.local_defs: Dict[str, ast.AST] = {
            n.name: n
            for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        }

    def run(self) -> None:
        self._walk(self.fn.body, governing=[], in_handler=False)

    # -- helpers ------------------------------------------------------------

    def _collectives_in_expr(self, expr: ast.AST) -> List[ast.Call]:
        out = []
        for call in _expr_calls(expr):
            if _call_last(call) in LOCKSTEP_OPS:
                out.append(call)
        return out

    def _callable_has_collective(self, expr: ast.AST) -> Optional[str]:
        """Collective op name inside a branch callable (lambda body or
        a same-function nested def referenced by name), or None."""
        body: Optional[ast.AST] = None
        if isinstance(expr, ast.Lambda):
            body = expr.body
        elif isinstance(expr, ast.Name) and expr.id in self.local_defs:
            body = self.local_defs[expr.id]
        if body is None:
            return None
        for sub in ast.walk(body):
            if (
                isinstance(sub, ast.Call)
                and _call_last(sub) in LOCKSTEP_OPS
            ):
                return _call_last(sub)
        return None

    def _note_collective(
        self,
        call: ast.Call,
        governing: List[Tuple[ast.AST, int, bool]],
        in_handler: bool,
    ) -> None:
        op = _call_last(call)
        assert op is not None
        self.order.append((call.lineno, op))
        if in_handler:
            self.findings.append(
                Finding(
                    NAME,
                    CODE,
                    self.rel,
                    call.lineno,
                    f"lockstep collective `{op}` inside an except "
                    "handler: peers that did not raise never reach it "
                    "— one-sided divergence strands them; encode the "
                    "failure into the next agreement step (width −2 / "
                    "payload-confirm) and raise on every process "
                    "together",
                )
            )
            return
        for test, line, force in governing:
            if force or self.taint.is_tainted_expr(test):
                self.findings.append(
                    Finding(
                        NAME,
                        CODE,
                        self.rel,
                        call.lineno,
                        f"lockstep collective `{op}` is governed by a "
                        f"branch on host-local state (`{_src(self.ctx, test)}` "
                        f"at line {line}): a process whose local data "
                        "takes the other side skips the collective and "
                        "strands every peer — derive the predicate "
                        "from a prior agreement step (gathered header/"
                        "confirm data) or issue the collective "
                        "unconditionally",
                    )
                )
                return  # one finding per collective site

    def _scan_stmt_exprs(
        self,
        exprs: Iterable[Optional[ast.AST]],
        governing: List[Tuple[ast.AST, int, bool]],
        in_handler: bool,
    ) -> None:
        for expr in exprs:
            if expr is None:
                continue
            for call in _expr_calls(expr):
                last = _call_last(call)
                if last in LOCKSTEP_OPS:
                    self._note_collective(call, governing, in_handler)
                elif last in _TRACED_BRANCH_CALLS:
                    for arg in call.args:
                        op = self._callable_has_collective(arg)
                        if op is not None:
                            self.findings.append(
                                Finding(
                                    NAME,
                                    CODE,
                                    self.rel,
                                    call.lineno,
                                    f"collective `{op}` inside a "
                                    f"`lax.{last}` branch callable: the "
                                    "traced predicate selects the branch "
                                    "per device, so devices disagree on "
                                    "whether the collective runs — hoist "
                                    "it above the cond (the pod dense "
                                    "program's unconditional all_gather "
                                    "shape)",
                                )
                            )

    # -- walk ---------------------------------------------------------------

    def _walk(
        self,
        body: Sequence[ast.stmt],
        governing: List[Tuple[ast.AST, int, bool]],
        in_handler: bool,
    ) -> None:
        governing = list(governing)
        for stmt in body:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # analyzed as their own functions
            if isinstance(stmt, ast.If):
                self._scan_stmt_exprs([stmt.test], governing, in_handler)
                inner = governing + [(stmt.test, stmt.lineno, False)]
                self._walk(stmt.body, inner, in_handler)
                self._walk(stmt.orelse, inner, in_handler)
                # One-sided exit: the test governs everything after.
                if _branch_terminates(stmt.body) or (
                    stmt.orelse and _branch_terminates(stmt.orelse)
                ):
                    governing.append((stmt.test, stmt.lineno, False))
            elif isinstance(stmt, ast.While):
                self._scan_stmt_exprs([stmt.test], governing, in_handler)
                is_const_true = (
                    isinstance(stmt.test, ast.Constant)
                    and stmt.test.value is True
                )
                inner = governing + (
                    []
                    if is_const_true
                    else [(stmt.test, stmt.lineno, False)]
                )
                self._walk(stmt.body, inner, in_handler)
                self._walk(stmt.orelse, governing, in_handler)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_stmt_exprs([stmt.iter], governing, in_handler)
                # A loop over per-process data governs its body's
                # collectives: stream lengths diverge across processes,
                # so trip counts do too (the exact deadlock the synced
                # streams' while-True + liveness codes exist to avoid).
                inner = governing + (
                    []
                    if _agreed_iterable(stmt.iter, self.taint)
                    else [(stmt.iter, stmt.lineno, True)]
                )
                self._walk(stmt.body, inner, in_handler)
                self._walk(stmt.orelse, governing, in_handler)
            elif isinstance(stmt, ast.Try):
                self._walk(stmt.body, governing, in_handler)
                for handler in stmt.handlers:
                    self._walk(handler.body, governing, in_handler=True)
                self._walk(stmt.orelse, governing, in_handler)
                self._walk(stmt.finalbody, governing, in_handler)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._scan_stmt_exprs(
                    [item.context_expr for item in stmt.items],
                    governing,
                    in_handler,
                )
                self._walk(stmt.body, governing, in_handler)
            else:
                self._scan_stmt_exprs(
                    [
                        v
                        for v in ast.iter_child_nodes(stmt)
                        if isinstance(v, ast.expr)
                    ],
                    governing,
                    in_handler,
                )


def _functions(tree: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """Every function at any nesting depth, with a qualified name —
    including defs inside compound statements (a kernel builder defining
    its mirror program under ``if mirror:`` is still protocol code)."""

    def visit(node: ast.AST, prefix: str) -> Iterator[Tuple[str, ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield qual, child
                yield from visit(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, f"{prefix}{child.name}.")
            elif isinstance(child, (ast.stmt, ast.excepthandler)):
                yield from visit(child, prefix)

    yield from visit(tree, "")


def _analyze_file(
    rel: str, ctx: Any
) -> Tuple[List[Finding], Dict[str, List[str]]]:
    findings: List[Finding] = []
    orders: Dict[str, List[str]] = {}
    for qual, fn in _functions(ctx.tree):
        walker = _FnWalker(rel, ctx, fn, qual)
        walker.run()
        findings.extend(walker.findings)
        if walker.order:
            orders[f"{rel}::{qual}"] = [
                op for _, op in sorted(walker.order)
            ]
    return findings, orders


def collective_order(project: Project) -> Dict[str, List[str]]:
    """Per protocol function: its lockstep collective sequence in
    source order — the payload ``--collective-order`` emits and
    docs/CONCURRENCY.md embeds (no line numbers: the doc must not
    churn on unrelated edits)."""
    out: Dict[str, List[str]] = {}
    for top in project.rule_paths(NAME, DEFAULT_PATHS):
        for rel in project.walk(top):
            ctx = project.file(rel)
            if ctx is None or ctx.tree is None:
                continue
            _, orders = _analyze_file(rel, ctx)
            out.update(orders)
    return out


class CollectiveCongruenceRule:
    name = NAME
    code = CODE
    summary = (
        "lockstep collectives (device psum/all_gather/ppermute, host "
        "allgathers, podstream header/confirm steps) must not be "
        "governed by branches on host-local state"
    )
    project_wide = False

    def check(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for top in project.rule_paths(NAME, DEFAULT_PATHS):
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                file_findings, _ = _analyze_file(rel, ctx)
                findings.extend(file_findings)
        return findings


RULE = CollectiveCongruenceRule()
