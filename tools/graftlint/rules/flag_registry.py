"""GL004 flag-registry: CLI flags, config fields, and docs stay in sync.

The flag surface is the operational API: a flag defined in
``cli/main.py`` but absent from ``utils/config.py``'s dataclasses (and
never read off ``args``) is dead weight; a dataclass field without a
flag is unreachable config; and an undocumented flag — or documentation
for a flag that no longer exists — is how operators end up cargo-culting
invocations out of old logs. Three-way sync, checked statically:

1. every ``add_argument("--flag")`` in the config/CLI modules must bind
   to a config dataclass field OR be consumed (``args.<dest>`` /
   ``getattr(args, "<dest>")``) in the CLI module;
2. every config dataclass field must be settable by some flag;
3. every defined flag must appear (as a ``--flag`` literal) in README
   or docs/, and every ``--flag`` token in README/docs must be defined
   by the CLI, the config module, or a script's argparse.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint.engine import FileCtx, Finding, Project

NAME = "flag-registry"
CODE = "GL004"

DEFAULT_CONFIG_MODULE = "spark_examples_tpu/utils/config.py"
DEFAULT_CLI_MODULE = "spark_examples_tpu/cli/main.py"
DEFAULT_SCRIPT_PATHS = ("scripts", "tools")
DEFAULT_DOC_PATHS = ("README.md", "docs")
DEFAULT_CONFIG_CLASSES = ("GenomicsConfig", "PcaConfig")

# A long-option token in prose: --flag, --flag-name. The lookarounds
# reject --xla_force_... style env-flag prose (underscore continues the
# token) and mid-word dashes.
_DOC_FLAG = re.compile(r"(?<![\w-])--([a-z][a-z0-9]*(?:-[a-z0-9]+)*)(?![\w-])")


def _add_argument_flags(
    ctx: Optional[FileCtx],
) -> List[Tuple[str, str, int, bool]]:
    """(flag, dest, line, bool_optional) for every add_argument call
    defining a long option."""
    out: List[Tuple[str, str, int, bool]] = []
    if ctx is None or ctx.tree is None:
        return out
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
        ):
            continue
        longs = [
            a.value
            for a in node.args
            if isinstance(a, ast.Constant)
            and isinstance(a.value, str)
            and a.value.startswith("--")
        ]
        if not longs:
            continue
        dest = None
        bool_optional = False
        for kw in node.keywords:
            if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                dest = kw.value.value
            if kw.arg == "action":
                src = ast.unparse(kw.value)
                bool_optional = "BooleanOptionalAction" in src
        if dest is None:
            dest = longs[0].lstrip("-").replace("-", "_")
        for flag in longs:
            out.append((flag, dest, node.lineno, bool_optional))
    return out


def _dataclass_fields(
    ctx: Optional[FileCtx], class_names: Iterable[str]
) -> Set[str]:
    fields: Set[str] = set()
    if ctx is None or ctx.tree is None:
        return fields
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef) and node.name in class_names:
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    fields.add(stmt.target.id)
    return fields


def _consumed_dests(ctx: Optional[FileCtx]) -> Set[str]:
    """Names read off an ``args`` namespace in the CLI module."""
    used: Set[str] = set()
    if ctx is None or ctx.tree is None:
        return used
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "args"
        ):
            used.add(node.attr)
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "getattr"
            and len(node.args) >= 2
            and isinstance(node.args[0], ast.Name)
            and node.args[0].id == "args"
            and isinstance(node.args[1], ast.Constant)
        ):
            used.add(node.args[1].value)
    return used


class FlagRegistryRule:
    name = NAME
    code = CODE
    summary = (
        "CLI flags <-> config dataclass fields <-> README/docs entries "
        "stay a closed, synchronized registry"
    )
    project_wide = True

    def check(self, project: Project) -> Iterable[Finding]:
        cfg = project.config.get("rules", {}).get(NAME, {})
        config_module = cfg.get("config_module", DEFAULT_CONFIG_MODULE)
        cli_module = cfg.get("cli_module", DEFAULT_CLI_MODULE)
        script_paths = cfg.get("script_paths", list(DEFAULT_SCRIPT_PATHS))
        doc_paths = cfg.get("doc_paths", list(DEFAULT_DOC_PATHS))
        config_classes = cfg.get(
            "config_classes", list(DEFAULT_CONFIG_CLASSES)
        )
        doc_ignore = set(cfg.get("doc_ignore", ()))

        findings: List[Finding] = []
        config_ctx = project.file(config_module)
        cli_ctx = project.file(cli_module)
        config_flags = _add_argument_flags(config_ctx)
        cli_flags = _add_argument_flags(cli_ctx)
        fields = _dataclass_fields(config_ctx, config_classes)
        consumed = _consumed_dests(cli_ctx)

        defined: Dict[str, Tuple[str, int]] = {}
        for flags, rel in (
            (config_flags, config_module),
            (cli_flags, cli_module),
        ):
            for flag, dest, line, bool_optional in flags:
                defined[flag] = (rel, line)
                if bool_optional:
                    defined["--no-" + flag[2:]] = (rel, line)

        # 1. Defined flag -> config field or CLI consumption.
        for flags, rel in (
            (config_flags, config_module),
            (cli_flags, cli_module),
        ):
            for flag, dest, line, _ in flags:
                if dest not in fields and dest not in consumed:
                    findings.append(
                        Finding(
                            NAME,
                            CODE,
                            rel,
                            line,
                            f"flag {flag} (dest {dest!r}) binds to no "
                            "config dataclass field and is never read "
                            "off args in the CLI — dead flag",
                        )
                    )

        # 2. Config field -> some flag's dest.
        dests = {d for flags in (config_flags, cli_flags) for _, d, _, _ in flags}
        for field_name in sorted(fields - dests):
            findings.append(
                Finding(
                    NAME,
                    CODE,
                    config_module,
                    _line_of(config_ctx, field_name),
                    f"config field {field_name!r} has no CLI flag — "
                    "unreachable configuration",
                )
            )

        # Gather script-defined flags (validate_trace etc.) for the
        # docs->defined direction only.
        script_defined: Set[str] = set()
        for top in script_paths:
            for rel in project.walk(top):
                for flag, _, _, bool_optional in _add_argument_flags(
                    project.file(rel)
                ):
                    script_defined.add(flag)
                    if bool_optional:
                        script_defined.add("--no-" + flag[2:])

        # 3a. Defined flag (config/CLI surface) -> documented.
        doc_tokens: Dict[str, Tuple[str, int]] = {}
        for top in doc_paths:
            for rel in project.walk(top, suffixes=(".md",)):
                ctx = project.file(rel)
                if ctx is None:
                    continue
                for lineno, line in enumerate(ctx.lines, 1):
                    for m in _DOC_FLAG.finditer(line):
                        doc_tokens.setdefault(
                            "--" + m.group(1), (rel, lineno)
                        )
        for flag in sorted(defined):
            if flag not in doc_tokens and flag not in doc_ignore:
                rel, line = defined[flag]
                findings.append(
                    Finding(
                        NAME,
                        CODE,
                        rel,
                        line,
                        f"flag {flag} is documented nowhere in "
                        "README.md or docs/ — undocumented operational "
                        "surface",
                    )
                )

        # 3b. Documented flag -> defined somewhere real.
        all_defined = set(defined) | script_defined
        for flag in sorted(doc_tokens):
            if flag not in all_defined and flag not in doc_ignore:
                rel, line = doc_tokens[flag]
                findings.append(
                    Finding(
                        NAME,
                        CODE,
                        rel,
                        line,
                        f"documented flag {flag} is defined by no "
                        "argparse surface (CLI, config, scripts) — "
                        "stale documentation",
                    )
                )
        return findings


def _line_of(ctx: Optional[FileCtx], needle: str) -> int:
    if ctx is not None:
        for lineno, line in enumerate(ctx.lines, 1):
            if needle in line:
                return lineno
    return 1


RULE = FlagRegistryRule()
