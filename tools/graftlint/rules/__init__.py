"""graftlint rule registry.

A rule is an object with ``name`` (pragma id), ``code`` (stable GLxxx),
``summary``, optional ``project_wide`` (cross-file contract rules run on
their full configured scope regardless of CLI path narrowing), and
``check(project) -> Iterable[Finding]``.
"""

from tools.graftlint.rules.atomic_commit import RULE as ATOMIC_COMMIT
from tools.graftlint.rules.collective_congruence import (
    RULE as COLLECTIVE_CONGRUENCE,
)
from tools.graftlint.rules.deadlock_order import RULE as DEADLOCK_ORDER
from tools.graftlint.rules.donation_aliasing import RULE as DONATION_ALIASING
from tools.graftlint.rules.dtype_discipline import RULE as DTYPE_DISCIPLINE
from tools.graftlint.rules.fencing_discipline import (
    RULE as FENCING_DISCIPLINE,
)
from tools.graftlint.rules.flag_registry import RULE as FLAG_REGISTRY
from tools.graftlint.rules.guarded_fields import RULE as GUARDED_FIELDS
from tools.graftlint.rules.jit_purity import RULE as JIT_PURITY
from tools.graftlint.rules.journal_compat import RULE as JOURNAL_COMPAT
from tools.graftlint.rules.lock_discipline import RULE as LOCK_DISCIPLINE
from tools.graftlint.rules.native_gil import RULE as NATIVE_GIL
from tools.graftlint.rules.resilience_routing import RULE as RESILIENCE_ROUTING
from tools.graftlint.rules.retrace_discipline import (
    RULE as RETRACE_DISCIPLINE,
)
from tools.graftlint.rules.span_contract import RULE as SPAN_CONTRACT

ALL_RULES = [
    JIT_PURITY,
    DTYPE_DISCIPLINE,
    SPAN_CONTRACT,
    FLAG_REGISTRY,
    RESILIENCE_ROUTING,
    NATIVE_GIL,
    LOCK_DISCIPLINE,
    DEADLOCK_ORDER,
    GUARDED_FIELDS,
    COLLECTIVE_CONGRUENCE,
    DONATION_ALIASING,
    RETRACE_DISCIPLINE,
    ATOMIC_COMMIT,
    FENCING_DISCIPLINE,
    JOURNAL_COMPAT,
]

__all__ = ["ALL_RULES"]
