"""GL006 native-gil: the GIL-released native core never touches CPython.

``native/genomics_native.cpp`` is loaded with ``ctypes.CDLL`` and every
exported function runs with the GIL **released** (ctypes drops it for
the duration of the foreign call — that is exactly why the multi-worker
block builders scale). Touching the Python C-API from such a region
(``PyObject``, ``PyGILState_*``, ``Py_*`` anything, or including
``Python.h`` at all) is undefined behavior unless the GIL is explicitly
re-acquired — a crash that only reproduces under thread pressure, the
worst kind. The native core is therefore *pure C++ by contract*: arrays
in, arrays out, via raw pointers. This rule scans the source (comments
and string literals stripped) and flags any CPython identifier.
"""

from __future__ import annotations

import re
from typing import Iterable, List

from tools.graftlint.engine import Finding, Project

NAME = "native-gil"
CODE = "GL006"

DEFAULT_PATHS = ("spark_examples_tpu/native",)

_CAPI = re.compile(r"\bPy[A-Z_][A-Za-z0-9_]*|\bPython\.h\b")


def strip_comments_and_strings(src: str) -> str:
    """Blank out //, /* */ comments and "..."/'...' literals, keeping
    line structure so findings carry real line numbers."""
    out: List[str] = []
    i, n = 0, len(src)
    mode = None  # None | "line" | "block" | '"' | "'"
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if mode is None:
            if c == "/" and nxt == "/":
                mode = "line"
                out.append("  ")
                i += 2
                continue
            if c == "/" and nxt == "*":
                mode = "block"
                out.append("  ")
                i += 2
                continue
            if c in ('"', "'"):
                mode = c
                out.append(" ")
                i += 1
                continue
            out.append(c)
        else:
            if c == "\n":
                out.append("\n")
                if mode == "line":
                    mode = None
                i += 1
                continue
            if mode == "block" and c == "*" and nxt == "/":
                mode = None
                out.append("  ")
                i += 2
                continue
            if mode in ('"', "'") and c == "\\":
                # Preserve an escaped newline: blanking it would merge
                # two source lines and shift every later finding (and
                # pragma lookup) off by one.
                out.append(" \n" if nxt == "\n" else "  ")
                i += 2
                continue
            if mode in ('"', "'") and c == mode:
                mode = None
            out.append(" ")
        i += 1
    return "".join(out)


class NativeGilRule:
    name = NAME
    code = CODE
    summary = (
        "the ctypes-loaded (GIL-released) native core stays pure C++: "
        "no Python C-API identifiers, no Python.h"
    )
    project_wide = False

    def check(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for top in project.rule_paths(NAME, DEFAULT_PATHS):
            for rel in project.walk(
                top, suffixes=(".cpp", ".cc", ".h", ".hpp")
            ):
                ctx = project.file(rel)
                if ctx is None:
                    continue
                stripped = strip_comments_and_strings(ctx.text)
                for lineno, line in enumerate(
                    stripped.splitlines(), 1
                ):
                    for m in _CAPI.finditer(line):
                        findings.append(
                            Finding(
                                NAME,
                                CODE,
                                rel,
                                lineno,
                                f"Python C-API touch {m.group(0)!r} in "
                                "a GIL-released region: every export "
                                "here runs under ctypes with the GIL "
                                "dropped — CPython calls are undefined "
                                "behavior unless PyGILState is "
                                "re-acquired, and this core is pure-"
                                "C++ by contract",
                            )
                        )
        return findings


RULE = NativeGilRule()
