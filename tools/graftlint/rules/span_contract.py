"""GL003 span-contract: telemetry emission and schema can never diverge.

``scripts/validate_trace.py`` is the runtime schema gate for telemetry
artifacts: it pins the closed ``ingest.*`` span set and the wire/ingest
metric contracts (transport/mode labels, histogram triplets). But the
gate only fires on artifacts a run happened to emit — rename a span at
the emission site and every artifact simply stops carrying it, forever
green. This rule closes the loop statically:

- every ``span(...)`` call must be used as a context manager (``with
  obs.span(...)``): a bare open/close pair leaks the span on any
  exception path and silently corrupts the trace nesting;
- the set of ``ingest.*`` span name literals in the tree must equal
  ``validate_trace._INGEST_SPANS`` **exactly** (both directions — an
  emitted name the schema does not know, or a schema name nothing emits,
  is a finding);
- every metric name in the wire/ingest contracts must be registered
  somewhere, and its registration must chain the label the schema
  requires (``transport=`` for wire, ``mode=`` for ingest).

The schema is imported from ``scripts/validate_trace.py`` itself — one
name-set source, shared, so the two sides provably cannot drift (the
meta-test in tests/test_graftlint.py asserts this sharing).
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint.astutil import call_name, literal_str
from tools.graftlint.engine import Finding, Project

NAME = "span-contract"
CODE = "GL003"

DEFAULT_PATHS = ("spark_examples_tpu",)
SCHEMA_SCRIPT = "scripts/validate_trace.py"

_REGISTRATION_ATTRS = ("counter", "gauge", "histogram")


def load_schema(root: str) -> Optional[Any]:
    """Import scripts/validate_trace.py from the project root (stdlib-
    only module; None when absent, e.g. in fixture mini-projects)."""
    path = os.path.join(root, SCHEMA_SCRIPT)
    if not os.path.exists(path):
        return None
    spec = importlib.util.spec_from_file_location(
        "graftlint_validate_trace", path
    )
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _span_calls(tree: ast.AST) -> List[Tuple[ast.Call, bool]]:
    """(call, used_as_context_manager) for every ``*.span(...)`` call."""
    with_items: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                with_items.add(id(item.context_expr))
    out: List[Tuple[ast.Call, bool]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        cname = call_name(node)
        if cname is None:
            # e.g. get_tracer().span(...): dotted_name can't flatten a
            # call in the chain; look at the raw attribute.
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "span"
            ):
                out.append((node, id(node) in with_items))
            continue
        if cname == "span" or cname.endswith(".span"):
            out.append((node, id(node) in with_items))
    return out


def extract_span_names(project: Project) -> Dict[str, List[Tuple[str, int]]]:
    """Literal span name -> [(path, line), ...] across the scope."""
    names: Dict[str, List[Tuple[str, int]]] = {}
    for top in project.rule_paths(NAME, DEFAULT_PATHS):
        for rel in project.walk(top):
            ctx = project.file(rel)
            if ctx is None or ctx.tree is None:
                continue
            for call, _ in _span_calls(ctx.tree):
                lit = literal_str(call.args[0]) if call.args else None
                if lit is not None:
                    names.setdefault(lit, []).append((rel, call.lineno))
    return names


def extract_instant_names(
    project: Project,
) -> Dict[str, List[Tuple[str, int]]]:
    """Literal instant name -> [(path, line), ...] across the scope.

    The ``pod.*`` family is emitted through ``instant(...)``, not
    ``span(...)`` — the timestamp pairs are points, not durations — so
    the closed-set cross-check needs its own call scan."""
    names: Dict[str, List[Tuple[str, int]]] = {}
    for top in project.rule_paths(NAME, DEFAULT_PATHS):
        for rel in project.walk(top):
            ctx = project.file(rel)
            if ctx is None or ctx.tree is None:
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                cname = call_name(node)
                is_instant = (
                    cname == "instant"
                    or (cname is not None and cname.endswith(".instant"))
                    or (
                        cname is None
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "instant"
                    )
                )
                if not is_instant:
                    continue
                lit = literal_str(node.args[0]) if node.args else None
                if lit is not None:
                    names.setdefault(lit, []).append((rel, node.lineno))
    return names


def extract_metric_registrations(
    project: Project,
) -> Dict[str, List[Tuple[str, int, str, Set[str]]]]:
    """Metric name -> [(path, line, kind, chained label kwargs)]."""
    regs: Dict[str, List[Tuple[str, int, str, Set[str]]]] = {}
    for top in project.rule_paths(NAME, DEFAULT_PATHS):
        for rel in project.walk(top):
            ctx = project.file(rel)
            if ctx is None or ctx.tree is None:
                continue
            # Registration call id -> labels kwargs chained onto it.
            labels_of: Dict[int, Set[str]] = {}
            for node in ast.walk(ctx.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"
                    and isinstance(node.func.value, ast.Call)
                ):
                    labels_of[id(node.func.value)] = {
                        kw.arg for kw in node.keywords if kw.arg
                    }
            for node in ast.walk(ctx.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REGISTRATION_ATTRS
                ):
                    continue
                lit = literal_str(node.args[0]) if node.args else None
                if lit is None:
                    continue
                regs.setdefault(lit, []).append(
                    (
                        rel,
                        node.lineno,
                        node.func.attr,
                        labels_of.get(id(node), set()),
                    )
                )
    return regs


def _schema_line(project: Project, needle: str) -> int:
    ctx = project.file(SCHEMA_SCRIPT)
    if ctx is not None:
        for lineno, line in enumerate(ctx.lines, 1):
            if needle in line:
                return lineno
    return 1


class SpanContractRule:
    name = NAME
    code = CODE
    summary = (
        "spans are context-managed; ingest.*/job.*/gramian.sparse.*/"
        "gramian.sketch.*/pairhmm.* span names, pod.* instant names, "
        "and wire/ingest/serving/sparse/sketch metric registrations "
        "match scripts/validate_trace.py exactly"
    )
    project_wide = True

    def check(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        # 1. Context-manager discipline at every span call site.
        for top in project.rule_paths(NAME, DEFAULT_PATHS):
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                for call, managed in _span_calls(ctx.tree):
                    if not managed:
                        findings.append(
                            Finding(
                                NAME,
                                CODE,
                                rel,
                                call.lineno,
                                "span opened outside a `with` block: a "
                                "bare open/close pair leaks the span on "
                                "any exception path and corrupts trace "
                                "nesting",
                            )
                        )
        # 2-3. Name-set cross-check against the runtime schema — the
        # same closed-set discipline for each prefixed span family
        # (ingest sub-phases, serving job tier).
        schema = load_schema(project.root)
        if schema is None:
            return findings
        span_names = extract_span_names(project)
        for prefix, attr in (
            ("ingest.", "_INGEST_SPANS"),
            ("job.", "_JOB_SPANS"),
            ("gramian.sparse.", "_SPARSE_SPANS"),
            ("gramian.sketch.", "_SKETCH_SPANS"),
            ("pairhmm.", "_PAIRHMM_SPANS"),
        ):
            emitted = {n for n in span_names if n.startswith(prefix)}
            schema_spans: Set[str] = set(getattr(schema, attr, set()))
            for name in sorted(emitted - schema_spans):
                rel, line = span_names[name][0]
                findings.append(
                    Finding(
                        NAME,
                        CODE,
                        rel,
                        line,
                        f"span {name!r} is not in validate_trace."
                        f"{attr} — artifacts carrying it fail the "
                        "runtime schema gate; add it to the schema in "
                        "the same change",
                    )
                )
            for name in sorted(schema_spans - emitted):
                findings.append(
                    Finding(
                        NAME,
                        CODE,
                        SCHEMA_SCRIPT,
                        _schema_line(project, f'"{name}"'),
                        f"schema span {name!r} is emitted nowhere in "
                        "the tree (literal scan) — dead schema entries "
                        "hide renames; remove it or restore the "
                        "emission",
                    )
                )
        # 3b. The pod.* instant family, both directions — same closed-
        # set discipline, over instant() calls instead of span() calls
        # (merge_pod_trace.py keys its clock alignment on these names).
        instant_names = extract_instant_names(project)
        pod_schema: Set[str] = set(getattr(schema, "_POD_INSTANTS", set()))
        pod_emitted = {
            n for n in instant_names if n.startswith("pod.")
        }
        for name in sorted(pod_emitted - pod_schema):
            rel, line = instant_names[name][0]
            findings.append(
                Finding(
                    NAME,
                    CODE,
                    rel,
                    line,
                    f"instant {name!r} is not in validate_trace."
                    "_POD_INSTANTS — artifacts carrying it fail the "
                    "runtime schema gate; add it to the schema in the "
                    "same change",
                )
            )
        for name in sorted(pod_schema - pod_emitted):
            findings.append(
                Finding(
                    NAME,
                    CODE,
                    SCHEMA_SCRIPT,
                    _schema_line(project, f'"{name}"'),
                    f"schema pod instant {name!r} is emitted nowhere "
                    "in the tree (literal scan) — dead schema entries "
                    "hide renames; remove it or restore the emission",
                )
            )
        # 4-5. Metric contract: required names registered, with the
        # labels the schema's sample checks demand.
        regs = extract_metric_registrations(project)
        required: Dict[str, Optional[str]] = {
            name: "transport"
            for name in getattr(schema, "_WIRE_COUNTERS", ())
        }
        wire_hist = getattr(schema, "_WIRE_HISTOGRAM", None)
        if wire_hist:
            required[wire_hist] = "transport"
        for name in getattr(schema, "_INGEST_COUNTERS", ()):
            required[name] = "mode"
        ingest_hist = getattr(schema, "_INGEST_HISTOGRAM", None)
        if ingest_hist:
            required[ingest_hist] = "mode"
        # Serving/resilience counters: the schema names the label each
        # sample must carry (breaker probes, job outcomes, sheds).
        required.update(getattr(schema, "_LABELED_COUNTERS", {}))
        # Plain serving histograms and gauges: registration required,
        # no label contract (None = skip the label check).
        for name in getattr(schema, "_SERVING_HISTOGRAMS", ()):
            required[name] = None
        for name in getattr(schema, "_SERVING_GAUGES", ()):
            required[name] = None
        for name, label in sorted(required.items()):
            sites = regs.get(name)
            if not sites:
                findings.append(
                    Finding(
                        NAME,
                        CODE,
                        SCHEMA_SCRIPT,
                        _schema_line(project, f'"{name}"'),
                        f"schema metric {name!r} is registered nowhere "
                        "in the tree — the runtime contract it encodes "
                        "is dead",
                    )
                )
                continue
            for rel, line, _kind, labels in sites:
                if label is None:
                    continue
                if label not in labels:
                    findings.append(
                        Finding(
                            NAME,
                            CODE,
                            rel,
                            line,
                            f"metric {name!r} registration does not "
                            f"chain .labels({label}=...) — "
                            "validate_trace rejects its samples "
                            f"without the {label!r} label",
                        )
                    )
        return findings


RULE = SpanContractRule()
