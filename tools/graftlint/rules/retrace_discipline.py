"""GL012 retrace-discipline: geometry reaching executables must be bucketed.

``jax.jit`` caches one executable per (shapes, static args) key. The
sparse engine's whole perf story (the pow2 panel bucketing that closed
the r06 pod gap, the carrier buckets, the scan-chunk row padding)
hinges on every geometry-bearing value that reaches a jit entry being
ROUNDED through a registered bucket helper first: a raw per-window
Python int (``lens.size``, a local nnz, an unrounded width) reaching a
``static_argnames`` argument — or a shape-determining argument of the
panel/carrier builders — mints a fresh executable per distinct value,
and the bench measures XLA compilation instead of accumulation. No
tier-1 test asserts wall-clock, so the regression is silent; this rule
makes it a review-time failure.

Checked call sites, over ``ops/`` + ``parallel/``:

1. **jit entries with static_argnames** (``@partial(jax.jit,
   static_argnames=(...))`` defs and ``jax.jit(f, static_argnames=...)``
   assignment forms): every *geometry-named* static argument (``n``,
   ``n_bits``, ``rows``, ``width``, ``iters``, ``chunk``, ... — dtype/
   path/flag statics are exempt by name) must be **bucket-derived**;
2. **executable-keyed factories** (``@functools.lru_cache`` defs, e.g.
   the ``_sparse_tile_kernels`` compiled-kernel cache): their geometry
   parameters gate one compiled program per distinct value, exactly
   like a static arg;
3. **registered shape-bearing helpers** whose arguments become jit
   operand shapes: ``padded_carrier_matrix(n_rows=, k_bucket=)`` and
   ``_densify_window(..., width)``.

**Bucket-derived** (computed bottom-up over the calling function's
assignments): integer constants; calls to a registered bucket helper
(``dense_panel_width``, ``_carrier_bucket``, ``_pad_rows_for_scan``,
``_pow2_rows``, ``randomized_panel_width``, ``round_up_multiple``;
extendable via ``bucket_helpers`` in the rule config); the calling
function's own parameters (the caller owns the contract — its call
sites are checked in turn); arithmetic/`max`/`min`/`int()` over
bucket-derived values; and ``.shape``/``.size``/``len()`` only when the
subject is a function parameter or another operand of the same call
(an operand's shape is already part of the executable key). Everything
else — above all ``.size``/``.shape`` of stream-local window data — is
raw geometry and a finding.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.graftlint.astutil import dotted_name, last_component
from tools.graftlint.engine import Finding, Project

NAME = "retrace-discipline"
CODE = "GL012"

DEFAULT_PATHS = (
    "spark_examples_tpu/ops",
    "spark_examples_tpu/parallel",
)

DEFAULT_BUCKET_HELPERS = (
    "dense_panel_width",
    "_carrier_bucket",
    "_pad_rows_for_scan",
    "_pow2_rows",
    "pairhmm_bucket",
    "randomized_panel_width",
    "round_up_multiple",
)

# Shape-bearing helper arguments that become jit operand geometry:
# name -> (positional indices, keyword names) to check.
SHAPE_HELPERS: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {
    "padded_carrier_matrix": ((), ("n_rows", "k_bucket")),
    "_densify_window": ((3,), ("width",)),
}

# A static/factory parameter is geometry-bearing when one of its
# underscore-separated words is a size noun; dtype/path/flag statics
# stay exempt. Word matching, not substring (the GL007 lesson).
_GEOMETRY_WORDS = frozenset(
    {
        "n",
        "k",
        "v",
        "rows",
        "cols",
        "width",
        "widths",
        "bits",
        "len",
        "size",
        "count",
        "samples",
        "variants",
        "padded",
        "bucket",
        "chunk",
        "iters",
        "depth",
    }
)
_WORD_SPLIT = re.compile(r"[^a-zA-Z0-9]+")

# Numeric wrappers that preserve bucket-derivation (range/enumerate:
# bounded iteration over derived bounds stays derived).
_PASSTHROUGH_CALLS = frozenset(
    {"max", "min", "int", "abs", "range", "enumerate"}
)


def is_geometry_name(name: str) -> bool:
    return any(
        w in _GEOMETRY_WORDS for w in _WORD_SPLIT.split(name.lower()) if w
    )


def _static_names(call: ast.Call) -> Tuple[str, ...]:
    """static_argnames from a jit/pjit/partial call, else ()."""
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            val = kw.value
            if isinstance(val, ast.Constant) and isinstance(val.value, str):
                return (val.value,)
            if isinstance(val, (ast.Tuple, ast.List)):
                return tuple(
                    elt.value
                    for elt in val.elts
                    if isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)
                )
    return ()


def _jit_like(call: ast.Call) -> bool:
    return last_component(dotted_name(call.func)) in ("jit", "pjit", "partial")


def _lru_like(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        dec = dec.func
    return last_component(dotted_name(dec)) in ("lru_cache", "cache")


class _Entry:
    """One executable-keyed callable: which args carry geometry."""

    __slots__ = ("name", "kind", "positions", "keywords")

    def __init__(
        self,
        name: str,
        kind: str,
        positions: Tuple[int, ...],
        keywords: Tuple[str, ...],
    ) -> None:
        self.name = name
        self.kind = kind  # "static" | "factory" | "shape"
        self.positions = positions
        self.keywords = keywords


def _index_entries(trees: Sequence[ast.AST]) -> Dict[str, _Entry]:
    entries: Dict[str, _Entry] = {
        name: _Entry(name, "shape", pos, kws)
        for name, (pos, kws) in SHAPE_HELPERS.items()
    }

    def geometry_params(
        fn: ast.AST, only: Optional[Sequence[str]] = None
    ) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
        params = [a.arg for a in fn.args.args]
        names = [
            p
            for p in (only if only is not None else params)
            if p in params and is_geometry_name(p)
        ]
        return tuple(params.index(p) for p in names), tuple(names)

    for tree in trees:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call) and _jit_like(dec):
                        statics = _static_names(dec)
                        if statics:
                            pos, kws = geometry_params(node, statics)
                            if kws:
                                entries[node.name] = _Entry(
                                    node.name, "static", pos, kws
                                )
                    elif _lru_like(dec):
                        pos, kws = geometry_params(node)
                        if kws:
                            entries[node.name] = _Entry(
                                node.name, "factory", pos, kws
                            )
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if _jit_like(node.value):
                    statics = _static_names(node.value)
                    inner = node.value.args[0] if node.value.args else None
                    if statics and isinstance(inner, ast.Name):
                        # name = jax.jit(f, static_argnames=...): the
                        # static names index into f's signature, which
                        # this pass does not resolve — geometry-named
                        # statics are checked by NAME at call sites via
                        # keywords only.
                        geo = tuple(
                            s for s in statics if is_geometry_name(s)
                        )
                        if geo:
                            for tgt in node.targets:
                                if isinstance(tgt, ast.Name):
                                    entries[tgt.id] = _Entry(
                                        tgt.id, "static", (), geo
                                    )
    return entries


class _Derivation:
    """Bucket-derivation over one calling function."""

    def __init__(self, fn: ast.AST, helpers: Set[str]) -> None:
        self.helpers = helpers
        self.params = {
            a.arg
            for a in list(fn.args.args)
            + list(fn.args.posonlyargs)
            + list(fn.args.kwonlyargs)
        }
        self.derived: Set[str] = set(self.params)
        # Lambda parameters are parameters too (the
        # `lambda kk: principal_components(c, kk)` finish idiom).
        for node in ast.walk(fn):
            if isinstance(node, ast.Lambda):
                for a in node.args.args:
                    self.params.add(a.arg)
                    self.derived.add(a.arg)
        # Two forward passes over the function's assignments reach a
        # fixpoint on real accumulator code.
        assigns: List[Tuple[ast.AST, ast.AST]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    assigns.append((t, node.value))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                assigns.append((node.target, node.value))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                # A loop target over range/enumerate of bucket-derived
                # bounds is bounded, parameter-congruent iteration (the
                # fused retry-doubling shape); data-stream targets stay
                # raw.
                it = node.iter
                if (
                    isinstance(it, ast.Call)
                    and last_component(dotted_name(it.func))
                    in ("range", "enumerate")
                ):
                    assigns.append((node.target, it))
        assigns.sort(key=lambda tv: getattr(tv[1], "lineno", 0))
        for _ in range(2):
            for target, value in assigns:
                if isinstance(target, ast.Name):
                    if self.blessed(value, other_args=frozenset()):
                        self.derived.add(target.id)
                elif isinstance(target, ast.Tuple):
                    # Conservative: a tuple unpack blesses its targets
                    # only when the whole RHS is blessed.
                    if self.blessed(value, other_args=frozenset()):
                        for elt in target.elts:
                            if isinstance(elt, ast.Name):
                                self.derived.add(elt.id)

    def blessed(self, expr: ast.AST, other_args: frozenset) -> bool:
        if isinstance(expr, ast.Constant):
            return True
        if isinstance(expr, (ast.Tuple, ast.List)):
            return all(self.blessed(e, other_args) for e in expr.elts)
        if isinstance(expr, ast.Name):
            # UPPERCASE names are module constants by convention —
            # compile-time geometry (_DEF_ITERS, SCATTER_CHUNK_VARIANTS).
            return expr.id in self.derived or expr.id.isupper()
        if isinstance(expr, ast.BinOp):
            return self.blessed(expr.left, other_args) and self.blessed(
                expr.right, other_args
            )
        if isinstance(expr, ast.UnaryOp):
            return self.blessed(expr.operand, other_args)
        if isinstance(expr, ast.IfExp):
            return self.blessed(expr.body, other_args) and self.blessed(
                expr.orelse, other_args
            )
        if isinstance(expr, ast.Compare):
            return all(
                self.blessed(e, other_args)
                for e in [expr.left, *expr.comparators]
            )
        if isinstance(expr, ast.BoolOp):
            return all(self.blessed(v, other_args) for v in expr.values)
        if isinstance(expr, ast.Call):
            last = last_component(dotted_name(expr.func))
            if last in self.helpers:
                return True  # the bucket helper IS the blessing
            if last == "len":
                # len() of an array is raw geometry unless the subject's
                # shape already rides the executable key.
                return bool(expr.args) and self._shape_subject_ok(
                    expr.args[0], other_args
                )
            if last in _PASSTHROUGH_CALLS:
                return all(
                    self.blessed(a, other_args) for a in expr.args
                )
            return False
        if isinstance(expr, ast.Attribute):
            # x.size / x.shape: raw geometry unless the subject's shape
            # is already part of the executable key.
            if expr.attr in ("size", "shape"):
                return self._shape_subject_ok(expr.value, other_args)
            return self.blessed(expr.value, other_args)
        if isinstance(expr, ast.Subscript):
            return self.blessed(expr.value, other_args)
        return False

    def _shape_subject_ok(
        self, subject: ast.AST, other_args: frozenset
    ) -> bool:
        return (
            isinstance(subject, ast.Name)
            and (
                subject.id in self.params
                or subject.id in other_args
            )
        )


def _call_arg_names(call: ast.Call) -> frozenset:
    names = set()
    for a in call.args:
        if isinstance(a, ast.Name):
            names.add(a.id)
    for kw in call.keywords:
        if isinstance(kw.value, ast.Name):
            names.add(kw.value.id)
    return frozenset(names)


class RetraceDisciplineRule:
    name = NAME
    code = CODE
    summary = (
        "geometry reaching static args / executable-keyed factories / "
        "panel+carrier builders must come from the registered bucket "
        "helpers or compile-time constants, never raw per-window ints"
    )
    project_wide = False

    def check(self, project: Project) -> Iterable[Finding]:
        paths = project.rule_paths(NAME, DEFAULT_PATHS)
        cfg = project.config.get("rules", {}).get(NAME, {})
        helpers = set(DEFAULT_BUCKET_HELPERS) | set(
            cfg.get("bucket_helpers", ())
        )
        files: List[Tuple[str, ast.AST]] = []
        for top in paths:
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                files.append((rel, ctx.tree))
        entries = _index_entries([tree for _, tree in files])
        findings: List[Finding] = []
        for rel, tree in files:
            for node in ast.walk(tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    findings.extend(
                        self._check_fn(rel, node, entries, helpers)
                    )
        return findings

    def _check_fn(
        self,
        rel: str,
        fn: ast.AST,
        entries: Dict[str, _Entry],
        helpers: Set[str],
    ) -> List[Finding]:
        derivation = _Derivation(fn, helpers)
        findings: List[Finding] = []
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            entry = entries.get(last_component(dotted_name(call.func)) or "")
            if entry is None:
                continue
            other_args = _call_arg_names(call)
            checked: List[Tuple[str, ast.AST]] = []
            for pos in entry.positions:
                if pos < len(call.args):
                    checked.append((f"arg {pos}", call.args[pos]))
            for kw in call.keywords:
                if kw.arg in entry.keywords:
                    checked.append((kw.arg, kw.value))
            for label, expr in checked:
                if derivation.blessed(expr, other_args):
                    continue
                kind_txt = {
                    "static": "static (executable-key) argument",
                    "factory": "executable-cache factory argument",
                    "shape": "shape-determining argument",
                }[entry.kind]
                findings.append(
                    Finding(
                        NAME,
                        CODE,
                        rel,
                        call.lineno,
                        f"`{entry.name}(...)` {kind_txt} `{label}` is "
                        "raw per-call geometry: every distinct value "
                        "mints a fresh executable (silent retraces ate "
                        "the r06 pod win) — round it through a "
                        "registered bucket helper "
                        f"({', '.join(sorted(helpers))}) or derive it "
                        "from function parameters/constants",
                    )
                )
        return findings


RULE = RetraceDisciplineRule()
