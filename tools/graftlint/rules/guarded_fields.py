"""GL009 guarded-fields: lock-guarded attributes stay guarded everywhere.

Atomicity inference per class: if ANY write to ``self._x`` happens
while a class lock is held, the lock is evidently what makes ``_x``
coherent — so every other read or write of ``_x`` in the class must
also hold it (or be an intentional, pragma'd lock-free access — the
pragma policy and sanctioned examples live in docs/CONCURRENCY.md). The
half-guarded field is the classic Python race: the author locked the
writer, a later PR added a reader, and the GIL makes it pass every test
while torn multi-step updates stay observable in production.

What counts as a *write* (mutation coverage matters more than purity):

- direct stores: ``self._x = v``, ``self._x += v``, ``del self._x``;
- container stores through the attribute: ``self._x[k] = v``,
  ``del self._x[k]``;
- mutator method calls: ``self._x.append/pop/update/clear/...``;
- ``heapq`` mutations taking the attribute as first argument.

Guardedness is flow-sensitive (the must-held reaching-locks dataflow
over the function CFG), so ``with self._lock:`` blocks, the bounded
acquire/finally-release shape, and branches all resolve correctly.
``*_locked`` methods are seeded as holding the class locks (their
convention IS the precondition — GL007 proves the call sites).
``__init__``/``__new__``/``__post_init__`` are construction-phase and
exempt: no second thread can hold a reference yet. Underscore-private
attributes only — a public attribute is an API whose synchronization
contract belongs to its docstring, not to inference.

Attributes whose constructor-inferred class owns locks of its own
(``self._queue = AdmissionQueue(...)``) are *internally synchronized*
collaborators and exempt: calling their thread-safe, mutator-named API
(``pop``, ``discard``) lock-free is the design, and holding the outer
lock for it would only manufacture nesting GL008 then has to order.
"""

from __future__ import annotations

import ast
import os
from typing import FrozenSet, Iterable, List, Set, Tuple

from tools.graftlint.astutil import dotted_name
from tools.graftlint.classmodel import ScopeModel, scan_scope
from tools.graftlint.dataflow import (
    build_cfg,
    class_lock_keys,
    held_at_nodes,
    is_lock_name,
    make_resolver,
    node_scan_roots,
    walk_skip_nested,
)
from tools.graftlint.engine import Finding, Project

NAME = "guarded-fields"
CODE = "GL009"

DEFAULT_PATHS = (
    "spark_examples_tpu/serving",
    "spark_examples_tpu/arrays",
    "spark_examples_tpu/utils",
)

_CONSTRUCTION = frozenset({"__init__", "__new__", "__post_init__"})

_MUTATORS = frozenset(
    {
        "append",
        "appendleft",
        "add",
        "clear",
        "discard",
        "extend",
        "insert",
        "move_to_end",
        "pop",
        "popleft",
        "popitem",
        "remove",
        "reverse",
        "setdefault",
        "sort",
        "update",
        "write",
        "writelines",
    }
)
_HEAP_FNS = frozenset(
    {"heappush", "heappop", "heapify", "heappushpop", "heapreplace"}
)

# (attr, line, is_write)
Access = Tuple[str, int, bool]


def _is_self_attr(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _tracked(attr: str) -> bool:
    """Underscore-private, non-dunder, not itself a lock."""
    return (
        attr.startswith("_")
        and not attr.startswith("__")
        and not is_lock_name(attr)
    )


def _accesses(root: ast.AST) -> Iterable[Access]:
    """Classified self-attribute accesses inside one scan root."""
    writes: Set[int] = set()
    for sub in walk_skip_nested(root):
        if isinstance(sub, ast.Attribute) and _is_self_attr(sub):
            if isinstance(sub.ctx, (ast.Store, ast.Del)):
                writes.add(id(sub))
        elif isinstance(sub, ast.Subscript):
            # self._x[k] = v / del self._x[k]: the Attribute itself is
            # a Load; the mutation is the subscript's context.
            if isinstance(sub.ctx, (ast.Store, ast.Del)) and _is_self_attr(
                sub.value
            ):
                writes.add(id(sub.value))
        elif isinstance(sub, ast.Call):
            func = sub.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _MUTATORS
                and _is_self_attr(func.value)
            ):
                writes.add(id(func.value))
            cname = dotted_name(func) or ""
            if cname.rsplit(".", 1)[-1] in _HEAP_FNS and sub.args:
                if _is_self_attr(sub.args[0]):
                    writes.add(id(sub.args[0]))
    for sub in walk_skip_nested(root):
        if not (isinstance(sub, ast.Attribute) and _is_self_attr(sub)):
            continue
        if not _tracked(sub.attr):
            continue
        yield sub.attr, sub.lineno, id(sub) in writes


class GuardedFieldsRule:
    name = NAME
    code = CODE
    summary = (
        "a self._x field ever written under a class lock is read and "
        "written ONLY under it (construction exempt; pragma the "
        "intentional lock-free paths)"
    )
    project_wide = False

    def check(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        paths = project.rule_paths(NAME, DEFAULT_PATHS)
        # The cross-file class index: typed attributes whose class owns
        # locks of its own (AdmissionQueue, _ResultCache, JobJournal)
        # are internally synchronized — their mutator-looking method
        # names (pop/discard/...) are thread-safe API, not races.
        model = scan_scope(project, paths)
        for top in paths:
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                stem = os.path.splitext(os.path.basename(rel))[0]
                for node in ast.iter_child_nodes(ctx.tree):
                    if isinstance(node, ast.ClassDef):
                        findings.extend(
                            self._check_class(rel, stem, node, model)
                        )
        return findings

    def _check_class(
        self,
        rel: str,
        stem: str,
        cls: ast.ClassDef,
        model: ScopeModel,
    ) -> List[Finding]:
        locks = class_lock_keys(cls, stem)
        if not locks:
            return []
        info = model.classes.get(cls.name)
        synchronized = frozenset(
            attr
            for attr in (info.attr_types if info is not None else ())
            if info is not None and model.attr_is_synchronized(info, attr)
        )
        resolve = make_resolver(cls.name, stem)
        # (attr, line, write, guarded, method) over all non-construction
        # methods, flow-sensitively.
        observed: List[Tuple[str, int, bool, bool, str]] = []
        for fn in ast.iter_child_nodes(cls):
            if not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if fn.name in _CONSTRUCTION:
                continue
            seed = (
                locks if fn.name.endswith("_locked") else frozenset()
            )
            cfg = build_cfg(fn, resolve)
            states = held_at_nodes(cfg, resolve, seed=seed, must=True)
            for node in cfg.nodes:
                held = states.get(node)
                if held is None:
                    continue
                guarded = bool(held & locks)
                for root in node_scan_roots(node):
                    for attr, line, is_write in _accesses(root):
                        if attr in synchronized:
                            continue
                        observed.append(
                            (attr, line, is_write, guarded, fn.name)
                        )
        guarded_fields: FrozenSet[str] = frozenset(
            attr
            for attr, _, is_write, guarded, _ in observed
            if is_write and guarded
        )
        lock_list = ", ".join(sorted(locks))
        findings: List[Finding] = []
        for attr, line, is_write, guarded, method in observed:
            if attr not in guarded_fields or guarded:
                continue
            kind = "write to" if is_write else "read of"
            findings.append(
                Finding(
                    NAME,
                    CODE,
                    rel,
                    line,
                    f"unguarded {kind} `self.{attr}` in "
                    f"`{cls.name}.{method}`: the field is written "
                    f"under a class lock ({lock_list}) elsewhere, so "
                    "every access must hold it — or carry an explicit "
                    "pragma documenting why lock-free is sound here",
                )
            )
        findings.sort(key=lambda f: f.line)
        return findings


RULE = GuardedFieldsRule()
