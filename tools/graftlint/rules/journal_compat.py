"""GL015 journal-compat: journal/job-record keys come from ONE registry,
and new-version keys are absence-tolerant on read.

The job journal is append-only and accumulates across server
generations: a round-6 journal replays under round-18 code, and a
record written today must still fold correctly under next year's
reader. That compatibility contract has two failure modes, both
silent: a writer emits a key the replay readers never learned
(orphaned data — or worse, a reader that would have dispatched on it
skips it forever), or a reader subscripts a key that old records do
not carry (every pre-upgrade journal becomes a KeyError at recovery
time, which ``_replay``'s tolerant fold downgrades to dropped jobs).

This rule is the GL003 schema-sharing pattern applied to durability.
``spark_examples_tpu/serving/journal_schema.py`` (configurable via
``registry_module``) is the single key registry; the rule
importlib-loads it — the same name sets the mixed-version replay test
and the crashsim journal scenario consume — and checks, across the
serving scope:

- **writers**: every key in a journal-event dict literal (a dict with
  a literal ``"e"`` key) or augmented onto one by subscript-assign
  must be registered; the ``"e"`` value must be a registered event
  kind. Job-record literals (``Job.to_record`` shape: literal ``"id"``
  + ``"state"`` keys) and subscript-augments on variables bound from
  ``record_of``/``to_record``/``job_record`` calls must use registered
  job-record keys.
- **readers**: inside any function that calls ``replay_events``,
  event-dict accesses must use registered keys, and OPTIONAL keys
  (post-round-6 additions: ``trace``, ``replica``, ``fence``, ...)
  must be read tolerantly — ``e.get(k)``, or a subscript guarded by an
  ``e.get(k)`` in the same statement.
- **staleness** (the other drift direction): a registered key that no
  writer in scope ever emits is a finding at the registry — the
  registry must describe the code, not a remembered version of it.

Absent registry module (fixture mini-projects) disables the rule, as
GL003 does when ``validate_trace.py`` is missing.
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import (
    Any,
    FrozenSet,
    Iterable,
    Iterator,
    List,
    Optional,
    Set,
    Tuple,
)

from tools.graftlint.astutil import call_name, last_component, literal_str
from tools.graftlint.dataflow import walk_skip_nested
from tools.graftlint.engine import Finding, Project

NAME = "journal-compat"
CODE = "GL015"

DEFAULT_PATHS = ("spark_examples_tpu/serving",)
DEFAULT_REGISTRY = "spark_examples_tpu/serving/journal_schema.py"

# Calls whose result is a serialized job record; subscript-assigns on
# the bound variable are job-record writes.
_RECORD_SOURCES = frozenset({"record_of", "to_record", "job_record"})

_REGISTRY_NAMES = (
    "JOURNAL_EVENT_KINDS",
    "JOURNAL_REQUIRED_KEYS",
    "JOURNAL_OPTIONAL_KEYS",
    "JOURNAL_KEYS",
    "JOB_RECORD_KEYS",
)


def load_registry(root: str, rel: str) -> Optional[Any]:
    """Import the key registry from the project root (stdlib-only by
    contract; None when absent, e.g. in fixture mini-projects)."""
    path = os.path.join(root, rel)
    if not os.path.exists(path):
        return None
    spec = importlib.util.spec_from_file_location(
        "graftlint_journal_schema", path
    )
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _dict_literal_keys(
    node: ast.Dict,
) -> List[Tuple[str, ast.AST]]:
    """(literal key, value expr) pairs; non-literal keys skipped."""
    out: List[Tuple[str, ast.AST]] = []
    for k, v in zip(node.keys, node.values):
        if k is None:
            continue  # **spread — opaque
        lit = literal_str(k)
        if lit is not None:
            out.append((lit, v))
    return out


def _stmt_exprs(stmt: ast.AST) -> Iterator[ast.AST]:
    """Expression subtrees owned by ONE statement — nested statements
    (compound bodies) and nested defs are someone else's scope."""
    stack: List[ast.AST] = []
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, (ast.stmt, ast.excepthandler)):
            continue
        stack.append(child)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (
                    ast.stmt,
                    ast.excepthandler,
                    ast.FunctionDef,
                    ast.AsyncFunctionDef,
                    ast.Lambda,
                    ast.ClassDef,
                ),
            ):
                continue
            stack.append(child)


def _subscript_key(node: ast.Subscript) -> Optional[str]:
    sl = node.slice
    # py3.8 ast.Index compatibility is not needed — repo floor is 3.9.
    return literal_str(sl)


class JournalCompatRule:
    name = NAME
    code = CODE
    summary = (
        "journal/job-record keys come from the shared registry module; "
        "post-round-6 keys are absence-tolerant on read; the registry "
        "never goes stale"
    )
    # Writers, readers, and the registry live in different files — the
    # staleness direction needs the whole scope even when the CLI
    # restricts paths.
    project_wide = True

    def check(self, project: Project) -> Iterable[Finding]:
        cfg = project.config.get("rules", {}).get(NAME, {})
        registry_rel = cfg.get("registry_module", DEFAULT_REGISTRY)
        registry = load_registry(project.root, registry_rel)
        if registry is None:
            return []
        missing = [
            n for n in _REGISTRY_NAMES if not hasattr(registry, n)
        ]
        if missing:
            return [
                Finding(
                    NAME,
                    CODE,
                    registry_rel,
                    1,
                    f"registry module lacks {', '.join(missing)} — the "
                    "shared-schema contract needs every name set",
                )
            ]
        journal_keys = frozenset(registry.JOURNAL_KEYS)
        optional_keys = frozenset(registry.JOURNAL_OPTIONAL_KEYS)
        event_kinds = frozenset(registry.JOURNAL_EVENT_KINDS)
        record_keys = frozenset(registry.JOB_RECORD_KEYS)

        findings: List[Finding] = []
        written_journal: Set[str] = set()
        written_record: Set[str] = set()
        for top in project.rule_paths(NAME, DEFAULT_PATHS):
            for rel in project.walk(top):
                ctx = project.file(rel)
                if ctx is None or ctx.tree is None:
                    continue
                if os.path.normpath(rel) == os.path.normpath(
                    registry_rel
                ):
                    continue  # the registry is the spec, not a writer
                for fn in _functions(ctx.tree):
                    findings.extend(
                        self._check_writers(
                            rel,
                            fn,
                            journal_keys,
                            event_kinds,
                            record_keys,
                            written_journal,
                            written_record,
                        )
                    )
                    findings.extend(
                        self._check_readers(
                            rel, fn, journal_keys, optional_keys
                        )
                    )
        for key in sorted(journal_keys - written_journal):
            findings.append(
                Finding(
                    NAME,
                    CODE,
                    registry_rel,
                    1,
                    f"registered journal key {key!r} is written by no "
                    "serialization site in scope — stale registry "
                    "entries teach readers to tolerate keys that "
                    "cannot exist; remove it or restore the writer",
                )
            )
        for key in sorted(record_keys - written_record):
            findings.append(
                Finding(
                    NAME,
                    CODE,
                    registry_rel,
                    1,
                    f"registered job-record key {key!r} is written by "
                    "no serialization site in scope — remove it or "
                    "restore the writer",
                )
            )
        return findings

    # -- writers ---------------------------------------------------------------

    def _check_writers(
        self,
        rel: str,
        fn: ast.AST,
        journal_keys: FrozenSet[str],
        event_kinds: FrozenSet[str],
        record_keys: FrozenSet[str],
        written_journal: Set[str],
        written_record: Set[str],
    ) -> List[Finding]:
        findings: List[Finding] = []
        journal_vars: Set[str] = set()
        record_vars: Set[str] = set()

        # First pass: dict literals + the variables bound to them.
        for node in walk_skip_nested(fn, skip_self=True):
            value = getattr(node, "value", None)
            if isinstance(
                node, (ast.Assign, ast.AnnAssign)
            ) and isinstance(value, ast.Call):
                if (
                    last_component(call_name(value))
                    in _RECORD_SOURCES
                ):
                    for tgt in _assign_targets(node):
                        if isinstance(tgt, ast.Name):
                            record_vars.add(tgt.id)
            if not isinstance(node, ast.Dict):
                continue
            pairs = _dict_literal_keys(node)
            keys = {k for k, _ in pairs}
            if "e" in keys:
                bound = _bound_names(fn, node)
                journal_vars.update(bound)
                for key, value in pairs:
                    written_journal.add(key)
                    if key not in journal_keys:
                        findings.append(
                            Finding(
                                NAME,
                                CODE,
                                rel,
                                node.lineno,
                                f"journal event written with key {key!r} "
                                "not in the shared registry "
                                "(journal_schema.JOURNAL_KEYS) — a key "
                                "the replay readers never learned is "
                                "orphaned data; register it and decide "
                                "its absence-tolerance",
                            )
                        )
                    if key == "e":
                        kind = literal_str(value)
                        if kind is not None and kind not in event_kinds:
                            findings.append(
                                Finding(
                                    NAME,
                                    CODE,
                                    rel,
                                    node.lineno,
                                    f"journal event kind {kind!r} not in "
                                    "journal_schema.JOURNAL_EVENT_KINDS "
                                    "— replay folds unknown kinds as "
                                    "corruption",
                                )
                            )
            elif keys >= {"id", "state"}:
                bound = _bound_names(fn, node)
                record_vars.update(bound)
                for key, _ in pairs:
                    written_record.add(key)
                    if key not in record_keys:
                        findings.append(
                            Finding(
                                NAME,
                                CODE,
                                rel,
                                node.lineno,
                                f"job record written with key {key!r} "
                                "not in journal_schema.JOB_RECORD_KEYS "
                                "— every record consumer treats the "
                                "record as the registry's closed set",
                            )
                        )

        # Second pass: subscript-assign augments on bound variables.
        for node in walk_skip_nested(fn, skip_self=True):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                ):
                    continue
                key = _subscript_key(tgt)
                if key is None:
                    continue
                var = tgt.value.id
                if var in journal_vars:
                    written_journal.add(key)
                    if key not in journal_keys:
                        findings.append(
                            Finding(
                                NAME,
                                CODE,
                                rel,
                                node.lineno,
                                f"journal event augmented with key "
                                f"{key!r} not in the shared registry — "
                                "register it and decide its "
                                "absence-tolerance",
                            )
                        )
                elif var in record_vars:
                    written_record.add(key)
                    if key not in record_keys:
                        findings.append(
                            Finding(
                                NAME,
                                CODE,
                                rel,
                                node.lineno,
                                f"job record augmented with key {key!r} "
                                "not in "
                                "journal_schema.JOB_RECORD_KEYS",
                            )
                        )
        return findings

    # -- readers ---------------------------------------------------------------

    def _check_readers(
        self,
        rel: str,
        fn: ast.AST,
        journal_keys: FrozenSet[str],
        optional_keys: FrozenSet[str],
    ) -> List[Finding]:
        replays = any(
            last_component(call_name(c)) == "replay_events"
            for c in _calls(fn)
        )
        if not replays:
            return []
        event_vars = _replay_event_vars(fn)
        if not event_vars:
            return []
        findings: List[Finding] = []
        for stmt in walk_skip_nested(fn, skip_self=True):
            if not isinstance(stmt, ast.stmt):
                continue
            # .get(k) guards present in this statement, per variable.
            guarded: Set[Tuple[str, str]] = set()
            accesses: List[Tuple[str, str, bool, int]] = []
            for expr in _stmt_exprs(stmt):
                if (
                    isinstance(expr, ast.Call)
                    and isinstance(expr.func, ast.Attribute)
                    and expr.func.attr == "get"
                    and isinstance(expr.func.value, ast.Name)
                    and expr.func.value.id in event_vars
                    and expr.args
                ):
                    key = literal_str(expr.args[0])
                    if key is not None:
                        var = expr.func.value.id
                        guarded.add((var, key))
                        accesses.append((var, key, True, expr.lineno))
                elif (
                    isinstance(expr, ast.Subscript)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id in event_vars
                    and not isinstance(expr.ctx, ast.Store)
                ):
                    key = _subscript_key(expr)
                    if key is not None:
                        accesses.append(
                            (expr.value.id, key, False, expr.lineno)
                        )
            for var, key, tolerant, line in accesses:
                if key not in journal_keys:
                    findings.append(
                        Finding(
                            NAME,
                            CODE,
                            rel,
                            line,
                            f"replay reader accesses journal key {key!r} "
                            "not in the shared registry — a reader "
                            "dispatching on an unregistered key reads a "
                            "key no writer is checked to emit",
                        )
                    )
                elif (
                    key in optional_keys
                    and not tolerant
                    and (var, key) not in guarded
                ):
                    findings.append(
                        Finding(
                            NAME,
                            CODE,
                            rel,
                            line,
                            f"replay reader subscripts OPTIONAL journal "
                            f"key {key!r} without a guarding "
                            f"`.get({key!r})` in the same statement — "
                            "pre-upgrade journals do not carry it, and "
                            "the KeyError at replay time drops the job",
                        )
                    )
        return findings


def _assign_targets(node: ast.AST) -> List[ast.expr]:
    """Bind targets of plain and annotated assignments alike —
    ``event: Dict[str, Any] = {...}`` binds exactly as ``event = {...}``
    does."""
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, ast.AnnAssign) and node.value is not None:
        return [node.target]
    return []


def _bound_names(fn: ast.AST, dict_node: ast.Dict) -> Set[str]:
    """Names the function binds directly to this dict literal."""
    out: Set[str] = set()
    for node in walk_skip_nested(fn, skip_self=True):
        if getattr(node, "value", None) is dict_node:
            for tgt in _assign_targets(node):
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _replay_event_vars(fn: ast.AST) -> Set[str]:
    """Loop variables that iterate journal events: ``for e in
    replay_events(...)`` directly, or through a variable bound to the
    replay result (optionally via ``list(...)``)."""
    replay_bound: Set[str] = set()
    for node in walk_skip_nested(fn, skip_self=True):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if (
            isinstance(value, ast.Call)
            and last_component(call_name(value)) == "list"
            and value.args
        ):
            value = value.args[0]
        if (
            isinstance(value, ast.Call)
            and last_component(call_name(value)) == "replay_events"
        ):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    replay_bound.add(tgt.id)
    out: Set[str] = set()
    for node in walk_skip_nested(fn, skip_self=True):
        if not isinstance(node, (ast.For, ast.AsyncFor)):
            continue
        src = node.iter
        from_replay = (
            isinstance(src, ast.Call)
            and last_component(call_name(src)) == "replay_events"
        ) or (
            isinstance(src, ast.Name) and src.id in replay_bound
        )
        if from_replay and isinstance(node.target, ast.Name):
            out.add(node.target.id)
    return out


def _calls(fn: ast.AST) -> Iterator[ast.Call]:
    for node in walk_skip_nested(fn, skip_self=True):
        if isinstance(node, ast.Call):
            yield node


def _functions(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        elif isinstance(node, ast.ClassDef):
            for sub in ast.iter_child_nodes(node):
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield sub


RULE = JournalCompatRule()
