"""Reaching-locks dataflow over graftlint CFGs.

The lattice element at a program point is the set of lock keys held
there. Two modes:

- **must** (meet = intersection): a lock is in the state only if it is
  held on EVERY path reaching the point — what GL007 (may this
  ``*_locked`` call run here?) and GL009 (is this field access guarded?)
  need. Unreachable predecessors are ⊤ and drop out of the meet.
- **may** (meet = union): a lock is in the state if it is held on SOME
  path — what GL008 needs to derive potential lock-order edges.

Lock identity is canonical: ``ClassName.attr`` for ``self.<attr>``
locks, ``<module-stem>.name`` for module-level locks. The resolver is
built per analysis context by :func:`make_resolver`; an expression that
does not *look like* a lock (see :func:`is_lock_name`) never becomes a
key, so ``with obs.span(...)`` or ``with open(...)`` stay invisible.
"""

from __future__ import annotations

import ast
import re
from typing import (
    Callable,
    Dict,
    FrozenSet,
    Iterator,
    List,
    Optional,
    Set,
    Tuple,
)

from tools.graftlint.astutil import dotted_name
from tools.graftlint.cfg import CFG, Node, build_cfg

__all__ = [
    "Resolver",
    "is_lock_name",
    "make_resolver",
    "class_lock_keys",
    "module_lock_keys",
    "held_at_nodes",
    "must_events",
    "scan_calls",
    "manual_lock_ops",
    "node_scan_roots",
    "walk_skip_nested",
    "build_cfg",
]

# A name is lock-like when one of its underscore-separated words is a
# synchronization noun. Substring matching would be wrong ("blocks"
# contains "lock"); word matching keeps data attributes out.
_LOCK_WORDS = frozenset(
    {
        "lock",
        "locks",
        "cv",
        "cond",
        "condition",
        "mutex",
        "sem",
        "semaphore",
        "rlock",
    }
)
_WORD_SPLIT = re.compile(r"[^a-zA-Z0-9]+")

# resolve(expr) -> canonical lock key, or None for non-lock expressions.
Resolver = Callable[[ast.AST], Optional[str]]


def is_lock_name(name: str) -> bool:
    """True when the (unqualified) attribute/variable name reads as a
    lock: ``_lock``, ``_cv``, ``_flush_lock``, ``device_lock``..."""
    return any(
        w in _LOCK_WORDS for w in _WORD_SPLIT.split(name.lower()) if w
    )


def make_resolver(
    class_name: Optional[str], module_stem: str
) -> Resolver:
    """Lock-key resolver for code inside ``class_name`` (None at module
    level) of module ``module_stem``."""

    def resolve(expr: ast.AST) -> Optional[str]:
        name = dotted_name(expr)
        if name is None:
            return None
        parts = name.split(".")
        if not is_lock_name(parts[-1]):
            return None
        if parts[0] == "self" and len(parts) == 2:
            owner = class_name if class_name else module_stem
            return f"{owner}.{parts[1]}"
        if parts[0] == "self":
            # self.a.b.lock — a lock owned through another object;
            # key it by the full path under the class for stability.
            owner = class_name if class_name else module_stem
            return f"{owner}.{'.'.join(parts[1:])}"
        return f"{module_stem}.{name}"

    return resolve


def walk_skip_nested(
    node: ast.AST, *, skip_self: bool = False
) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested function/class/lambda
    bodies — they run on other call stacks. ``skip_self=True`` starts
    from the node's children (walk a function's body without treating
    the function itself as nested). The ONE shared implementation for
    every flow-sensitive rule: what counts as opaque must never differ
    between rules."""
    stack: List[ast.AST] = (
        list(ast.iter_child_nodes(node)) if skip_self else [node]
    )
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(
                child,
                (
                    ast.FunctionDef,
                    ast.AsyncFunctionDef,
                    ast.Lambda,
                    ast.ClassDef,
                ),
            ):
                continue
            stack.append(child)


def scan_calls(stmt: ast.AST) -> Iterator[ast.Call]:
    """Calls syntactically inside one statement (nested defs opaque)."""
    for sub in walk_skip_nested(stmt):
        if isinstance(sub, ast.Call):
            yield sub


def node_scan_roots(node: Node) -> List[ast.AST]:
    """The AST(s) a CFG node is *responsible for* evaluating.

    Compound statements own only their header expressions — their body
    statements are separate CFG nodes, and scanning the whole subtree
    from the header would attribute inner lock operations (and field
    accesses) to the wrong program point.
    """
    if node.kind != "stmt" or node.stmt is None:
        return []
    s = node.stmt
    if isinstance(s, (ast.If, ast.While)):
        return [s.test]
    if isinstance(s, (ast.For, ast.AsyncFor)):
        return [s.iter]
    if isinstance(s, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in s.items]
    if isinstance(s, ast.Try):
        return []
    if isinstance(s, ast.ExceptHandler):
        return [s.type] if s.type is not None else []
    if isinstance(
        s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    ):
        return []
    return [s]


def manual_lock_ops(
    stmt: ast.AST, resolve: Resolver
) -> Tuple[List[str], List[str]]:
    """(acquired, released) lock keys from explicit ``X.acquire(...)`` /
    ``X.release()`` calls inside one statement."""
    acquired: List[str] = []
    released: List[str] = []
    for call in scan_calls(stmt):
        func = call.func
        if not isinstance(func, ast.Attribute):
            continue
        if func.attr not in ("acquire", "release"):
            continue
        key = resolve(func.value)
        if key is None:
            continue
        (acquired if func.attr == "acquire" else released).append(key)
    return acquired, released


def class_lock_keys(cls: ast.ClassDef, module_stem: str) -> FrozenSet[str]:
    """Every lock key a class's methods synchronize on via ``self``:
    ``with self.X`` / ``self.X.acquire()`` where X is lock-like."""
    resolve = make_resolver(cls.name, module_stem)
    keys: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                key = resolve(item.context_expr)
                if key is not None and key.startswith(cls.name + "."):
                    keys.add(key)
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr in ("acquire", "release"):
                key = resolve(node.func.value)
                if key is not None and key.startswith(cls.name + "."):
                    keys.add(key)
    return frozenset(keys)


# Constructor names that bind a synchronization primitive.
_LOCK_CTORS = frozenset(
    {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
)


def module_lock_keys(
    tree: ast.AST, module_stem: str
) -> FrozenSet[str]:
    """Module-global lock keys: ``X = threading.Lock()``-style bindings
    (a lock-like NAME alone is not enough — ``LOCK_CHECK_ENV = "..."``
    is a string, not a lock) plus any bare lock-like name synchronized
    on at module scope."""
    keys: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                name = dotted_name(item.context_expr)
                if name and "." not in name and is_lock_name(name):
                    keys.add(f"{module_stem}.{name}")
        elif isinstance(node, ast.Assign):
            if not (
                isinstance(node.value, ast.Call)
                and (dotted_name(node.value.func) or "").rsplit(".", 1)[
                    -1
                ]
                in _LOCK_CTORS
            ):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and is_lock_name(tgt.id):
                    keys.add(f"{module_stem}.{tgt.id}")
    return frozenset(keys)


def held_at_nodes(
    cfg: CFG,
    resolve: Resolver,
    seed: FrozenSet[str] = frozenset(),
    must: bool = True,
) -> Dict[Node, FrozenSet[str]]:
    """Solve the reaching-locks equations; returns IN[node] — the locks
    held *entering* each reachable node (unreachable nodes absent)."""
    preds = cfg.preds()
    # OUT states; None = ⊤ (unreachable so far).
    out: Dict[Node, Optional[FrozenSet[str]]] = {
        n: None for n in cfg.nodes
    }
    in_states: Dict[Node, FrozenSet[str]] = {}

    def transfer(node: Node, state: FrozenSet[str]) -> FrozenSet[str]:
        if node.kind == "acquire" and node.lock is not None:
            return state | {node.lock}
        if node.kind == "release" and node.lock is not None:
            return state - {node.lock}
        if node.kind == "stmt" and node.stmt is not None:
            for root in node_scan_roots(node):
                acq, rel = manual_lock_ops(root, resolve)
                if acq or rel:
                    state = (state - frozenset(rel)) | frozenset(acq)
        return state

    worklist: List[Node] = [cfg.entry]
    on_list = {cfg.entry}
    while worklist:
        node = worklist.pop()
        on_list.discard(node)
        if node is cfg.entry:
            state: Optional[FrozenSet[str]] = seed
        else:
            state = None
            for p in preds[node]:
                p_out = out[p]
                if p_out is None:
                    continue
                if state is None:
                    state = p_out
                elif must:
                    state = state & p_out
                else:
                    state = state | p_out
            if state is None:
                continue  # still unreachable
        in_states[node] = state
        new_out = transfer(node, state)
        if out[node] != new_out:
            out[node] = new_out
            for s in node.succs:
                if s not in on_list:
                    worklist.append(s)
                    on_list.add(s)
    return in_states


def must_events(
    cfg: CFG,
    events_at: Callable[[Node], FrozenSet[str]],
) -> Dict[Node, FrozenSet[str]]:
    """Forward must-EVENT dataflow: IN[node] = the event tags that have
    occurred on EVERY path from entry to node.

    The gen-only sibling of :func:`held_at_nodes` — an event that
    happened (an ``os.fsync``, a fresh fence-token read) cannot
    un-happen, so the transfer function only adds (meet is still
    intersection over predecessors; unreachable predecessors are ⊤ and
    drop out). GL013 uses it for fsync-before-rename ordering; GL014
    for fence-token-read-dominates-write.
    """
    preds = cfg.preds()
    out: Dict[Node, Optional[FrozenSet[str]]] = {n: None for n in cfg.nodes}
    in_states: Dict[Node, FrozenSet[str]] = {}
    worklist: List[Node] = [cfg.entry]
    on_list = {cfg.entry}
    while worklist:
        node = worklist.pop()
        on_list.discard(node)
        if node is cfg.entry:
            state: Optional[FrozenSet[str]] = frozenset()
        else:
            state = None
            for p in preds[node]:
                p_out = out[p]
                if p_out is None:
                    continue
                state = p_out if state is None else (state & p_out)
            if state is None:
                continue  # unreachable so far
        in_states[node] = state
        new_out = state | events_at(node)
        if out[node] != new_out:
            out[node] = new_out
            for s in node.succs:
                if s not in on_list:
                    worklist.append(s)
                    on_list.add(s)
    return in_states
