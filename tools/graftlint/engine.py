"""graftlint engine: file discovery, config, pragmas, rule runner, output.

The engine is rule-agnostic: a rule is any object with ``name``,
``code``, ``summary`` and a ``check(project) -> Iterable[Finding]``
method (see :mod:`tools.graftlint.rules`). The engine owns everything
rules should not re-implement — parsing files once, pragma suppression,
config scoping, and the two output formats (human lines and JSONL for
machine consumption in CI).

Design constraints baked in:

- **stdlib only** — must run on any dev box / CI image with no installs
  (the same bar scripts/validate_trace.py holds itself to);
- **Python 3.10 compatible** — ``tomllib`` is 3.11+, so config loading
  falls back to a deliberately tiny TOML-subset reader for the handful
  of shapes ``[tool.graftlint]`` uses (string/bool scalars and string
  arrays; nested ``[tool.graftlint.rules.<name>]`` tables);
- **suppressions are data** — every pragma hit is counted per rule and
  shown in the summary, so silencing debt stays visible.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "FileCtx",
    "Project",
    "load_config",
    "run_lint",
    "sarif_document",
    "main",
]

_PRAGMA = re.compile(
    r"(?:#|//)\s*graftlint:\s*(disable|disable-file)\s*=\s*"
    r"([a-z0-9_,\- ]+)"
)
# Pragmas that suppress for the whole file must sit near the top, so a
# reviewer reading the file head sees the debt declaration.
_FILE_PRAGMA_WINDOW = 10


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str  # rule name, e.g. "jit-purity"
    code: str  # stable id, e.g. "GL001"
    path: str  # repo-relative path
    line: int  # 1-based
    message: str

    def human(self) -> str:
        return f"{self.path}:{self.line}: {self.code}[{self.rule}] {self.message}"

    def jsonl(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)


class FileCtx:
    """One parsed source file: text, lines, AST (Python only), pragmas."""

    def __init__(self, root: str, relpath: str, text: str) -> None:
        self.root = root
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        self.parse_error_line = 1
        if relpath.endswith(".py"):
            try:
                self.tree = ast.parse(text, filename=relpath)
            except SyntaxError as e:
                self.parse_error = f"syntax error: {e.msg}"
                self.parse_error_line = e.lineno or 1
        # line -> set of rule names disabled on that line
        self.line_pragmas: Dict[int, set] = {}
        self.file_pragmas: set = set()
        for lineno, line in enumerate(self.lines, 1):
            m = _PRAGMA.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
            if m.group(1) == "disable-file":
                if lineno <= _FILE_PRAGMA_WINDOW:
                    self.file_pragmas |= rules
            else:
                self.line_pragmas[lineno] = (
                    self.line_pragmas.get(lineno, set()) | rules
                )

    def suppressed(self, rule: str, line: int) -> bool:
        """A finding is suppressed by a same-line pragma, a pragma on
        the line directly above (for lines where a trailing comment
        will not fit), or a file-level pragma."""
        if rule in self.file_pragmas:
            return True
        for at in (line, line - 1):
            if rule in self.line_pragmas.get(at, set()):
                return True
        return False


class Project:
    """The analyzed tree: config + lazily-parsed files keyed by relpath."""

    def __init__(self, root: str, config: Dict[str, Any]) -> None:
        self.root = os.path.abspath(root)
        self.config = config
        self._files: Dict[str, Optional[FileCtx]] = {}

    def file(self, relpath: str) -> Optional[FileCtx]:
        relpath = relpath.replace(os.sep, "/")
        if relpath not in self._files:
            abspath = os.path.join(self.root, relpath)
            try:
                with open(abspath, encoding="utf-8", errors="replace") as f:
                    text = f.read()
            except OSError:
                self._files[relpath] = None
            else:
                self._files[relpath] = FileCtx(self.root, relpath, text)
        return self._files[relpath]

    def walk(self, top: str, suffixes: Sequence[str] = (".py",)) -> List[str]:
        """Repo-relative paths under ``top`` with one of ``suffixes``,
        minus config-excluded subtrees, sorted for stable output."""
        exclude = tuple(self.config.get("exclude", ()))
        out: List[str] = []
        top_abs = os.path.join(self.root, top)
        if os.path.isfile(top_abs):
            rel = os.path.relpath(top_abs, self.root).replace(os.sep, "/")
            return [rel] if not _excluded(rel, exclude) else []
        for dirpath, dirnames, filenames in os.walk(top_abs):
            rel_dir = os.path.relpath(dirpath, self.root).replace(os.sep, "/")
            dirnames[:] = [
                d
                for d in sorted(dirnames)
                if not _excluded(_relnorm(f"{rel_dir}/{d}"), exclude)
            ]
            for fn in sorted(filenames):
                if not fn.endswith(tuple(suffixes)):
                    continue
                rel = _relnorm(f"{rel_dir}/{fn}")
                if not _excluded(rel, exclude):
                    out.append(rel)
        return out

    def rule_paths(self, rule_name: str, default: Sequence[str]) -> List[str]:
        rules_cfg = self.config.get("rules", {})
        cfg = rules_cfg.get(rule_name, {}) if isinstance(rules_cfg, dict) else {}
        return list(cfg.get("paths", default))

    def rule_enabled(self, rule_name: str) -> bool:
        rules_cfg = self.config.get("rules", {})
        cfg = rules_cfg.get(rule_name, {}) if isinstance(rules_cfg, dict) else {}
        return bool(cfg.get("enabled", True))


def _relnorm(rel: str) -> str:
    """Strip a leading ``./`` *prefix* (``str.lstrip`` strips a charset
    and would corrupt dot-prefixed names like ``.sanitize``)."""
    while rel.startswith("./"):
        rel = rel[2:]
    return rel


def _excluded(rel: str, exclude: Sequence[str]) -> bool:
    return any(
        rel == ex or rel.startswith(ex.rstrip("/") + "/") for ex in exclude
    )


# -- config ----------------------------------------------------------------


def _mini_toml_table(text: str, table: str) -> Dict[str, Any]:
    """Extract one TOML table (and its ``<table>.rules.*`` subtables)
    without tomllib: the Python 3.10 fallback. Supports only the value
    shapes [tool.graftlint] uses — quoted strings, booleans, and
    (possibly multi-line) arrays of quoted strings."""
    out: Dict[str, Any] = {}
    current: Optional[Dict[str, Any]] = None
    pending_key: Optional[str] = None
    pending_items: List[str] = []
    for raw in text.splitlines():
        line = raw.strip()
        if pending_key is not None:
            pending_items += re.findall(r'"((?:[^"\\]|\\.)*)"', line)
            if line.endswith("]"):
                assert current is not None
                current[pending_key] = pending_items
                pending_key, pending_items = None, []
            continue
        if not line or line.startswith("#"):
            continue
        if line.startswith("["):
            header = line.strip("[]").strip()
            if header == table:
                current = out
            elif header.startswith(table + ".rules."):
                name = header[len(table + ".rules.") :].strip("\"'")
                current = out.setdefault("rules", {}).setdefault(name, {})
            else:
                current = None
            continue
        if current is None or "=" not in line:
            continue
        key, _, value = line.partition("=")
        key, value = key.strip().strip('"'), value.strip()
        if value.startswith("[") and not value.endswith("]"):
            pending_key = key
            pending_items = re.findall(r'"((?:[^"\\]|\\.)*)"', value)
            continue
        if value.startswith("["):
            current[key] = re.findall(r'"((?:[^"\\]|\\.)*)"', value)
        elif value in ("true", "false"):
            current[key] = value == "true"
        else:
            current[key] = value.strip('"')
    return out


def load_config(root: str) -> Dict[str, Any]:
    """``[tool.graftlint]`` from ``<root>/pyproject.toml`` (or {})."""
    path = os.path.join(root, "pyproject.toml")
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return {}
    try:
        import tomllib  # Python 3.11+

        return (
            tomllib.loads(text).get("tool", {}).get("graftlint", {}) or {}
        )
    except ImportError:
        return _mini_toml_table(text, "tool.graftlint")


def find_root(start: str) -> str:
    """Nearest ancestor of ``start`` holding a pyproject.toml."""
    d = os.path.abspath(start)
    while True:
        if os.path.exists(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.abspath(start)
        d = parent


# -- runner ----------------------------------------------------------------


def run_lint(
    root: str,
    paths: Sequence[str],
    rules: Optional[Sequence[Any]] = None,
) -> Tuple[List["Finding"], Dict[str, int]]:
    """Run all (enabled) rules; returns (findings, suppressed_counts).

    ``paths`` narrows *per-file* scoping: a rule only reports findings in
    files under one of the given repo-relative paths. Project-wide
    cross-check rules (span-contract, flag-registry) always examine
    their full configured scope — a contract between N files cannot be
    checked through a keyhole — but their findings are still attributed
    to real files and reported regardless of ``paths``, because a broken
    cross-file contract is never out of scope.
    """
    if rules is None:
        from tools.graftlint.rules import ALL_RULES

        rules = ALL_RULES
    config = load_config(root)
    project = Project(root, config)
    findings: List[Finding] = []
    suppressed: Dict[str, int] = {}
    for rule in rules:
        if not project.rule_enabled(rule.name):
            continue
        for finding in rule.check(project):
            if not _in_scope(finding, rule, paths):
                continue
            ctx = project.file(finding.path)
            if ctx is not None and ctx.suppressed(rule.name, finding.line):
                suppressed[rule.name] = suppressed.get(rule.name, 0) + 1
                continue
            findings.append(finding)
    # A Python file in scope that does not parse must FAIL the gate,
    # not silently pass it: every rule skips `tree is None` files, so
    # without this the most broken files are the only ones ungated.
    # Not suppressible by design.
    for rel, ctx in sorted(project._files.items()):
        if ctx is None or not ctx.parse_error:
            continue
        finding = Finding(
            "parse-error",
            "GL000",
            rel,
            ctx.parse_error_line,
            f"{ctx.parse_error} — unparseable files cannot be analyzed, "
            "so no invariant is proven here; fix the syntax first",
        )
        if _in_scope(finding, _PARSE_ERROR_SCOPE, paths):
            findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings, suppressed


class _ParseErrorScope:
    project_wide = False


_PARSE_ERROR_SCOPE = _ParseErrorScope()


def _in_scope(finding: Finding, rule: Any, paths: Sequence[str]) -> bool:
    if not paths or getattr(rule, "project_wide", False):
        return True
    norm = [p.replace(os.sep, "/").rstrip("/") for p in paths]
    return any(
        finding.path == p or finding.path.startswith(p + "/") for p in norm
    )


def sarif_document(findings: Sequence[Finding]) -> Dict[str, Any]:
    """Findings as one SARIF 2.1.0 run — the schema GitHub code
    scanning ingests for inline PR annotations. Rule metadata comes
    from the live registry so every GLxxx id resolves even on a clean
    run (an empty ``results`` array with full ``rules`` is how SARIF
    says "checked and found nothing", not "didn't check")."""
    from tools.graftlint.rules import ALL_RULES

    rules = [
        {
            "id": rule.code,
            "name": rule.name,
            "shortDescription": {"text": rule.summary},
        }
        for rule in ALL_RULES
    ]
    results = [
        {
            "ruleId": f.code,
            "level": "error",
            "message": {"text": f"[{f.rule}] {f.message}"},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {"startLine": max(f.line, 1)},
                    }
                }
            ],
        }
        for f in findings
    ]
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "informationUri": (
                            "docs/STATIC_ANALYSIS.md"
                        ),
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    from tools.graftlint.rules import ALL_RULES

    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="Project-invariant static analysis for spark_examples_tpu",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="Repo-relative files/directories to report on "
        "(default: everything in the configured scopes)",
    )
    parser.add_argument(
        "--root",
        default=None,
        help="Project root (default: nearest ancestor with pyproject.toml)",
    )
    parser.add_argument(
        "--format",
        choices=("human", "jsonl", "sarif"),
        default="human",
        help="Output format (jsonl: one finding object per line plus a "
        "trailing summary object; sarif: one SARIF 2.1.0 document for "
        "code-scanning upload)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="List rules and exit"
    )
    parser.add_argument(
        "--lock-graph",
        action="store_true",
        help="Print the GL008-derived lock-acquisition hierarchy as "
        "JSON and exit (the exact payload docs/CONCURRENCY.md embeds "
        "and the drift test pins)",
    )
    parser.add_argument(
        "--collective-order",
        action="store_true",
        help="Print the GL010-derived per-function lockstep collective "
        "sequences as JSON and exit (the exact payload "
        "docs/CONCURRENCY.md embeds and the drift test pins)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.code}  {rule.name:20s} {rule.summary}")
        return 0

    if args.lock_graph:
        from tools.graftlint.rules.deadlock_order import lock_graph

        root = args.root or find_root(os.getcwd())
        project = Project(root, load_config(root))
        print(json.dumps(lock_graph(project), indent=2, sort_keys=True))
        return 0

    if args.collective_order:
        from tools.graftlint.rules.collective_congruence import (
            collective_order,
        )

        root = args.root or find_root(os.getcwd())
        project = Project(root, load_config(root))
        print(
            json.dumps(collective_order(project), indent=2, sort_keys=True)
        )
        return 0

    root = args.root or find_root(os.getcwd())
    # Relative positional paths are ROOT-relative (as the help text
    # says): resolving them against a different cwd would silently
    # scope every rule to nothing and exit a false green 0.
    rel_paths = [
        os.path.relpath(
            p if os.path.isabs(p) else os.path.join(root, p), root
        )
        for p in args.paths
    ]
    findings, suppressed = run_lint(root, rel_paths)

    if args.format == "sarif":
        print(json.dumps(sarif_document(findings), sort_keys=True))
    elif args.format == "jsonl":
        for f in findings:
            print(f.jsonl())
        print(
            json.dumps(
                {
                    "summary": {
                        "findings": len(findings),
                        "suppressed": suppressed,
                    }
                },
                sort_keys=True,
            )
        )
    else:
        for f in findings:
            print(f.human())
        supp_total = sum(suppressed.values())
        detail = (
            " ("
            + ", ".join(f"{k}: {v}" for k, v in sorted(suppressed.items()))
            + ")"
            if suppressed
            else ""
        )
        print(
            f"graftlint: {len(findings)} finding(s), "
            f"{supp_total} suppressed by pragma{detail}",
            file=sys.stderr,
        )
    return 1 if findings else 0
