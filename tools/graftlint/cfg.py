"""Intraprocedural control-flow graphs for graftlint's flow-sensitive rules.

One :class:`CFG` per function: statement-granularity nodes plus
*synthetic* acquire/release nodes for ``with <lock>:`` blocks, so the
reaching-locks lattice (:mod:`tools.graftlint.dataflow`) sees lock
lifetimes as explicit events on the graph rather than re-deriving them
from syntax at every program point.

Fidelity choices (documented because every one shapes what the
concurrency rules can and cannot prove):

- **``with`` unwinding is modeled.** A statement raising inside
  ``with self._lock:`` reaches the enclosing handler *through* a
  release node — the handler provably does NOT hold the lock, exactly
  like the runtime. ``break``/``continue`` out of a ``with`` likewise
  pass through release nodes for every lock entered inside the loop.
- **Every statement may raise.** Each statement node gets an edge to
  the innermost exception continuation (handler dispatch, with-unwind
  chain, or function exit). For the must-held analysis this is the
  conservative direction: handlers meet (intersect) over every raising
  point.
- **``finally`` runs once with merged continuations.** The finally body
  is built once; its exit edges are the union of the continuations that
  can reach it (normal fall-through, uncaught-exception propagation,
  ``return`` routing). Merging paths can only shrink a must-held set,
  never grow it — safe for GL007/GL009.
- **Nested ``def``/``class``/``lambda`` bodies are opaque.** They
  execute on other call stacks; the enclosing function's lock state
  neither enters nor leaves them here.
- Compound statements without explicit handling (``match``) degrade to
  a single opaque node.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["CFG", "Node", "build_cfg"]

# resolve(expr) -> canonical lock key ("Class._lock") or None.
LockResolver = Callable[[ast.AST], Optional[str]]


class Node:
    """One CFG vertex.

    ``kind`` is one of ``entry``/``exit``/``stmt``/``acquire``/
    ``release``; ``stmt`` is the owning AST statement (None for
    entry/exit); ``lock`` is the resolved lock key on synthetic
    acquire/release nodes.
    """

    __slots__ = ("idx", "kind", "stmt", "lock", "succs")

    def __init__(
        self,
        idx: int,
        kind: str,
        stmt: Optional[ast.stmt],
        lock: Optional[str] = None,
    ) -> None:
        self.idx = idx
        self.kind = kind
        self.stmt = stmt
        self.lock = lock
        self.succs: List["Node"] = []

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0) if self.stmt else 0

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        extra = f" {self.lock}" if self.lock else ""
        return f"<Node {self.idx} {self.kind}{extra} L{self.line}>"


class CFG:
    """The graph: ``entry`` → ... → ``exit`` over :class:`Node`."""

    def __init__(self, fn: ast.AST) -> None:
        self.fn = fn
        self.nodes: List[Node] = []
        self.entry = self.new_node("entry", None)
        self.exit = self.new_node("exit", None)

    def new_node(
        self,
        kind: str,
        stmt: Optional[ast.stmt],
        lock: Optional[str] = None,
    ) -> Node:
        node = Node(len(self.nodes), kind, stmt, lock)
        self.nodes.append(node)
        return node

    def preds(self) -> Dict[Node, List[Node]]:
        out: Dict[Node, List[Node]] = {n: [] for n in self.nodes}
        for n in self.nodes:
            for s in n.succs:
                out[s].append(n)
        return out


class _Loop:
    """Break/continue routing for the innermost loop."""

    __slots__ = ("head", "breaks", "with_depth")

    def __init__(self, head: Node, with_depth: int) -> None:
        self.head = head
        self.breaks: List[Node] = []
        # How many with-held locks were entered OUTSIDE this loop: a
        # break/continue releases only the locks entered inside it.
        self.with_depth = with_depth


class _Fin:
    """One enclosing ``finally`` a return must route through: its entry
    node plus the with-depth at try entry — a return unwinds only the
    locks entered INSIDE the try (an enclosing ``with``'s lock is still
    held while the finally body runs; the ``__exit__`` fires after)."""

    __slots__ = ("entry", "with_depth")

    def __init__(self, entry: Node, with_depth: int) -> None:
        self.entry = entry
        self.with_depth = with_depth


class _Builder:
    def __init__(self, cfg: CFG, resolve: LockResolver) -> None:
        self.cfg = cfg
        self.resolve = resolve
        # Stack of lock keys entered via `with` in the current lexical
        # path (for break/continue unwind routing).
        self.with_keys: List[str] = []

    # `frontier` is the set of nodes whose next normal successor is the
    # statement about to be built; each _build_* returns the new
    # frontier (empty = control never falls through).

    def seq(
        self,
        body: Sequence[ast.stmt],
        frontier: List[Node],
        exc: Node,
        loop: Optional[_Loop],
        fin_chain: Tuple["_Fin", ...],
    ) -> List[Node]:
        for stmt in body:
            if not frontier:
                break  # unreachable code after return/raise/break
            frontier = self.stmt(stmt, frontier, exc, loop, fin_chain)
        return frontier

    def _link(self, frontier: Sequence[Node], target: Node) -> None:
        for n in frontier:
            if target not in n.succs:
                n.succs.append(target)

    def _stmt_node(
        self, stmt: ast.stmt, frontier: List[Node], exc: Node
    ) -> Node:
        node = self.cfg.new_node("stmt", stmt)
        self._link(frontier, node)
        # Any statement may raise: edge to the innermost exception
        # continuation (with-unwind chain / handler dispatch / exit).
        if exc is not node:
            node.succs.append(exc)
        return node

    def _unwind_to(self, start: Node, upto_depth: int, target: Node) -> None:
        """Route ``start`` to ``target`` through release nodes for every
        with-held lock above ``upto_depth`` (innermost first)."""
        cur = start
        for key in reversed(self.with_keys[upto_depth:]):
            rel = self.cfg.new_node("release", cur.stmt, key)
            cur.succs.append(rel)
            cur = rel
        cur.succs.append(target)

    def stmt(
        self,
        stmt: ast.stmt,
        frontier: List[Node],
        exc: Node,
        loop: Optional[_Loop],
        fin_chain: Tuple["_Fin", ...],
    ) -> List[Node]:
        if isinstance(stmt, (ast.If,)):
            test = self._stmt_node(stmt, frontier, exc)
            then_out = self.seq(stmt.body, [test], exc, loop, fin_chain)
            else_out = self.seq(stmt.orelse, [test], exc, loop, fin_chain)
            if not stmt.orelse:
                else_out = [test]
            return then_out + else_out

        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = self._stmt_node(stmt, frontier, exc)
            inner = _Loop(head, len(self.with_keys))
            body_out = self.seq(stmt.body, [head], exc, inner, fin_chain)
            self._link(body_out, head)  # back edge
            after: List[Node] = inner.breaks
            # Loop-exit path (condition false / iterator exhausted),
            # possibly through an `else` clause.
            else_out = self.seq(stmt.orelse, [head], exc, loop, fin_chain)
            after = after + (else_out if stmt.orelse else [head])
            return after

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            header = self._stmt_node(stmt, frontier, exc)
            keys = [
                k
                for k in (
                    self.resolve(item.context_expr) for item in stmt.items
                )
                if k is not None
            ]
            cur: List[Node] = [header]
            body_exc = exc
            for key in keys:
                acq = self.cfg.new_node("acquire", stmt, key)
                self._link(cur, acq)
                cur = [acq]
                # Exception inside the body unwinds through a release
                # of this lock before reaching the outer continuation.
                unwind = self.cfg.new_node("release", stmt, key)
                unwind.succs.append(body_exc)
                body_exc = unwind
                self.with_keys.append(key)
            body_out = self.seq(stmt.body, cur, body_exc, loop, fin_chain)
            for key in reversed(keys):
                self.with_keys.pop()
                rel = self.cfg.new_node("release", stmt, key)
                self._link(body_out, rel)
                body_out = [rel]
            return body_out

        if isinstance(stmt, ast.Try):
            # The finally entry exists BEFORE the body is built so that
            # return/uncaught-exception routing inside can target it.
            fin_entry: Optional[Node] = None
            if stmt.finalbody:
                fin_entry = self.cfg.new_node("stmt", stmt)
            # Exception continuation inside the body: each handler
            # entry, plus (uncaught) the finally or the outer exc.
            dispatch = self.cfg.new_node("stmt", stmt)
            body_exc = dispatch
            inner_fin = (
                (_Fin(fin_entry, len(self.with_keys)),) + fin_chain
                if fin_entry
                else fin_chain
            )
            body_out = self.seq(
                stmt.body, frontier, body_exc, loop, inner_fin
            )
            body_out = self.seq(
                stmt.orelse, body_out, body_exc, loop, inner_fin
            )
            handler_outs: List[Node] = []
            for handler in stmt.handlers:
                h_entry = self.cfg.new_node("stmt", handler)
                dispatch.succs.append(h_entry)
                h_exc = fin_entry if fin_entry is not None else exc
                handler_outs += self.seq(
                    handler.body, [h_entry], h_exc, loop, inner_fin
                )
            # Uncaught path: dispatch also propagates outward (through
            # finally when present). A bare `except:` still gets this
            # edge — conservative, and harmless for must-analysis.
            dispatch.succs.append(fin_entry if fin_entry else exc)
            if fin_entry is not None:
                self._link(body_out + handler_outs, fin_entry)
                fin_out = self.seq(
                    stmt.finalbody, [fin_entry], exc, loop, fin_chain
                )
                # Merged continuations: normal fall-through plus the
                # propagation paths (outer exception target; function
                # exit for returns routed here).
                for n in fin_out:
                    for target in (exc, self.cfg.exit):
                        if target not in n.succs:
                            n.succs.append(target)
                return fin_out
            return body_out + handler_outs

        if isinstance(stmt, ast.Return):
            node = self._stmt_node(stmt, frontier, exc)
            if fin_chain:
                # Route through the innermost finally, releasing ONLY
                # the with-locks entered inside that try — a lock whose
                # `with` encloses the try/finally is still held while
                # the finally body runs.
                fin = fin_chain[0]
                self._unwind_to(node, fin.with_depth, fin.entry)
            else:
                self._unwind_to(node, 0, self.cfg.exit)
            return []

        if isinstance(stmt, ast.Raise):
            node = self._stmt_node(stmt, frontier, exc)
            # The raise edge to `exc` is already there.
            return []

        if isinstance(stmt, ast.Break):
            node = self._stmt_node(stmt, frontier, exc)
            if loop is not None:
                sink = self.cfg.new_node("stmt", stmt)
                self._unwind_to(node, loop.with_depth, sink)
                loop.breaks.append(sink)
            return []

        if isinstance(stmt, ast.Continue):
            node = self._stmt_node(stmt, frontier, exc)
            if loop is not None:
                self._unwind_to(node, loop.with_depth, loop.head)
            return []

        # Opaque statements (assignments, expressions, nested defs,
        # imports, match, ...): one node, normal fall-through.
        node = self._stmt_node(stmt, frontier, exc)
        return [node]


def build_cfg(fn: ast.AST, resolve: LockResolver) -> CFG:
    """CFG for a FunctionDef/AsyncFunctionDef/Lambda body."""
    cfg = CFG(fn)
    body = fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]
    builder = _Builder(cfg, resolve)
    out = builder.seq(body, [cfg.entry], cfg.exit, None, ())
    builder._link(out, cfg.exit)
    return cfg
