"""graftlint: project-invariant static analysis for spark_examples_tpu.

Generic linters cannot see this project's contracts: jit-purity of the
device kernels, integer-exact dtype discipline on the Gramian path, the
span/metric telemetry schema, the CLI-flag registry, resilience routing
of every transport call, and the GIL-released native core staying clear
of the Python C-API. Each of those is a *runtime* invariant today —
enforced only by tests that must happen to exercise the offending path.
graftlint proves them at review time instead.

Usage (from the repo root)::

    python -m tools.graftlint spark_examples_tpu/
    python -m tools.graftlint --format jsonl spark_examples_tpu/
    python -m tools.graftlint --list-rules

Suppress a finding with a pragma on the offending line (or the line
directly above it)::

    x = host_only_helper()  # graftlint: disable=jit-purity

or for a whole file (first 10 lines)::

    # graftlint: disable-file=span-contract

Suppressions are counted and reported — they are visible debt, not
silence. Configuration lives in ``[tool.graftlint]`` in pyproject.toml;
see docs/STATIC_ANALYSIS.md for every rule's rationale.
"""

from tools.graftlint.engine import (  # noqa: F401
    Finding,
    Project,
    load_config,
    run_lint,
)

__all__ = ["Finding", "Project", "load_config", "run_lint"]
