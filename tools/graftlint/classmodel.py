"""Shared cross-file class index for the concurrency rules.

GL008 (deadlock-order) and GL009 (guarded-fields) both need the same
lightweight whole-scope model: which classes exist, what locks each
synchronizes on, and what class each ``self.<attr>`` is constructed as
(``self._queue = AdmissionQueue(...)`` types ``_queue``). One
*inference implementation*, two consumers — the rules cannot disagree
about HOW an attribute is typed. Each rule still scans its own
configured path set (the scopes legitimately differ: GL009 self-lints
``tools/graftlint``, GL008 does not), so each builds its own model
instance over its own scope.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from tools.graftlint.astutil import dotted_name
from tools.graftlint.dataflow import class_lock_keys, module_lock_keys
from tools.graftlint.engine import Project

__all__ = ["ClassInfo", "ScopeModel", "scan_scope"]


class ClassInfo:
    """One indexed class: its methods, locks, and typed attributes."""

    __slots__ = ("name", "rel", "stem", "node", "methods", "attr_types", "locks")

    def __init__(self, rel: str, stem: str, node: ast.ClassDef) -> None:
        self.name = node.name
        self.rel = rel
        self.stem = stem
        self.node = node
        self.methods: Dict[str, ast.AST] = {
            sub.name: sub
            for sub in ast.iter_child_nodes(node)
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # attr name -> candidate class names (from constructor assigns).
        self.attr_types: Dict[str, Set[str]] = {}
        self.locks: FrozenSet[str] = frozenset()


class ScopeModel:
    """Everything the concurrency rules index over their scope."""

    def __init__(self) -> None:
        self.classes: Dict[str, ClassInfo] = {}
        # (rel, stem, class name | None, function) to analyze.
        self.functions: List[Tuple[str, str, Optional[str], ast.AST]] = []
        self.module_locks: Dict[str, FrozenSet[str]] = {}
        self.all_locks: Set[str] = set()

    def attr_classes(self, info: ClassInfo, attr: str) -> List[ClassInfo]:
        """Indexed ClassInfos an attribute of ``info`` may hold."""
        return [
            self.classes[n]
            for n in sorted(info.attr_types.get(attr, ()))
            if n in self.classes
        ]

    def attr_is_synchronized(self, info: ClassInfo, attr: str) -> bool:
        """True when every inferred class for the attribute owns locks
        of its own — an internally-synchronized collaborator whose
        discipline is ITS OWN rules' business, not the holder's."""
        candidates = self.attr_classes(info, attr)
        return bool(candidates) and all(c.locks for c in candidates)


def scan_scope(project: Project, paths: Iterable[str]) -> ScopeModel:
    model = ScopeModel()
    for top in paths:
        for rel in project.walk(top):
            ctx = project.file(rel)
            if ctx is None or ctx.tree is None:
                continue
            stem = os.path.splitext(os.path.basename(rel))[0]
            mod_locks = module_lock_keys(ctx.tree, stem)
            model.module_locks[rel] = mod_locks
            model.all_locks |= mod_locks
            for node in ast.iter_child_nodes(ctx.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    model.functions.append((rel, stem, None, node))
                elif isinstance(node, ast.ClassDef):
                    info = ClassInfo(rel, stem, node)
                    info.locks = class_lock_keys(node, stem)
                    model.all_locks |= info.locks
                    model.classes[node.name] = info
                    for m in info.methods.values():
                        model.functions.append((rel, stem, node.name, m))
    # Attribute types: self.X = SomeIndexedClass(...) anywhere in the
    # class (constructors are usually __init__, but late binds count).
    for info in model.classes.values():
        for m in info.methods.values():
            for node in ast.walk(m):
                if not isinstance(node, ast.Assign):
                    continue
                called: Set[str] = set()
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call):
                        cname = dotted_name(sub.func)
                        if cname is None:
                            continue
                        last = cname.rsplit(".", 1)[-1]
                        if last in model.classes:
                            called.add(last)
                if not called:
                    continue
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        info.attr_types.setdefault(tgt.attr, set()).update(
                            called
                        )
    return model
