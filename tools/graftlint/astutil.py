"""Small AST helpers shared by graftlint rules (stdlib ``ast`` only)."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

__all__ = [
    "dotted_name",
    "call_name",
    "last_component",
    "walk_calls",
    "is_jit_decorator",
    "jitted_functions",
    "literal_str",
]


def last_component(name: Optional[str]) -> Optional[str]:
    """Final segment of a dotted name (``psum`` for ``jax.lax.psum``);
    passes None through — the match-by-last-component idiom the SPMD
    rules share."""
    return name.rsplit(".", 1)[-1] if name else None


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    """Dotted name of a call's callee (``obs.span`` for ``obs.span(...)``)."""
    return dotted_name(call.func)


def walk_calls(node: ast.AST) -> Iterator[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def _is_jit_callable(node: ast.AST) -> bool:
    """True for ``jax.jit`` / ``jit`` / ``pjit`` / ``jax.pjit`` refs."""
    name = dotted_name(node)
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1]
    return last in ("jit", "pjit")


def is_jit_decorator(dec: ast.expr) -> bool:
    """Decorator forms that make the function body a traced program:
    ``@jax.jit``, ``@jit``, ``@pjit``, ``@partial(jax.jit, ...)``,
    ``@functools.partial(pjit, ...)``."""
    if _is_jit_callable(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_jit_callable(dec.func):
            return True
        fname = dotted_name(dec.func)
        if fname and fname.rsplit(".", 1)[-1] == "partial" and dec.args:
            return _is_jit_callable(dec.args[0])
    return False


def jitted_functions(tree: ast.AST) -> Iterator[ast.AST]:
    """Every node whose body is traced: decorated (async) defs, plus
    the lambda or (same-module) named-function reference in inline
    ``jax.jit(f)`` call forms — ``jax.jit(_local)(x)`` traces
    ``_local``'s body exactly like a decorator would. Cross-module
    references cannot be resolved from one tree and are skipped."""
    defs_by_name = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)
    seen: set = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(is_jit_decorator(d) for d in node.decorator_list):
                if id(node) not in seen:
                    seen.add(id(node))
                    yield node
        elif isinstance(node, ast.Call) and _is_jit_callable(node.func):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Lambda):
                    yield arg
                elif (
                    isinstance(arg, ast.Name)
                    and arg.id in defs_by_name
                    and id(defs_by_name[arg.id]) not in seen
                ):
                    target = defs_by_name[arg.id]
                    seen.add(id(target))
                    yield target


def literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
