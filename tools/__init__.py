"""Repo-local developer tooling (not shipped in the wheel).

``tools.graftlint`` is the project-invariant static analyzer; run it
from the repo root as ``python -m tools.graftlint spark_examples_tpu/``.
"""
