"""The crashed-file-system model: prefixes of the op log → on-disk states.

Replaying a prefix of the recorded operations yields, per file, the
pair (latest content image, latest DURABLE image). The durable image
advances only at ``fsync``; metadata operations (``rename``,
``unlink``, ``mkdir``, ``rmdir``) are applied in order and assumed
durable — the ext4-ordered-journaling behavior the repo's commit
protocol is written against. The model's one deliberate pessimism is
the ALICE failure class: a rename moves the FILE, not a guarantee —
if the source was never fsynced, the crashed state can expose a torn
image under the DESTINATION name. That is precisely the bug shape a
missing fsync-before-rename creates, and the harness's planted-bug
test proves the model catches it.

Variant enumeration is bounded: for each crash prefix, the
most-recently-written still-volatile file gets three materializations
— ``full`` (every page made it), ``torn`` (durable floor plus half
the unsynced tail, the contiguous-truncation model), and ``floor``
(only what was fsynced; absent if nothing ever was). Other volatile
files materialize full — a legal (optimistic) outcome that keeps the
state count linear in the op count; the per-file variants still visit
every commit point because every prefix boundary makes each write the
"most recent" one somewhere in the enumeration.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.crashsim.recorder import FsOp

VARIANTS = ("full", "torn", "floor")


@dataclass
class _FileState:
    content: bytes = b""
    durable: Optional[bytes] = None  # None = never fsynced
    last_write_idx: int = -1

    @property
    def volatile(self) -> bool:
        return self.durable != self.content


@dataclass(frozen=True)
class CrashState:
    """One materializable crashed state: every op in ``ops[:n_ops]``
    happened, then the process died; ``variant`` picks the fate of the
    most-recently-written volatile file (``focus``)."""

    n_ops: int
    variant: str
    focus: Optional[str]
    files: Tuple[Tuple[str, bytes], ...]
    dirs: Tuple[str, ...]

    def describe(self) -> str:
        focus = f" focus={self.focus}" if self.focus else ""
        return f"crash@{self.n_ops}/{self.variant}{focus}"


def _move_prefix(
    table: Dict[str, _FileState], src: str, dst: str
) -> None:
    """Directory rename: move every entry under ``src/`` to ``dst/``."""
    prefix = src + "/"
    moved = [k for k in table if k == src or k.startswith(prefix)]
    for k in moved:
        new_key = dst + k[len(src):]
        table[new_key] = table.pop(k)


def _replay_prefix(
    ops: List[FsOp], n: int
) -> Tuple[Dict[str, _FileState], Set[str]]:
    files: Dict[str, _FileState] = {}
    dirs: Set[str] = set()
    for idx, op in enumerate(ops[:n]):
        if op.kind == "write":
            st = files.setdefault(op.path, _FileState())
            st.content = op.content or b""
            st.last_write_idx = idx
        elif op.kind == "fsync":
            st = files.get(op.path)
            if st is not None:
                st.durable = st.content
        elif op.kind == "rename":
            assert op.dst is not None
            if op.path in files:
                files[op.dst] = files.pop(op.path)
            else:
                # Directory rename (or a file the recorder never saw a
                # write for): move the subtree.
                _move_prefix(files, op.path, op.dst)
                moved_dirs = {
                    d
                    for d in dirs
                    if d == op.path or d.startswith(op.path + "/")
                }
                for d in moved_dirs:
                    dirs.discard(d)
                    dirs.add(op.dst + d[len(op.path):])
        elif op.kind == "unlink":
            files.pop(op.path, None)
        elif op.kind == "mkdir":
            dirs.add(op.path)
        elif op.kind == "rmdir":
            dirs.discard(op.path)
    return files, dirs


def _torn(st: _FileState) -> bytes:
    floor = st.durable or b""
    tail = st.content[len(floor):]
    if not tail:
        # Shrinking/rewriting file: torn = half of the full image.
        return st.content[: max(0, len(st.content) // 2)]
    return floor + tail[: len(tail) // 2]


def enumerate_crash_states(ops: List[FsOp]) -> Iterator[CrashState]:
    """Every (prefix, variant) crashed state, deduplicated: prefixes
    whose materialized image is identical to an already-yielded one
    (e.g. consecutive metadata ops on paths that do not change file
    fates) still yield — the check is cheap and keeping the mapping
    prefix→state 1:1 makes violations easy to localize."""
    for n in range(len(ops) + 1):
        files, dirs = _replay_prefix(ops, n)
        focus: Optional[str] = None
        focus_idx = -1
        for path, st in files.items():
            if st.volatile and st.last_write_idx > focus_idx:
                focus = path
                focus_idx = st.last_write_idx
        variants = VARIANTS if focus is not None else ("full",)
        for variant in variants:
            out: List[Tuple[str, bytes]] = []
            for path, st in sorted(files.items()):
                if path == focus:
                    if variant == "torn":
                        out.append((path, _torn(st)))
                    elif variant == "floor":
                        if st.durable is not None:
                            out.append((path, st.durable))
                        # never-synced + floor → file absent
                    else:
                        out.append((path, st.content))
                else:
                    # Non-focus files: full image (optimistic-legal).
                    out.append((path, st.content))
            yield CrashState(
                n_ops=n,
                variant=variant,
                focus=focus,
                files=tuple(out),
                dirs=tuple(sorted(dirs)),
            )


def materialize(state: CrashState, dest: str) -> None:
    """Write the crashed state into ``dest`` (a fresh directory).

    Everything is back-dated an hour: recovery code that ages
    artifacts by mtime (the store's stale-CAS-mutex breaker, lock-file
    staleness) must see the crash as PAST, not as a racing live peer —
    a freshly-materialized lock dir with a now-mtime would make
    recovery wait out a holder that no longer exists."""
    os.makedirs(dest, exist_ok=True)
    stamp = time.time() - 3600.0
    for d in state.dirs:
        os.makedirs(os.path.join(dest, d), exist_ok=True)
    for rel, content in state.files:
        full = os.path.join(dest, rel)
        os.makedirs(os.path.dirname(full) or dest, exist_ok=True)
        with open(full, "wb") as f:
            f.write(content)
        os.utime(full, (stamp, stamp))
    for d in sorted(state.dirs, reverse=True):
        try:
            os.utime(os.path.join(dest, d), (stamp, stamp))
        except OSError:
            pass


@dataclass
class CrashInfo:
    """What the recovery check may know about the crash: the op prefix
    (the ground truth of what HAPPENED before the lights went out) and
    the variant chosen for the focus file."""

    ops: List[FsOp] = field(default_factory=list)
    variant: str = "full"
    focus: Optional[str] = None

    def renames_to(self, suffix: str) -> int:
        return sum(
            1
            for op in self.ops
            if op.kind == "rename"
            and op.dst is not None
            and op.dst.endswith(suffix)
        )

    def fsyncs_of(self, suffix: str) -> int:
        return sum(
            1
            for op in self.ops
            if op.kind == "fsync" and op.path.endswith(suffix)
        )

    def writes_of(self, suffix: str) -> List[bytes]:
        return [
            op.content or b""
            for op in self.ops
            if op.kind == "write" and op.path.endswith(suffix)
        ]


__all__ = [
    "VARIANTS",
    "CrashState",
    "CrashInfo",
    "enumerate_crash_states",
    "materialize",
]
