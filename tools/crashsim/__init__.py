"""crashsim — ALICE-style crash-consistency checking for the repo's
persistence surfaces.

The deterministic chaos suite kills processes at the torn-write seams
(``faults.inject_write``) — one crash point per seam, chosen by hand.
crashsim inverts that: it RECORDS the file-system operation sequence a
real workload performs (every ``open``-for-write capture, ``os.fsync``,
``os.rename``/``os.replace``, ``unlink``/``mkdir``/``rmdir`` under a
scratch root), then enumerates EVERY crash prefix of that log,
materializes each crashed state in a fresh directory under a
pessimistic-but-legal file-system model, runs the real recovery path
(store reload, journal replay, staging reuse, lease reacquire, delta
reload, flight-record parse), and asserts the pinned invariants:

- **committed-value-survives** — once the atomic rename is in the
  prefix, recovery sees the committed value, whole;
- **no-partial-visible** — a file visible under its final name is
  never torn (the fsync-before-rename order made durable what the
  rename published);
- **replay byte-identity** — journal replay of a crashed log is a
  prefix of the appended events and is stable across re-replays;
- **fencing floor monotone** — a lease doc is never torn, so the
  token floor survives every crash.

The model (``tools/crashsim/model.py``) is deliberately conservative
in the direction that finds bugs: unfsynced ("volatile") content
propagates THROUGH renames — a rename publishes whatever the data
pages happen to hold, which is exactly how a missing
fsync-before-rename surfaces a torn file under a committed name (the
ALICE "All File Systems Are Not Created Equal" failure class, OSDI
'14). Renames, unlinks, and mkdirs are treated as ordered and durable
(ext4-ordered journaling); per crash prefix, torn variants are
enumerated for the most-recently-written volatile file and the
contiguous-tail-truncation model stands in for arbitrary page
reordering. ``os.open``-level I/O (directory fsyncs, mutex lock dirs'
mtimes) is below the interposition layer; both limits are documented
in docs/STATIC_ANALYSIS.md.

Run it: ``python -m tools.crashsim`` (``--list`` for scenarios,
``--out`` for a JSONL report, exit 1 on any violation).
"""

from tools.crashsim.model import CrashState, enumerate_crash_states
from tools.crashsim.recorder import FsOp, OpRecorder

__all__ = [
    "CrashState",
    "FsOp",
    "OpRecorder",
    "enumerate_crash_states",
]
