"""File-system operation recording for crash-prefix enumeration.

:class:`OpRecorder` is a context manager that patches ``builtins.open``
and the ``os``-level metadata operations for the duration of a
workload, recording every durability-relevant operation on paths under
one scratch root. Python cannot interpose on libc ``write(2)`` without
a C shim, so writes are captured as FULL-FILE IMAGES at the moments
the page cache state is knowable from userspace: ``flush()``,
``close()``, and ``os.fsync(fd)``. That granularity is exactly the
granularity the repo's own commit discipline exposes — every persisted
write flushes before it fsyncs and fsyncs before it renames — and it
keeps the op log small enough to enumerate every prefix.

Only paths under ``root`` are recorded; everything else (imports,
telemetry, the test harness's own files) passes straight through to
the real functions. The recorder is process-global while active
(``builtins.open`` has no narrower scope), so it is NOT reentrant and
not thread-safe against concurrent recorders — one workload at a time,
which is what the harness does.
"""

from __future__ import annotations

import builtins
import os
from dataclasses import dataclass
from typing import IO, Any, Callable, Dict, List, Optional


@dataclass(frozen=True)
class FsOp:
    """One recorded operation. ``kind`` is ``write``/``fsync``/
    ``rename``/``unlink``/``mkdir``/``rmdir``; paths are relative to
    the recorder root; ``content`` is the full file image for
    ``write`` ops (None otherwise); ``dst`` is set for ``rename``."""

    kind: str
    path: str
    content: Optional[bytes] = None
    dst: Optional[str] = None


_WRITE_MODE_CHARS = ("w", "a", "x", "+")


def _is_write_mode(mode: str) -> bool:
    return any(c in mode for c in _WRITE_MODE_CHARS)


class _RecordingFile:
    """Forwarding proxy around a real file object that snapshots the
    on-disk image at every flush/close (after forwarding the call, so
    the snapshot reads what the OS actually has)."""

    def __init__(self, recorder: "OpRecorder", f: IO[Any], path: str):
        self._recorder = recorder
        self._f = f
        self._path = path

    # -- the capture points ----------------------------------------------------

    def flush(self) -> None:
        self._f.flush()
        self._recorder._capture(self._path)

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._recorder._capture(self._path)
        self._recorder._forget_fd(self)
        self._f.close()

    # -- plumbing --------------------------------------------------------------

    def __enter__(self) -> "_RecordingFile":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def __iter__(self) -> Any:
        return iter(self._f)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._f, name)


class OpRecorder:
    """Record durability-relevant fs ops under ``root`` while active."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        self.ops: List[FsOp] = []
        self._last_image: Dict[str, bytes] = {}
        self._open_files: Dict[int, _RecordingFile] = {}
        self._orig: Dict[str, Callable[..., Any]] = {}
        self._active = False

    # -- path helpers ----------------------------------------------------------

    def _rel(self, path: Any) -> Optional[str]:
        """Repo-root-relative path when under the recorder root, else
        None (op not recorded)."""
        try:
            abspath = os.path.abspath(os.fspath(path))
        except TypeError:
            return None  # fd-based or path-like we can't resolve
        if abspath == self.root:
            return "."
        if not abspath.startswith(self.root + os.sep):
            return None
        return os.path.relpath(abspath, self.root)

    # -- capture ---------------------------------------------------------------

    def _capture(self, rel: str) -> None:
        """Snapshot the current on-disk image of ``rel`` and record a
        write op if it changed since the last snapshot."""
        full = os.path.join(self.root, rel)
        try:
            with self._orig["open"](full, "rb") as f:  # type: ignore[no-any-return]
                content = f.read()
        except OSError:
            return
        if self._last_image.get(rel) == content:
            return
        self._last_image[rel] = content
        self.ops.append(FsOp("write", rel, content=content))

    def _forget_fd(self, proxy: _RecordingFile) -> None:
        self._open_files = {
            fd: p for fd, p in self._open_files.items() if p is not proxy
        }

    # -- patched entry points --------------------------------------------------

    def _open(self, file: Any, mode: str = "r", *args: Any, **kw: Any) -> Any:
        f = self._orig["open"](file, mode, *args, **kw)
        rel = self._rel(file) if isinstance(mode, str) else None
        if rel is None or not _is_write_mode(mode):
            return f
        proxy = _RecordingFile(self, f, rel)
        try:
            self._open_files[f.fileno()] = proxy
        except (OSError, ValueError):
            pass
        return proxy

    def _fsync(self, fd: int) -> None:
        self._orig["os.fsync"](fd)
        proxy = self._open_files.get(fd)
        if proxy is not None:
            self._capture(proxy._path)
            self.ops.append(FsOp("fsync", proxy._path))

    def _rename_like(self, name: str) -> Callable[..., Any]:
        orig = self._orig[name]

        def patched(src: Any, dst: Any, **kw: Any) -> Any:
            result = orig(src, dst, **kw)
            rel_src, rel_dst = self._rel(src), self._rel(dst)
            if rel_src is not None and rel_dst is not None:
                self.ops.append(FsOp("rename", rel_src, dst=rel_dst))
                # The image (and its durability) travels with the file.
                if rel_src in self._last_image:
                    self._last_image[rel_dst] = self._last_image.pop(
                        rel_src
                    )
            return result

        return patched

    def _meta(self, name: str, kind: str) -> Callable[..., Any]:
        orig = self._orig[name]

        def patched(path: Any, *args: Any, **kw: Any) -> Any:
            result = orig(path, *args, **kw)
            rel = self._rel(path)
            if rel is not None:
                self.ops.append(FsOp(kind, rel))
                if kind == "unlink":
                    self._last_image.pop(rel, None)
            return result

        return patched

    # -- lifecycle -------------------------------------------------------------

    def __enter__(self) -> "OpRecorder":
        if self._active:
            raise RuntimeError("OpRecorder is not reentrant")
        self._active = True
        self._orig = {
            "open": builtins.open,
            "os.fsync": os.fsync,
            "os.rename": os.rename,
            "os.replace": os.replace,
            "os.unlink": os.unlink,
            "os.remove": os.remove,
            "os.mkdir": os.mkdir,
            "os.rmdir": os.rmdir,
        }
        builtins.open = self._open  # type: ignore[assignment]
        os.fsync = self._fsync  # type: ignore[assignment]
        os.rename = self._rename_like("os.rename")  # type: ignore[assignment]
        os.replace = self._rename_like("os.replace")  # type: ignore[assignment]
        os.unlink = self._meta("os.unlink", "unlink")  # type: ignore[assignment]
        os.remove = self._meta("os.remove", "unlink")  # type: ignore[assignment]
        os.mkdir = self._meta("os.mkdir", "mkdir")  # type: ignore[assignment]
        os.rmdir = self._meta("os.rmdir", "rmdir")  # type: ignore[assignment]
        return self

    def __exit__(self, *exc: Any) -> None:
        builtins.open = self._orig["open"]  # type: ignore[assignment]
        os.fsync = self._orig["os.fsync"]  # type: ignore[assignment]
        os.rename = self._orig["os.rename"]  # type: ignore[assignment]
        os.replace = self._orig["os.replace"]  # type: ignore[assignment]
        os.unlink = self._orig["os.unlink"]  # type: ignore[assignment]
        os.remove = self._orig["os.remove"]  # type: ignore[assignment]
        os.mkdir = self._orig["os.mkdir"]  # type: ignore[assignment]
        os.rmdir = self._orig["os.rmdir"]  # type: ignore[assignment]
        self._open_files.clear()
        self._active = False


__all__ = ["FsOp", "OpRecorder"]
