"""Run scenarios: record once, crash everywhere, recover every state.

``run_scenario`` is the whole loop: run the workload under the
recorder in a ``work/`` directory, enumerate every crash state from
the op log, materialize each into its own ``crash-<n>-<variant>/``
directory, and run the scenario's check (which exercises the REAL
recovery code) against it. Checks also run against the live post-
workload tree — the zero-crash case must obviously pass too, and a
check that fails there is a broken check, not a durability bug.

A check raising is itself a violation: recovery code that throws on a
legal crashed state is exactly the failure the harness exists to find
(the pre-round-19 flight recorder would have failed this way — a torn
dump raising ``json.JSONDecodeError`` in the reader).
"""

from __future__ import annotations

import json
import os
import shutil
import traceback
from dataclasses import dataclass
from typing import IO, List, Optional

from tools.crashsim.model import (
    CrashInfo,
    enumerate_crash_states,
    materialize,
)
from tools.crashsim.recorder import OpRecorder
from tools.crashsim.scenarios import Scenario


@dataclass(frozen=True)
class Violation:
    scenario: str
    n_ops: int
    variant: str
    focus: Optional[str]
    message: str


@dataclass
class ScenarioResult:
    scenario: str
    n_ops: int
    n_states: int
    violations: List[Violation]

    @property
    def ok(self) -> bool:
        return not self.violations


def run_scenario(
    scenario: Scenario, workdir: str, keep_failures: bool = False
) -> ScenarioResult:
    """Record ``scenario`` under ``workdir`` and check every crashed
    state. Crashed-state directories are deleted as they pass; with
    ``keep_failures`` the violating ones stay on disk for autopsy."""
    os.makedirs(workdir, exist_ok=True)
    live_root = os.path.join(workdir, "work")
    os.makedirs(live_root)
    recorder = OpRecorder(live_root)
    with recorder:
        scenario.workload(live_root)

    violations: List[Violation] = []
    full_info = CrashInfo(ops=list(recorder.ops), variant="full")
    live_msg = _run_check(scenario, live_root, full_info)
    if live_msg is not None:
        violations.append(
            Violation(scenario.name, len(recorder.ops), "live", None,
                      f"check fails on the UNCRASHED tree: {live_msg}")
        )

    n_states = 0
    for state in enumerate_crash_states(recorder.ops):
        n_states += 1
        dest = os.path.join(
            workdir, f"crash-{state.n_ops:03d}-{state.variant}"
        )
        materialize(state, dest)
        info = CrashInfo(
            ops=list(recorder.ops[: state.n_ops]),
            variant=state.variant,
            focus=state.focus,
        )
        msg = _run_check(scenario, dest, info)
        if msg is not None:
            violations.append(
                Violation(
                    scenario.name, state.n_ops, state.variant,
                    state.focus, msg,
                )
            )
            if keep_failures:
                continue
        shutil.rmtree(dest, ignore_errors=True)
    return ScenarioResult(
        scenario=scenario.name,
        n_ops=len(recorder.ops),
        n_states=n_states,
        violations=violations,
    )


def _run_check(
    scenario: Scenario, root: str, info: CrashInfo
) -> Optional[str]:
    try:
        return scenario.check(root, info)
    except Exception:  # noqa: BLE001 - a throwing recovery IS the finding
        tail = traceback.format_exc().strip().splitlines()[-1]
        return f"recovery raised on a legal crashed state: {tail}"


def write_report(
    results: List[ScenarioResult], stream: IO[str]
) -> None:
    """One JSONL line per scenario plus one per violation — the same
    shape the graftlint CI legs tee into their artifacts."""
    for res in results:
        stream.write(
            json.dumps(
                {
                    "kind": "scenario",
                    "scenario": res.scenario,
                    "ops": res.n_ops,
                    "states": res.n_states,
                    "violations": len(res.violations),
                    "ok": res.ok,
                },
                sort_keys=True,
            )
            + "\n"
        )
        for v in res.violations:
            stream.write(
                json.dumps(
                    {
                        "kind": "violation",
                        "scenario": v.scenario,
                        "crash_ops": v.n_ops,
                        "variant": v.variant,
                        "focus": v.focus,
                        "message": v.message,
                    },
                    sort_keys=True,
                )
                + "\n"
            )


__all__ = [
    "ScenarioResult",
    "Violation",
    "run_scenario",
    "write_report",
]
