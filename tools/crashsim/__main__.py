"""CLI: ``python -m tools.crashsim`` — crash every commit point, then
recover.

Exit 0 when every crashed state recovers cleanly, 1 on any violation,
2 on usage errors. ``--iters`` repeats the whole sweep (the workloads
are deterministic, but repetition shakes out tmpfile-name and
dict-order sensitivity in recovery); ``--out`` tees a JSONL report for
the CI artifact.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
from typing import List

from tools.crashsim.harness import (
    ScenarioResult,
    run_scenario,
    write_report,
)
from tools.crashsim.scenarios import SCENARIOS


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.crashsim",
        description=(
            "Record each persistence workload, enumerate every crash "
            "prefix, materialize the crashed states, and run the real "
            "recovery code against each."
        ),
    )
    parser.add_argument(
        "--list", action="store_true", help="list scenarios and exit"
    )
    parser.add_argument(
        "--scenario",
        action="append",
        default=None,
        metavar="NAME",
        help="run only this scenario (repeatable)",
    )
    parser.add_argument(
        "--iters",
        type=int,
        default=1,
        metavar="N",
        help="repeat the full sweep N times (default 1)",
    )
    parser.add_argument(
        "--out",
        default=None,
        metavar="PATH",
        help="write a JSONL report to PATH",
    )
    parser.add_argument(
        "--keep-failures",
        action="store_true",
        help="keep violating crashed-state directories for autopsy",
    )
    args = parser.parse_args(argv)

    if args.list:
        for sc in SCENARIOS:
            print(f"{sc.name:18s} {sc.summary}")
        return 0

    selected = list(SCENARIOS)
    if args.scenario:
        by_name = {sc.name: sc for sc in SCENARIOS}
        unknown = [n for n in args.scenario if n not in by_name]
        if unknown:
            print(
                f"unknown scenario(s): {', '.join(unknown)} "
                f"(--list shows the choices)",
                file=sys.stderr,
            )
            return 2
        selected = [by_name[n] for n in args.scenario]
    if args.iters < 1:
        print("--iters must be >= 1", file=sys.stderr)
        return 2

    results: List[ScenarioResult] = []
    for i in range(args.iters):
        for sc in selected:
            with tempfile.TemporaryDirectory(
                prefix=f"crashsim-{sc.name}-"
            ) as workdir:
                res = run_scenario(
                    sc,
                    os.path.join(workdir, f"iter-{i}"),
                    keep_failures=args.keep_failures,
                )
            results.append(res)
            status = "ok" if res.ok else "FAIL"
            print(
                f"[crashsim] {sc.name:18s} iter {i}: {res.n_ops:3d} ops, "
                f"{res.n_states:3d} crashed states, "
                f"{len(res.violations)} violation(s) -- {status}"
            )
            for v in res.violations:
                print(
                    f"[crashsim]   crash@{v.n_ops}/{v.variant}"
                    f"{' focus=' + v.focus if v.focus else ''}: "
                    f"{v.message}"
                )

    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            write_report(results, f)

    total = sum(len(r.violations) for r in results)
    states = sum(r.n_states for r in results)
    print(
        f"[crashsim] {len(results)} scenario run(s), {states} crashed "
        f"state(s), {total} violation(s)"
    )
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
