"""The recorded workloads and their recovery invariants.

One scenario per commit discipline the repo hand-enforces: the durable
store's blob put and lease CAS, the job journal's append+replay, the
mirror's staging commit+promote, the delta cache's persist-dir
write-through, and the flight recorder's dump. Each scenario is a
(workload, check) pair: the workload runs ONCE under the
:class:`~tools.crashsim.recorder.OpRecorder`; the check runs once per
enumerated crashed state, against a directory materialized by the
model, and returns a violation message or None. Checks run the REAL
recovery code — ``LocalDirStore`` reads, ``JobJournal.replay_events``,
``DeltaIndex``'s load-and-sweep, a fresh ``lease_acquire`` — because
the invariant is about what recovery DOES, not about what the bytes
look like.

Workloads draw journal events from the GL015 registry
(``serving/journal_schema.py``): the static rule, the mixed-version
replay test, and this harness must all describe the same records.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tools.crashsim.model import CrashInfo

Check = Callable[[str, CrashInfo], Optional[str]]


@dataclass(frozen=True)
class Scenario:
    name: str
    summary: str
    workload: Callable[[str], None]
    check: Check


# -- store blob put ----------------------------------------------------------

_V1 = b"value-one" * 13
_V2 = b"value-two!" * 17


def _store_put_workload(root: str) -> None:
    from spark_examples_tpu.store import LocalDirStore

    store = LocalDirStore(root)
    store.put("jobs/a", _V1)
    store.put("jobs/a", _V2)


def _store_put_check(root: str, info: CrashInfo) -> Optional[str]:
    from spark_examples_tpu.store import LocalDirStore, StoreCorruptError

    store = LocalDirStore(root)
    commits = info.renames_to("objects/jobs/a")
    try:
        data: Optional[bytes] = store.get("jobs/a")
    except KeyError:
        data = None
    except StoreCorruptError as e:
        return f"torn blob visible under committed name: {e}"
    if commits == 0:
        if data is not None:
            return "uncommitted value visible before any rename"
    elif commits == 1:
        if data != _V1:
            return "committed v1 lost or mutated after its rename"
    else:
        if data != _V2:
            return "committed v2 lost or mutated after its rename"
    return None


# -- store lease CAS ---------------------------------------------------------


def _lease_workload(root: str) -> None:
    from spark_examples_tpu.store import LocalDirStore

    clock_now = [1000.0]
    store = LocalDirStore(root, clock=lambda: clock_now[0])
    lease = store.lease_acquire("replica-a", "owner-1", ttl_s=5.0)
    assert lease is not None and lease.token == 1
    clock_now[0] += 60.0  # owner-1 expires: takeover path, not release
    lease = store.lease_acquire("replica-a", "owner-2", ttl_s=5.0)
    assert lease is not None and lease.token == 2
    store.lease_renew(lease, ttl_s=5.0)


def _lease_check(root: str, info: CrashInfo) -> Optional[str]:
    from spark_examples_tpu.store import LocalDirStore

    doc_path = os.path.join(root, "leases", "replica-a.json")
    visible_token = 0
    if os.path.exists(doc_path):
        try:
            with open(doc_path, "rb") as f:
                doc = json.loads(f.read().decode("utf-8"))
            visible_token = int(doc["token"])
        except (ValueError, KeyError) as e:
            # THE fencing-floor invariant: a torn doc reads as "no
            # lease", which resets the token floor — fsync-before-
            # rename is what makes this state unreachable.
            return f"lease doc torn under committed name: {e}"
    commits = info.renames_to("leases/replica-a.json")
    expected = {0: 0, 1: 1, 2: 2, 3: 2}.get(commits, 2)
    if visible_token != expected:
        return (
            f"lease token {visible_token} visible after {commits} "
            f"committed CAS rename(s); expected {expected}"
        )
    # Recovery: every lease long-expired (workload clock was synthetic
    # epoch-1000); a fresh acquire must land STRICTLY above the floor.
    store = LocalDirStore(root)
    got = store.lease_acquire("replica-a", "owner-recover", ttl_s=5.0)
    if got is None:
        return "post-crash lease acquire rejected by an expired holder"
    if got.token <= visible_token:
        return (
            f"fencing floor regressed: reacquired token {got.token} "
            f"<= visible committed token {visible_token}"
        )
    return None


# -- journal append ----------------------------------------------------------


def _journal_events() -> List[Dict[str, object]]:
    """Registry-shaped events — the same keys GL015 checks writers
    against. Kept import-light: the registry is data, not machinery."""
    from spark_examples_tpu.serving import journal_schema as js

    spec = {"kind": "pca", "tenant": "t0", "num_pc": 2}
    events: List[Dict[str, object]] = [
        {
            "e": "submit",
            "id": "job-1",
            "seq": 1,
            "key": "cohort-1",
            "spec": spec,
            "ts": 1000.0,
            "trace": "trace-1",
        },
        {"e": "start", "id": "job-1"},
        {"e": "done", "id": "job-1", "rows": 3},
        {
            "e": "submit",
            "id": "job-2",
            "seq": 2,
            "key": "cohort-2",
            "spec": spec,
            "ts": 1001.0,
            "trace": "trace-2",
            "replica": "r-1",
            "fence": 4,
        },
    ]
    for ev in events:
        assert ev["e"] in js.JOURNAL_EVENT_KINDS
        assert set(ev) <= js.JOURNAL_KEYS
    return events


def _journal_workload(root: str) -> None:
    from spark_examples_tpu.serving.jobs import JobJournal

    events = _journal_events()
    journal = JobJournal(root)
    try:
        journal.append(events[0])
        journal.append(events[1])
        journal.flush()  # fsync: events 0-1 are the durable floor
        journal.append(events[2])
        journal.append(events[3])
    finally:
        journal.close()


def _journal_check(root: str, info: CrashInfo) -> Optional[str]:
    from spark_examples_tpu.serving.jobs import JobJournal

    expected = _journal_events()
    got = list(JobJournal.replay_events(root))
    if got != expected[: len(got)]:
        return (
            f"replay is not a prefix of the appended events: got "
            f"{len(got)} event(s), first divergence at "
            f"{next(i for i, (a, b) in enumerate(zip(got, expected)) if a != b)}"
        )
    if info.fsyncs_of("journal.jsonl") >= 1 and len(got) < 2:
        return (
            f"durable floor lost: the pre-crash flush() fsynced events "
            f"0-1 but replay recovered only {len(got)}"
        )
    again = list(JobJournal.replay_events(root))
    if again != got:
        return "replay is not byte-identical across re-replays"
    return None


# -- mirror staging ----------------------------------------------------------

_MIRROR_FILES: Tuple[Tuple[str, bytes], ...] = (
    ("variants.avro", b"A" * 307),
    ("callsets.avro", b"B" * 211),
)


def _mirror_workload(root: str) -> None:
    from spark_examples_tpu.genomics.mirror import _commit_tmp, _fsync_dir

    staging = os.path.join(root, "staging")
    final = os.path.join(root, "mirror")
    os.makedirs(staging)
    for name, content in _MIRROR_FILES:
        tmp = os.path.join(staging, f"{name}.tmp-{os.getpid()}")
        with open(tmp, "wb") as f:
            f.write(content)
        _commit_tmp(tmp, os.path.join(staging, name))
    os.rename(staging, final)  # the atomic promote
    _fsync_dir(root)


def _mirror_check(root: str, info: CrashInfo) -> Optional[str]:
    expected = dict(_MIRROR_FILES)
    for sub in ("mirror", "staging"):
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for name in os.listdir(base):
            if ".tmp-" in name:
                continue  # partials under tmp names are never trusted
            with open(os.path.join(base, name), "rb") as f:
                content = f.read()
            if content != expected.get(name):
                return (
                    f"{sub}/{name} visible under its committed name "
                    f"with {len(content)} byte(s) instead of "
                    f"{len(expected.get(name, b''))} — partial commit"
                )
    if info.renames_to("mirror"):
        base = os.path.join(root, "mirror")
        if not os.path.isdir(base):
            return "promoted mirror directory missing after its rename"
        names = {n for n in os.listdir(base) if ".tmp-" not in n}
        if names != set(expected):
            return (
                f"promoted mirror incomplete: {sorted(names)} != "
                f"{sorted(expected)}"
            )
    return None


# -- delta persist -----------------------------------------------------------

_DELTA_BASE_KEY = "basekey-0123456789abcdef"
_DELTA_SAMPLES = ("HG00096", "HG00097")


def _delta_g() -> np.ndarray:
    rng = np.random.RandomState(7)
    g = rng.standard_normal((4, 4)).astype(np.float32)
    return (g + g.T).astype(np.float32)


def _delta_workload(root: str) -> None:
    from spark_examples_tpu.serving.deltas import DeltaIndex

    index = DeltaIndex(persist_dir=os.path.join(root, "deltas"))
    index.put(_DELTA_BASE_KEY, _DELTA_SAMPLES, _delta_g())


def _delta_check(root: str, info: CrashInfo) -> Optional[str]:
    from spark_examples_tpu.serving.deltas import DeltaIndex

    pdir = os.path.join(root, "deltas")
    index = DeltaIndex(persist_dir=pdir)  # startup load sweeps partials
    n = len(index)
    if n not in (0, 1):
        return f"delta reload produced {n} entries from one persist"
    committed = info.renames_to(".npz")
    if committed and n != 1:
        return "committed delta entry lost: persisted rename landed " \
            "but reload found nothing"
    if n == 1:
        # Reaching into the index is fine here: bit-identity of the
        # reloaded G IS the invariant, and resolve() would re-wrap it.
        (entry,) = index._entries.values()
        if not entry.verify():
            return "reloaded delta entry fails its own checksum"
        if not np.array_equal(entry.g, _delta_g()):
            return "reloaded delta entry is not bit-identical"
    if os.path.isdir(pdir):
        leftover = [x for x in os.listdir(pdir) if ".tmp-" in x]
        if leftover:
            return f"startup sweep left partials behind: {leftover}"
    return None


# -- flight recorder dump ----------------------------------------------------


def _flightrec_workload(root: str) -> None:
    from spark_examples_tpu.obs.flightrec import FlightRecorder

    rec = FlightRecorder(capacity_per_thread=16)
    rec.note("state", "serving.start", {"port": 1234})
    rec.note("state", "job.running", {"id": "job-1"})
    rec.note("signal", "SIGTERM")
    rec.dump(os.path.join(root, "dumps", "flight.jsonl"), "crashsim")


def _flightrec_check(root: str, info: CrashInfo) -> Optional[str]:
    path = os.path.join(root, "dumps", "flight.jsonl")
    if not os.path.exists(path):
        return None  # crash before the commit: no dump is a fine dump
    with open(path, "rb") as f:
        lines = f.read().splitlines()
    if not lines:
        return "empty flight record visible under the committed name"
    for i, raw in enumerate(lines):
        try:
            doc = json.loads(raw)
        except ValueError:
            return (
                f"flight record torn under its committed name "
                f"(line {i + 1} of {len(lines)} unparseable) — the "
                "dump that exists FOR the incident is unreadable "
                "during one"
            )
        if i == 0 and "schema" not in doc:
            return "flight record first line lacks the schema header"
    return None


SCENARIOS: Tuple[Scenario, ...] = (
    Scenario(
        "store-put",
        "LocalDirStore.put: committed blob survives whole, torn "
        "partials only ever exist under .tmp- names",
        _store_put_workload,
        _store_put_check,
    ),
    Scenario(
        "store-lease-cas",
        "lease CAS: the doc is never torn, the fencing token floor "
        "is monotone across crash + reacquire",
        _lease_workload,
        _lease_check,
    ),
    Scenario(
        "journal-append",
        "JobJournal: replay is a prefix of appends, the flushed floor "
        "survives, re-replay is byte-identical",
        _journal_workload,
        _journal_check,
    ),
    Scenario(
        "mirror-staging",
        "mirror staging: committed files are whole, the directory "
        "promote is atomic",
        _mirror_workload,
        _mirror_check,
    ),
    Scenario(
        "delta-persist",
        "delta write-through: reload sees 0 or 1 bit-identical "
        "entries and sweeps partials",
        _delta_workload,
        _delta_check,
    ),
    Scenario(
        "flightrec-dump",
        "flight recorder: a dump visible under its final name always "
        "parses",
        _flightrec_workload,
        _flightrec_check,
    ),
)


__all__ = ["Scenario", "SCENARIOS", "Check"]
