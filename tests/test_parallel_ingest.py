"""Parallel native ingest engine: multi-worker CSR→packed-block production.

Contract under test (arrays/blocks.py + models/pca.py routing):

- ``packed_blocks_from_csr(workers=1)`` under the numpy fallback is
  BYTE-identical to the historical composition
  ``pack_indicator_block(b) for b in blocks_from_csr(...)`` — the
  goldens-unchanged guarantee;
- the native scatter, any worker count, and any block completion order
  leave G bit-identical (integer-exact accumulation);
- multi-worker native production clears the ≥2× throughput bar over the
  single-worker Python path (TestIngestPerfAcceptance — deterministic,
  CPU, same style as test_wire_format.py::TestPerfAcceptance).
"""

import os
import time

import numpy as np
import pytest

from spark_examples_tpu.arrays.blocks import (
    blocks_from_csr,
    csr_windows,
    packed_blocks_from_csr,
)
from spark_examples_tpu.native import force_fallback as _force_python_fallback
from spark_examples_tpu.native import load
from spark_examples_tpu.ops.gramian import pack_indicator_block

_NATIVE = load() is not None and hasattr(load(), "csr_to_packed_blocks")


def _random_pairs(rng, n_shards, n_samples, max_rows):
    """Per-shard CSR pairs, including empty shards (None and 0-row)."""
    pairs = []
    for _ in range(n_shards):
        roll = rng.random()
        if roll < 0.1:
            pairs.append(None)
            continue
        rows = int(rng.integers(0, max_rows))
        lens = rng.integers(0, n_samples + 1, rows)
        idx = (
            np.concatenate(
                [
                    rng.choice(n_samples, size=n, replace=False)
                    for n in lens
                ]
            ).astype(np.int64)
            if lens.sum()
            else np.zeros(0, np.int64)
        )
        offs = np.zeros(rows + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        pairs.append((idx, offs))
    return pairs


def _legacy_packed(pairs, n_samples, block_variants):
    return [
        pack_indicator_block(b)
        for b in blocks_from_csr(iter(pairs), n_samples, block_variants)
    ]


def _g_of(packed_blocks, n_samples):
    """Accumulate packed blocks on the (CPU) device accumulator."""
    from spark_examples_tpu.ops.gramian import gramian_blockwise

    return np.asarray(
        gramian_blockwise(
            iter(packed_blocks), n_samples, packed=True, prepacked=True
        )
    )


class TestPackedBlockProduction:
    N, BV = 37, 24

    @pytest.fixture()
    def pairs(self):
        return _random_pairs(np.random.default_rng(11), 12, self.N, 40)

    def test_serial_fallback_reproduces_legacy_bytes(self, pairs):
        """workers=1 + numpy fallback ≡ today's pipeline, byte for byte
        (the goldens-unchanged acceptance criterion)."""
        want = _legacy_packed(pairs, self.N, self.BV)
        with _force_python_fallback():
            got = list(
                packed_blocks_from_csr(iter(pairs), self.N, self.BV, workers=1)
            )
        assert len(got) == len(want)
        for a, b in zip(want, got):
            assert a.tobytes() == b.tobytes()

    @pytest.mark.skipif(not _NATIVE, reason="native core unavailable")
    def test_serial_native_reproduces_legacy_bytes(self, pairs):
        want = _legacy_packed(pairs, self.N, self.BV)
        got = list(
            packed_blocks_from_csr(iter(pairs), self.N, self.BV, workers=1)
        )
        assert len(got) == len(want)
        for a, b in zip(want, got):
            assert a.tobytes() == b.tobytes()

    @pytest.mark.parametrize("workers", [2, 3, 7])
    def test_multi_worker_block_multiset_identical(self, pairs, workers):
        """Completion order may differ; the SET of blocks may not."""
        want = sorted(
            b.tobytes() for b in _legacy_packed(pairs, self.N, self.BV)
        )
        got = sorted(
            b.tobytes()
            for b in packed_blocks_from_csr(
                iter(pairs), self.N, self.BV, workers=workers
            )
        )
        assert got == want

    @pytest.mark.parametrize("workers", [1, 4])
    def test_g_bit_identical_any_workers_any_order(self, pairs, workers):
        base = _g_of(_legacy_packed(pairs, self.N, self.BV), self.N)
        got = list(
            packed_blocks_from_csr(
                iter(pairs), self.N, self.BV, workers=workers
            )
        )
        np.testing.assert_array_equal(_g_of(got, self.N), base)
        # Adversarially shuffled completion orders: G must not move.
        for seed in range(3):
            rng = np.random.default_rng(seed)
            shuffled = [got[i] for i in rng.permutation(len(got))]
            np.testing.assert_array_equal(_g_of(shuffled, self.N), base)

    def test_empty_stream_yields_no_blocks(self):
        assert list(packed_blocks_from_csr(iter([]), self.N, self.BV)) == []
        assert (
            list(
                packed_blocks_from_csr(
                    iter([None, (np.zeros(0, np.int64), np.zeros(1, np.int64))]),
                    self.N,
                    self.BV,
                    workers=3,
                )
            )
            == []
        )

    def test_windows_match_block_composition(self, pairs):
        """csr_windows is the ONE slicing stage both block builders
        share: rebuilding dense blocks from its windows must equal
        blocks_from_csr exactly."""
        want = list(blocks_from_csr(iter(pairs), self.N, self.BV))
        rebuilt = []
        for idx, lens in csr_windows(iter(pairs), self.BV):
            cols = np.repeat(np.arange(lens.size, dtype=np.int64), lens)
            x = np.zeros((self.N, self.BV), dtype=np.int8)
            x[idx, cols] = 1
            rebuilt.append(x)
        assert len(rebuilt) == len(want)
        for a, b in zip(want, rebuilt):
            np.testing.assert_array_equal(a, b)

    def test_builder_exception_surfaces(self, pairs):
        """A failing build must surface, never silently drop a block."""

        def attempt(thunk, key):
            if key == "1":
                raise IOError("builder died")
            return thunk()

        with pytest.raises(IOError, match="builder died"):
            list(
                packed_blocks_from_csr(
                    iter(pairs), self.N, self.BV, workers=3, attempt=attempt
                )
            )


class TestDriverPackedRoute:
    """The driver's CSR route through the packed production engine."""

    def _sources(self, tmp_path):
        from spark_examples_tpu.genomics.fixtures import (
            DEFAULT_VARIANT_SET_ID,
            synthetic_cohort,
        )
        from spark_examples_tpu.genomics.sources import JsonlSource

        root = str(tmp_path / "c")
        if not os.path.exists(root):
            synthetic_cohort(12, 80, seed=21).dump(root)
        return JsonlSource(root), DEFAULT_VARIANT_SET_ID

    def _g(self, tmp_path, **conf_kw):
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        source, vsid = self._sources(tmp_path)
        conf = PcaConfig(
            variant_set_ids=[vsid],
            bases_per_partition=20_000,
            block_variants=32,
            **conf_kw,
        )
        driver = VariantsPcaDriver(conf, source)
        assert driver._fused_csr_possible()
        return np.asarray(
            driver.get_similarity_matrix_csr(driver.get_csr_fused())
        )

    def test_g_identical_across_paths_workers_depth_order(self, tmp_path):
        with _force_python_fallback():
            base = self._g(tmp_path, ingest_workers=1)
        for kw in (
            dict(ingest_workers=1),
            dict(ingest_workers=3),
            dict(ingest_workers=4, prefetch_depth=4),
            dict(ingest_workers=3, ingest_order="completion"),
        ):
            np.testing.assert_array_equal(self._g(tmp_path, **kw), base)

    def test_checkpointed_csr_route_identical(self, tmp_path):
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        source, vsid = self._sources(tmp_path)
        base = self._g(tmp_path, ingest_workers=1)
        conf = PcaConfig(
            variant_set_ids=[vsid],
            bases_per_partition=20_000,
            block_variants=32,
            ingest_workers=3,
            checkpoint_dir=str(tmp_path / "ck"),
            checkpoint_every=2,
        )
        driver = VariantsPcaDriver(conf, source)
        g = np.asarray(driver.get_similarity_matrix_checkpointed())
        np.testing.assert_array_equal(g, base)

    def test_config_validation(self):
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        with pytest.raises(ValueError, match="--prefetch-depth"):
            VariantsPcaDriver(PcaConfig(prefetch_depth=0), None)
        with pytest.raises(ValueError, match="--ingest-workers"):
            VariantsPcaDriver(PcaConfig(ingest_workers=-2), None)


@pytest.mark.skipif(not _NATIVE, reason="native core unavailable")
class TestIngestPerfAcceptance:
    """CPU throughput acceptance for the parallel native engine
    (deterministic workload; the bar is intentionally far below the
    measured margin, like TestPerfAcceptance in test_wire_format.py:
    measured ≈7–15× on a 2-core container against the ≥2× bar)."""

    N, BV, NB = 512, 4096, 24

    def _pair(self):
        rng = np.random.default_rng(3)
        v = self.BV * self.NB
        x = rng.random((self.N, v)) < 0.1
        cols, rows = np.nonzero(x.T)
        lens = np.bincount(cols, minlength=v)
        offs = np.zeros(v + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        return rows.astype(np.int64), offs

    def test_multi_worker_native_at_least_2x_python_serial(self):
        pair = self._pair()
        workers = min(os.cpu_count() or 1, 4)

        def produce(n_workers):
            blocks = 0
            for _ in packed_blocks_from_csr(
                iter([pair]), self.N, self.BV, workers=n_workers
            ):
                blocks += 1
            assert blocks == self.NB

        def best(fn, repeat=3):
            fn()  # warm
            out = []
            for _ in range(repeat):
                t0 = time.perf_counter()
                fn()
                out.append(time.perf_counter() - t0)
            return min(out)

        with _force_python_fallback():
            t_python = best(lambda: produce(1))
        t_native = best(lambda: produce(workers))
        speedup = t_python / t_native
        assert speedup >= 2.0, (
            f"multi-worker native {t_native:.3f}s vs python serial "
            f"{t_python:.3f}s = {speedup:.1f}x < 2x bar"
        )

    def test_same_workload_g_bit_identical(self):
        pair = self._pair()
        native = list(
            packed_blocks_from_csr(iter([pair]), self.N, self.BV, workers=4)
        )
        with _force_python_fallback():
            python = list(
                packed_blocks_from_csr(
                    iter([pair]), self.N, self.BV, workers=1
                )
            )
        assert sorted(b.tobytes() for b in native) == sorted(
            b.tobytes() for b in python
        )
        rng = np.random.default_rng(0)
        shuffled = [native[i] for i in rng.permutation(len(native))]
        np.testing.assert_array_equal(
            _g_of(shuffled, self.N), _g_of(python, self.N)
        )
