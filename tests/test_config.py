"""Flag-surface tests: GenomicsConf/PcaConf parity wiring."""

import argparse

from spark_examples_tpu.utils.config import (
    PLATINUM_GENOMES,
    PcaConfig,
    add_pca_flags,
    pca_config_from_args,
)


def _parse(argv):
    p = argparse.ArgumentParser()
    add_pca_flags(p)
    return pca_config_from_args(p.parse_args(argv))


def test_defaults_match_reference():
    conf = _parse([])
    assert conf.bases_per_partition == 1_000_000  # GenomicsConf.scala:32
    assert conf.references == "17:41196311:41277499"  # BRCA1 default
    assert conf.variant_set_ids == [PLATINUM_GENOMES]
    assert conf.num_pc == 2  # GenomicsConf.scala:85
    assert conf.min_allele_frequency is None
    assert not conf.all_references


def test_repeated_variant_set_id():
    conf = _parse(["--variant-set-id", "a", "--variant-set-id", "b"])
    assert conf.variant_set_ids == ["a", "b"]


def test_pca_extras():
    conf = _parse(
        [
            "--all-references",
            "--min-allele-frequency",
            "0.05",
            "--num-pc",
            "4",
            "--precise",
            "--checkpoint-dir",
            "/tmp/x",
            "--trace-dir",
            "/tmp/t",
        ]
    )
    assert conf.all_references and conf.precise
    assert conf.min_allele_frequency == 0.05
    assert conf.num_pc == 4
    assert conf.checkpoint_dir == "/tmp/x" and conf.trace_dir == "/tmp/t"


def test_ingest_pipeline_flags():
    # Defaults: double-buffered feed, auto worker sizing.
    conf = _parse([])
    assert conf.prefetch_depth == 2
    assert conf.ingest_workers == 0  # 0 = auto
    conf = _parse(
        ["--prefetch-depth", "4", "--ingest-workers", "3"]
    )
    assert conf.prefetch_depth == 4
    assert conf.ingest_workers == 3


def test_shards_partitioner_selection():
    conf = PcaConfig(bases_per_partition=50_000_000)
    brca1 = conf.shards(all_references=False)
    assert len(brca1) == 1 and brca1[0].contig == "17"
    all_auto = conf.shards(all_references=True)
    assert {s.contig for s in all_auto} == {str(i) for i in range(1, 23)}


def test_stage_timer_report():
    import time

    from spark_examples_tpu.utils.tracing import StageTimer

    t = StageTimer()
    with t.stage("a"):
        time.sleep(0.01)
    with t.stage("b"):
        pass
    rep = t.report()
    assert "a:" in rep and "b:" in rep and "total:" in rep


class TestSampleShardedFlag:
    def test_tri_state(self):
        import argparse

        from spark_examples_tpu.utils.config import (
            add_pca_flags,
            pca_config_from_args,
        )

        p = argparse.ArgumentParser()
        add_pca_flags(p)
        assert pca_config_from_args(
            p.parse_args([])
        ).sample_sharded is None
        assert pca_config_from_args(
            p.parse_args(["--sample-sharded"])
        ).sample_sharded is True
        assert pca_config_from_args(
            p.parse_args(["--no-sample-sharded"])
        ).sample_sharded is False
