"""End-to-end PCoA pipeline tests over the hermetic fixture (SURVEY.md §7's
minimum end-to-end slice, run on the CPU mesh)."""

import numpy as np
import pytest

from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.models.pca import VariantsPcaDriver
from spark_examples_tpu.ops import mllib_principal_components_reference
from spark_examples_tpu.utils.config import PcaConfig


def make_driver(tmp_path=None, n=40, v=300, **conf_kw):
    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        output_path=str(tmp_path / "out") if tmp_path else None,
        block_variants=64,
        **conf_kw,
    )
    source = synthetic_cohort(n, v, references=conf.references)
    return VariantsPcaDriver(conf, source), source


def reference_pipeline_numpy(source, conf):
    """Straight-line numpy re-implementation of the whole reference pipeline
    (ingest → scalar-loop Gramian → MLlib PCs) as the e2e golden."""
    from spark_examples_tpu.genomics.callsets import CallsetIndex
    from spark_examples_tpu.genomics.datasets import af_filter, calls_stream

    index = CallsetIndex.from_source(source, conf.variant_set_ids)
    shards = conf.shards(all_references=conf.all_references)
    variants = [
        v
        for s in shards
        for v in source.stream_variants(conf.variant_set_ids[0], s)
    ]
    variants = list(af_filter(variants, conf.min_allele_frequency))
    n = index.size
    g = np.zeros((n, n), dtype=np.int64)
    for calls in calls_stream([variants], index.indexes):
        for c1 in calls:
            for c2 in calls:
                g[c1, c2] += 1
    coords, _ = mllib_principal_components_reference(g, 2)
    return index, coords


class TestEndToEnd:
    def test_pipeline_matches_reference_semantics(self, tmp_path):
        driver, source = make_driver(tmp_path)
        result = driver.run()

        golden_source = synthetic_cohort(40, 300)
        index, golden = reference_pipeline_numpy(golden_source, driver.conf)

        got = np.array([[pc1, pc2] for _, pc1, pc2 in result])
        np.testing.assert_allclose(got, golden, atol=1e-4)

        # Output file format parity: name\tpc1\tpc2\tdataset, sorted by name.
        lines = (tmp_path / "out-pca.tsv").read_text().strip().split("\n")
        assert len(lines) == 40
        names = [l.split("\t")[0] for l in lines]
        assert names == sorted(names)
        assert all(len(l.split("\t")) == 4 for l in lines)

    def test_population_structure_separates(self, tmp_path):
        """PC1 should separate the two synthetic populations — signal, not
        just numerics."""
        conf = PcaConfig(variant_set_ids=[DEFAULT_VARIANT_SET_ID])
        source = synthetic_cohort(30, 400, population_structure=2, seed=3)
        driver = VariantsPcaDriver(conf, source)
        result = driver.run()
        import numpy as np

        rng = np.random.default_rng(3)
        groups = rng.integers(0, 2, size=30)  # same draw as the fixture
        pc1 = np.array([r[1] for r in result])
        means = [pc1[groups == g].mean() for g in (0, 1)]
        spread = abs(means[0] - means[1])
        within = max(pc1[groups == g].std() for g in (0, 1))
        assert spread > within  # clear separation

    def test_af_filter_reduces_variants(self):
        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            min_allele_frequency=0.4,
        )
        source = synthetic_cohort(20, 200)
        driver = VariantsPcaDriver(conf, source)
        calls = list(driver.get_calls([driver.filter_dataset(d) for d in driver.get_data()]))
        source2 = synthetic_cohort(20, 200)
        conf2 = PcaConfig(variant_set_ids=[DEFAULT_VARIANT_SET_ID])
        driver2 = VariantsPcaDriver(conf2, source2)
        calls2 = list(driver2.get_calls([driver2.filter_dataset(d) for d in driver2.get_data()]))
        assert 0 < len(calls) < len(calls2)

    def test_dropped_contigs_excluded(self):
        conf = PcaConfig(variant_set_ids=[DEFAULT_VARIANT_SET_ID])
        source = synthetic_cohort(10, 100, dropped_contig_every=4)
        driver = VariantsPcaDriver(conf, source)
        calls = list(driver.get_calls(driver.get_data()))
        # 25 of 100 variants are on chrX_alt and must be dropped.
        assert len(calls) <= 75

    def test_multi_dataset_merge_pipeline(self):
        """Two variantsets: join semantics through the full driver."""
        from spark_examples_tpu.genomics.sources import FixtureSource

        a = synthetic_cohort(8, 60, variant_set_id="setA", seed=1)
        b = synthetic_cohort(8, 60, variant_set_id="setB", seed=1)
        # Same seed → same positions/alleles → full overlap; distinct callsets.
        merged = FixtureSource(
            variants=a._variants + b._variants,
            callsets=a._callsets + b._callsets,
        )
        conf = PcaConfig(variant_set_ids=["setA", "setB"])
        driver = VariantsPcaDriver(conf, merged)
        result = driver.run()
        assert len(result) == 16
        # Dataset label is the callsetId prefix before "-".
        assert {r[0].split("-")[0] for r in result} == {"setA", "setB"}

    def _degenerate_merge_driver(self, mode):
        """A same-seed two-dataset merge: duplicated sample rows make
        the centered Gramian exactly rank-deficient — the cohort shape
        that collapses the fused CholeskyQR panel to NaN."""
        from spark_examples_tpu.genomics.sources import FixtureSource

        a = synthetic_cohort(8, 60, variant_set_id="setA", seed=1)
        b = synthetic_cohort(8, 60, variant_set_id="setB", seed=1)
        merged = FixtureSource(
            variants=a._variants + b._variants,
            callsets=a._callsets + b._callsets,
        )
        conf = PcaConfig(
            variant_set_ids=["setA", "setB"], pca_mode=mode
        )
        return VariantsPcaDriver(conf, merged)

    def test_degenerate_cohort_auto_falls_back_to_dense_eigh(self):
        """AUTO selection must not die on a numerically degenerate
        centered Gramian: the fused finish's panel collapse warns and
        falls back to dense eigh (exact on rank-deficient spectra),
        finishing with finite coordinates — the fix for the historical
        multi-dataset/elastic tier-1 failure family."""
        driver = self._degenerate_merge_driver("auto")
        with pytest.warns(UserWarning, match="dense-eigh finish"):
            result = driver.run()
        coords = np.array([r[1:] for r in result])
        assert np.isfinite(coords).all()
        assert len(result) == 16

    def test_degenerate_cohort_forced_fused_still_raises(self):
        """--pca-mode fused asked for exactly that program: the
        degenerate-panel collapse stays a hard error there."""
        driver = self._degenerate_merge_driver("fused")
        with pytest.raises(FloatingPointError, match="non-finite"):
            driver.run()


class TestCli:
    def test_cli_pca_fixture(self, capsys, tmp_path):
        from spark_examples_tpu.cli.main import main

        rc = main(
            [
                "pca",
                "--fixture-samples",
                "12",
                "--fixture-variants",
                "80",
                "--output-path",
                str(tmp_path / "cli"),
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "Matrix size: 12" in out
        assert "Non zero rows in matrix:" in out
        assert (tmp_path / "cli-pca.tsv").exists()

    def test_cli_generate_then_ingest(self, capsys, tmp_path):
        from spark_examples_tpu.cli.main import main

        rc = main(
            [
                "generate-fixture",
                "--fixture-samples",
                "9",
                "--fixture-variants",
                "40",
                "--out",
                str(tmp_path / "cohort"),
            ]
        )
        assert rc == 0
        rc = main(["pca", "--input-path", str(tmp_path / "cohort")])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Matrix size: 9" in out


def test_stream_similarity_matches_dense():
    import numpy as np

    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    conf = PcaConfig(variant_set_ids=[DEFAULT_VARIANT_SET_ID], block_variants=32)
    driver = VariantsPcaDriver(conf, synthetic_cohort(12, 90))
    calls = list(driver.get_calls(driver.get_data()))
    dense = np.asarray(driver.get_similarity_matrix(iter(calls)))
    stream = np.asarray(driver.get_similarity_matrix_stream(iter(calls)))
    np.testing.assert_array_equal(dense, stream)


class TestFusedPcaMode:
    """--pca-mode routing and fused-vs-stream coordinate parity
    (round-5: the fused finish is the shipped default, VariantsPca.scala's
    main running its fast dense path, VariantsPca.scala:38-50)."""

    def _structured_driver(self, mode, tmp_path=None, **kw):
        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            block_variants=64,
            pca_mode=mode,
            **kw,
        )
        # population_structure=2 gives a clean top-2 eigenbasis so the
        # 1e-4 fused/stream parity bar is well-defined.
        source = synthetic_cohort(
            48, 400, population_structure=2, seed=3, references=conf.references
        )
        return VariantsPcaDriver(conf, source)

    def test_fused_matches_stream_coordinates(self):
        fused = self._structured_driver("fused").run()
        stream = self._structured_driver("stream").run()
        a = np.array([[p1, p2] for _, p1, p2 in fused])
        b = np.array([[p1, p2] for _, p1, p2 in stream])
        assert np.abs(a - b).max() <= 1e-4
        assert [r[0] for r in fused] == [r[0] for r in stream]

    def test_auto_routes_fused_at_small_n_and_stream_above_limit(self):
        d = self._structured_driver("auto")
        g = np.eye(4, dtype=np.float32)
        assert d._pca_fused_eligible(g)
        d_big = self._structured_driver("auto", dense_eigh_limit=8)
        assert not d_big._pca_fused_eligible(g)  # N=48 > 8
        d_stream = self._structured_driver("stream")
        assert not d_stream._pca_fused_eligible(g)
        d_precise = self._structured_driver("auto", precise=True)
        assert not d_precise._pca_fused_eligible(g)

    def test_forced_fused_rejects_incompatible_configs_before_ingest(self):
        with pytest.raises(ValueError, match="pca-mode fused"):
            self._structured_driver("fused", precise=True)

    def test_fused_nonzero_rows_print_matches_stream(self, capsys):
        self._structured_driver("fused").run()
        out_fused = capsys.readouterr().out
        self._structured_driver("stream").run()
        out_stream = capsys.readouterr().out
        line = [
            l for l in out_fused.splitlines() if "Non zero rows" in l
        ]
        assert line and line == [
            l for l in out_stream.splitlines() if "Non zero rows" in l
        ]


def test_stream_similarity_host_memory_fence():
    """The stream alternate now runs through the sparse device engine:
    the bound is the streaming-sparse per-host footprint (the f32 G
    tiles), NOT the historical 16·N² host peak (NOTES.md verdict #7) —
    past it the refusal is still loud, never a silent OOM. The full
    bound matrix lives in tests/test_sparse_gramian.py."""
    conf = PcaConfig(variant_set_ids=[DEFAULT_VARIANT_SET_ID], block_variants=32)
    driver = VariantsPcaDriver(conf, synthetic_cohort(12, 90))
    calls = list(driver.get_calls(driver.get_data()))
    with pytest.raises(ValueError, match="GiB"):
        driver.get_similarity_matrix_stream(
            iter(calls), max_host_bytes=4 * 12 * 12 - 1
        )
    # At exactly the f32-G per-host footprint it runs — a budget 4x
    # under the old int64-G + f32-copy + jax-buffer peak.
    out = driver.get_similarity_matrix_stream(
        iter(calls), max_host_bytes=4 * 12 * 12
    )
    assert out.shape == (12, 12)
