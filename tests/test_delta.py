"""Incremental cohort deltas + gang-batched Gramians (ops + driver).

The serving tier's marginal-job machinery (docs/OPERATIONS.md §4c):
cohort sample restriction at the window boundary, exact rank-k sample
corrections against cached Gramians (`ops/delta.py`), and the vmapped
gang accumulator (`ops/gramian.gang_gramian_blockwise`). The contract
under test everywhere is BIT-IDENTITY: a restricted run equals the full
run's submatrix, a delta equals from-scratch, a gang member equals its
serial run — exact integer counts in f32, so equality is `==`, never
allclose.
"""

import random

import numpy as np
import pytest

from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.models.pca import VariantsPcaDriver
from spark_examples_tpu.ops.delta import (
    delta_gramian,
    sample_correction,
    signed_scatter_pairs,
)
from spark_examples_tpu.ops.gramian import gang_gramian_blockwise
from spark_examples_tpu.ops.sparse import padded_carrier_matrix
from spark_examples_tpu.serving import (
    AnalysisEngine,
    DeltaIndex,
    JobSpec,
    cohort_key,
    gramian_base_key,
    job_config,
)
from spark_examples_tpu.utils.config import PcaConfig

REFS = "17:41196311:41277499"
N, V = 12, 120


def _conf(**kw):
    kw.setdefault("variant_set_ids", [DEFAULT_VARIANT_SET_ID])
    kw.setdefault("references", REFS)
    kw.setdefault("bases_per_partition", 20_000)
    kw.setdefault("block_variants", 16)
    kw.setdefault("ingest_workers", 2)
    return PcaConfig(**kw)


@pytest.fixture(scope="module")
def cohort():
    src = synthetic_cohort(N, V, seed=11)
    ids = [f"{DEFAULT_VARIANT_SET_ID}-{i}" for i in range(N)]
    g_full = np.asarray(VariantsPcaDriver(_conf(), src).ingest_gramian())
    return src, ids, g_full


def _g(src, **kw):
    return np.asarray(VariantsPcaDriver(_conf(**kw), src).ingest_gramian())


class TestSampleRestriction:
    def test_restricted_gramian_is_the_full_submatrix(self, cohort):
        src, ids, g_full = cohort
        keep = [0, 1, 3, 4, 5, 6, 8, 9, 10, 11]
        g_sub = _g(src, exclude_samples=[ids[2], ids[7]])
        assert np.array_equal(g_sub, g_full[np.ix_(keep, keep)])

    def test_samples_include_list_and_order_independence(self, cohort):
        src, ids, g_full = cohort
        picked = [ids[5], ids[1], ids[9]]  # scrambled on purpose
        g_sub = _g(src, samples=picked)
        # The frame orders by FULL-index position, not by the user's
        # list order — permuted lists are one cohort.
        assert np.array_equal(
            g_sub, g_full[np.ix_([1, 5, 9], [1, 5, 9])]
        )
        assert np.array_equal(g_sub, _g(src, samples=sorted(picked)))

    def test_window_route_matches_block_route(self, cohort):
        src, ids, _ = cohort
        conf = _conf(exclude_samples=[ids[0], ids[4]])
        driver = VariantsPcaDriver(conf, src)
        g_blocks = np.asarray(driver.ingest_gramian())
        g_windows = np.asarray(
            VariantsPcaDriver(conf, src).ingest_gramian_windows()
        )
        assert np.array_equal(g_blocks, g_windows)

    def test_unknown_and_empty_restrictions_are_loud(self, cohort):
        src, ids, _ = cohort
        with pytest.raises(ValueError, match="unknown sample"):
            VariantsPcaDriver(
                _conf(samples=["nope"]), src
            ).ingest_gramian()
        with pytest.raises(ValueError, match="no samples"):
            VariantsPcaDriver(
                _conf(samples=[ids[0]], exclude_samples=[ids[0]]), src
            )
        # An EXPLICITLY empty include list is a contradictory cohort —
        # it must hit the same loud error, never silently run the full
        # cohort (an empty exclude list IS the unrestricted cohort).
        with pytest.raises(ValueError, match="no samples"):
            VariantsPcaDriver(_conf(samples=[]), src)
        full = VariantsPcaDriver(_conf(exclude_samples=[]), src)
        assert full.cohort.size == len(ids)

    def test_empty_samples_spec_fails_the_job_loudly(self, cohort):
        src, _, _ = cohort
        eng = AnalysisEngine(src)
        with pytest.raises(ValueError, match="no samples"):
            eng.run(job_config(JobSpec(samples=()), _conf()))
        # The gang-size probe rejects the same restrictions the driver
        # would (so doomed jobs never poison a gang).
        with pytest.raises(ValueError, match="no samples"):
            eng.cohort_size(job_config(JobSpec(samples=()), _conf()))
        with pytest.raises(ValueError, match="unknown sample"):
            eng.cohort_size(
                job_config(JobSpec(samples=("ghost",)), _conf())
            )

    def test_restriction_rejects_checkpoint_and_mesh(self, cohort):
        src, ids, _ = cohort
        with pytest.raises(ValueError, match="checkpointed"):
            VariantsPcaDriver(
                _conf(samples=[ids[0]], checkpoint_dir="/tmp/x"), src
            )
        from spark_examples_tpu.parallel.mesh import make_mesh

        with pytest.raises(ValueError, match="meshless"):
            VariantsPcaDriver(
                _conf(samples=[ids[0]]), src, mesh=make_mesh("data:2")
            )


class TestDeltaGramian:
    def test_pure_removal_delta_is_bit_identical(self, cohort):
        src, ids, g_full = cohort
        target = _conf(exclude_samples=[ids[3], ids[8]])
        driver = VariantsPcaDriver(target, src)
        got = driver.ingest_gramian_delta(g_full, tuple(ids))
        assert np.array_equal(got, np.asarray(driver.ingest_gramian()))

    def test_add_and_remove_delta_is_bit_identical(self, cohort):
        src, ids, _ = cohort
        g_anc = _g(src, samples=ids[:8])
        driver = VariantsPcaDriver(_conf(samples=ids[4:]), src)
        got = driver.ingest_gramian_delta(g_anc, tuple(ids[:8]))
        assert np.array_equal(got, np.asarray(driver.ingest_gramian()))

    def test_delta_from_cached_windows_and_shuffled_order(self, cohort):
        src, ids, _ = cohort
        anc_driver = VariantsPcaDriver(_conf(samples=ids[:9]), src)
        windows = []
        g_anc = np.asarray(
            anc_driver.ingest_gramian_windows(window_sink=windows)
        )
        assert windows, "cold window route must capture windows"
        driver = VariantsPcaDriver(_conf(samples=ids[1:10]), src)
        want = np.asarray(driver.ingest_gramian())
        got = driver.ingest_gramian_delta(
            g_anc, tuple(ids[:9]), windows=windows
        )
        assert np.array_equal(got, want)
        # Window ARRIVAL order is irrelevant — exact integer counts.
        shuffled = list(windows)
        random.Random(5).shuffle(shuffled)
        got2 = driver.ingest_gramian_delta(
            g_anc, tuple(ids[:9]), windows=shuffled
        )
        assert np.array_equal(got2, want)

    def test_sample_correction_columns_are_gramian_columns(self, cohort):
        """C[:, t] must equal G's column for touched sample t — the
        algebraic identity the delta path rests on."""
        src, ids, g_full = cohort
        driver = VariantsPcaDriver(_conf(), src)
        windows = list(driver._cohort_windows(restrict=False))
        touched = [2, 7, 11]
        row_of_full = np.arange(N, dtype=np.int64)
        col_of_full = np.full(N, len(touched), dtype=np.int64)
        col_of_full[touched] = np.arange(len(touched))
        corr = sample_correction(
            windows, row_of_full, col_of_full, N, len(touched)
        )
        assert np.array_equal(corr, g_full[:, touched])

    def test_signed_scatter_minus_cancels_plus(self):
        lens = np.asarray([2, 3, 1, 0], dtype=np.int64)
        idx = np.asarray([0, 2, 1, 3, 4, 2], dtype=np.int64)
        mat = padded_carrier_matrix(idx, lens, sentinel=5, n_rows=256)
        import jax.numpy as jnp

        acc = signed_scatter_pairs(
            jnp.zeros((5, 5), jnp.float32), mat, mat, sign=1
        )
        assert float(np.asarray(acc).sum()) > 0
        acc = signed_scatter_pairs(acc, mat, mat, sign=-1)
        assert np.array_equal(np.asarray(acc), np.zeros((5, 5)))
        with pytest.raises(ValueError, match="sign"):
            signed_scatter_pairs(acc, mat, mat, sign=2)

    def test_frame_mismatch_is_loud(self, cohort):
        src, ids, g_full = cohort
        driver = VariantsPcaDriver(_conf(samples=ids[:4]), src)
        with pytest.raises(ValueError, match="ancestor"):
            driver.ingest_gramian_delta(
                g_full[:3, :3], tuple(ids)
            )

    def test_delta_gramian_direct_api(self, cohort):
        """delta_gramian against numpy-built ground truth, shuffled
        ancestor frame order included."""
        rng = np.random.default_rng(3)
        n_full, n_var = 9, 40
        x = (rng.random((n_full, n_var)) < 0.3).astype(np.int64)
        windows = []
        for lo in range(0, n_var, 16):
            cols = x[:, lo : lo + 16]
            lens = cols.sum(axis=0).astype(np.int64)
            idx = np.concatenate(
                [np.nonzero(cols[:, j])[0] for j in range(cols.shape[1])]
            ) if lens.sum() else np.zeros(0, dtype=np.int64)
            windows.append((idx, lens))
        anc = np.asarray([7, 0, 3, 5, 1], dtype=np.int64)  # scrambled
        tgt = np.asarray([0, 2, 3, 6, 7], dtype=np.int64)
        g_anc = (x[anc] @ x[anc].T).astype(np.float32)
        want = (x[tgt] @ x[tgt].T).astype(np.float32)
        got = delta_gramian(g_anc, anc, tgt, n_full, windows)
        assert np.array_equal(got, want)


class TestGangGramian:
    def test_gang_matches_serial_per_cohort(self, cohort):
        src, ids, _ = cohort
        cohorts = [ids[:5], ids[3:9], ids[1:]]
        driver = VariantsPcaDriver(_conf(), src)
        windows = list(driver._cohort_windows(restrict=False))
        remaps, sizes = [], []
        for members in cohorts:
            sub, remap = driver.index.restricted(members)
            remaps.append(remap)
            sizes.append(sub.size)
        g = gang_gramian_blockwise(
            windows, remaps, max(sizes), block_variants=16
        )
        for b, members in enumerate(cohorts):
            want = _g(src, samples=list(members))
            assert np.array_equal(g[b, : sizes[b], : sizes[b]], want)
            # Padding rows/cols beyond the cohort stay zero (inert).
            assert not g[b, sizes[b] :, :].any()
            assert not g[b, :, sizes[b] :].any()

    def test_gang_is_order_independent(self, cohort):
        src, ids, _ = cohort
        driver = VariantsPcaDriver(_conf(), src)
        windows = list(driver._cohort_windows(restrict=False))
        _, remap = driver.index.restricted(ids[:6])
        a = gang_gramian_blockwise(windows, [remap], 6, block_variants=16)
        shuffled = list(windows)
        random.Random(9).shuffle(shuffled)
        b = gang_gramian_blockwise(
            shuffled, [remap], 6, block_variants=16
        )
        assert np.array_equal(a, b)

    def test_empty_gang_is_loud(self):
        with pytest.raises(ValueError, match=">= 1 cohort"):
            gang_gramian_blockwise(iter(()), [], 4)


class TestSpecSurface:
    def test_spec_sample_fields_validate_and_canonicalize(self):
        spec = JobSpec.from_record(
            {"samples": ["b", "a", "b"], "exclude_samples": ["z"]}
        )
        assert spec.samples == ("a", "b")
        assert spec.exclude_samples == ("z",)
        with pytest.raises(ValueError, match="samples"):
            JobSpec.from_record({"samples": [1]})
        with pytest.raises(ValueError, match="exclude_samples"):
            JobSpec.from_record({"exclude_samples": "notalist"})
        rt = JobSpec.from_record(spec.to_record())
        assert rt == spec

    def test_cohort_key_covers_sample_restriction(self):
        base = _conf()
        assert cohort_key(JobSpec(), base) != cohort_key(
            JobSpec(samples=("a",)), base
        )
        # Permutations canonicalize to one key via from_record.
        a = JobSpec.from_record({"samples": ["a", "b"]})
        b = JobSpec.from_record({"samples": ["b", "a"]})
        assert cohort_key(a, base) == cohort_key(b, base)

    def test_gramian_base_key_excludes_samples_and_num_pc(self):
        base = _conf()
        k0 = gramian_base_key(job_config(JobSpec(), base))
        assert k0 == gramian_base_key(
            job_config(JobSpec(samples=("a",), num_pc=5), base)
        )
        assert k0 != gramian_base_key(
            job_config(JobSpec(min_allele_frequency=0.25), base)
        )


class TestServingAcceptance:
    """The ISSUE's measured bars, pinned where CI can hold them: a
    ±16-sample delta ≥10× faster than the cold run of the same cohort
    (bit-identical), and gang-batched drain strictly faster than
    serial (jobs/s strictly above) with bit-identical per-job rows.
    Every executable is warmed on its exact shape before any timed
    window — these compare serving work, not XLA compiles.
    BENCH_SERVE_r01.json records the bench-scale capture."""

    def test_delta_10x_faster_than_cold_bit_identical(self):
        import time

        # v sized so the cold run's O(N·V) ingest dominates its ~70 ms
        # fixed costs several times over: the ≥10× bar then reflects
        # the structural O(k·N)-vs-O(N·V) gap, not scheduler luck.
        n, v, cohort_n = 96, 16000, 48
        src = synthetic_cohort(
            n, v, seed=6, sparse_calls=True, rare_variant_af=0.02
        )
        ids = [f"{DEFAULT_VARIANT_SET_ID}-{i}" for i in range(n)]
        base = dict(block_variants=512, ingest_workers=2)
        anc_conf = _conf(samples=ids[:cohort_n], **base)
        target = sorted(ids[8 : cohort_n + 8])
        target_conf = _conf(samples=target, **base)
        cold_engine = AnalysisEngine(src)
        # Warm the TARGET cohort end to end (not just the ancestor): a
        # near-degenerate spectrum makes the fused finish retry with a
        # NEW executable whose compile would otherwise land in the
        # timed cold leg and fake the speedup.
        AnalysisEngine(src).run(target_conf)
        warm = sorted(ids[: cohort_n - 8] + ids[cohort_n : cohort_n + 8])
        warm_conf = _conf(samples=warm, **base)
        # Best-of-N on BOTH legs (the bench discipline): a single
        # measurement under full-suite load turns scheduler noise into
        # flaky acceptance verdicts.
        t_cold = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            rows_cold = cold_engine.run(target_conf)
            t_cold = min(t_cold, time.perf_counter() - t0)

        def delta_once():
            # A fresh engine per repeat: re-running the tweak on one
            # engine would resolve its own cached result as an
            # exact-frame hit and time the zero-delta return, not the
            # rank-k correction.
            eng = AnalysisEngine(src, delta_max_samples=16)
            eng.run(anc_conf)  # cache the ancestor (cold)
            assert eng.delta_resolvable(warm_conf)
            eng.run(warm_conf)  # warm the correction executable
            assert eng.delta_resolvable(target_conf)
            t0 = time.perf_counter()
            rows = eng.run(target_conf)
            return time.perf_counter() - t0, rows

        (t_delta, rows_delta) = min(
            (delta_once() for _ in range(3)), key=lambda r: r[0]
        )
        assert rows_delta == rows_cold
        assert t_delta * 10 < t_cold, (
            f"±16-sample delta must be >=10x faster than cold: "
            f"delta {t_delta:.4f}s vs cold {t_cold:.4f}s "
            f"({t_cold / max(t_delta, 1e-9):.1f}x)"
        )

    def test_gang_drain_strictly_faster_than_serial(self):
        import time

        from spark_examples_tpu.serving import AnalysisJobTier

        n, v, cohort_n, n_jobs = 64, 1200, 32, 6
        src = synthetic_cohort(n, v, seed=8, sparse_calls=True)
        ids = [f"{DEFAULT_VARIANT_SET_ID}-{i}" for i in range(n)]
        base = _conf(block_variants=512, ingest_workers=2)
        specs = [
            JobSpec(
                samples=tuple(
                    sorted(ids[(i * 5 + j) % n] for j in range(cohort_n))
                )
            )
            for i in range(n_jobs)
        ]

        def drain(gang_max):
            tier = AnalysisJobTier(
                AnalysisEngine(src),
                base,
                workers=0,
                queue_depth=64,
                tenant_quota=64,
                gang_max_samples=gang_max,
            )
            jobs = [tier.submit(s)[0] for s in specs]
            t0 = time.perf_counter()
            # timeout=0: queue pre-filled, workers=0 — a blocking final
            # pop would count its whole wait against the timed leg.
            while tier.step(timeout=0.0):
                pass
            dt = time.perf_counter() - t0
            assert all(j.state == "done" for j in jobs)
            rows = [j.result for j in jobs]
            tier.close()
            return dt, rows

        drain(0)  # warm serial-shape executables
        drain(cohort_n)  # warm the batched accumulator
        t_serial, rows_serial = drain(0)
        t_gang, rows_gang = drain(cohort_n)
        assert rows_gang == rows_serial
        assert t_gang < t_serial, (
            f"gang-batched jobs/s must be strictly above serial: "
            f"gang {n_jobs / t_gang:.2f}/s vs serial "
            f"{n_jobs / t_serial:.2f}/s"
        )


class TestDeltaIndex:
    def test_nearest_ancestor_resolution_and_bounds(self):
        idx = DeltaIndex(max_delta_samples=2)
        g = np.eye(3, dtype=np.float32)
        idx.put("k", ("a", "b", "c"), g)
        idx.put("k", ("a", "b", "x"), g)
        # Exact frame wins at distance 0.
        assert idx.resolve("k", ("a", "b", "c")).samples == (
            "a", "b", "c",
        )
        # Distance 1 within bound; distance 3 out of bound; other base
        # keys never match.
        assert idx.resolve("k", ("a", "b")) is not None
        assert idx.resolve("k", ("q", "r", "s", "t", "u")) is None
        assert idx.resolve("other", ("a", "b", "c")) is None

    def test_checksum_guard_detects_corruption(self):
        idx = DeltaIndex(max_delta_samples=4)
        idx.put("k", ("a",), np.ones((2, 2), dtype=np.float32))
        entry = idx.resolve("k", ("a",))
        assert entry.verify()
        entry.g[0, 0] = 41.0  # bit rot / accidental mutation
        assert not entry.verify()
        idx.drop(entry)
        assert idx.resolve("k", ("a",)) is None

    def test_engine_fallback_on_corrupt_cache_is_still_exact(self):
        src = synthetic_cohort(8, 60, seed=4)
        ids = [f"{DEFAULT_VARIANT_SET_ID}-{i}" for i in range(8)]
        eng = AnalysisEngine(src, delta_max_samples=16)
        base = _conf()
        eng.run(base)
        # Corrupt the cached ancestor in place: the checksum guard must
        # fall back to cold and the answer must not change.
        entry = eng._deltas.resolve(gramian_base_key(base), tuple(ids))
        entry.g[0, 0] += 1.0
        tweaked = _conf(exclude_samples=[ids[2]])
        got = eng.run(tweaked)
        want = AnalysisEngine(src).run(tweaked)
        assert got == want


class TestDeltaPersistence:
    """ROADMAP item 1 remainder: DeltaIndex entries write through to
    the journal directory and survive a kill -9 — with checksummed
    re-load and a LOUD cold fallback for torn/stale files."""

    def test_entries_survive_restart_and_serve_warm_deltas(self, tmp_path):
        src = synthetic_cohort(8, 60, seed=4)
        ids = [f"{DEFAULT_VARIANT_SET_ID}-{i}" for i in range(8)]
        persist = str(tmp_path / "deltas")
        eng = AnalysisEngine(
            src, delta_max_samples=16, delta_persist_dir=persist
        )
        base = _conf()
        eng.run(base)  # caches + persists the full-frame ancestor
        import os

        files = [f for f in os.listdir(persist) if f.endswith(".npz")]
        assert files, "persisted entry expected beside the journal"
        # "kill -9": a brand-new engine on the same directory must
        # resolve the ancestor warm and serve the ±1 delta job
        # bit-identically to a cold engine.
        eng2 = AnalysisEngine(
            src, delta_max_samples=16, delta_persist_dir=persist
        )
        tweaked = _conf(exclude_samples=[ids[3]])
        assert eng2.delta_resolvable(tweaked)
        got = eng2.run(tweaked)
        want = AnalysisEngine(src).run(tweaked)
        assert got == want  # exact float equality

    def test_torn_and_stale_entries_fall_back_cold_loudly(
        self, tmp_path, capsys
    ):
        import os

        persist = str(tmp_path / "deltas")
        idx = DeltaIndex(max_delta_samples=4, persist_dir=persist)
        g = np.arange(9, dtype=np.float32).reshape(3, 3)
        idx.put("k1", ("a", "b", "c"), g)
        idx.put("k2", ("a", "b"), g[:2, :2].copy())
        names = sorted(
            f for f in os.listdir(persist) if f.endswith(".npz")
        )
        assert len(names) == 2
        # Torn file (a kill mid-write after the atomic-rename window
        # would leave a valid file; this models external truncation /
        # partial disk): half the bytes.
        torn = os.path.join(persist, names[0])
        with open(torn, "r+b") as f:
            f.truncate(os.path.getsize(torn) // 2)
        # Stale file: valid npz whose G no longer matches its
        # insert-time checksum.
        stale = os.path.join(persist, names[1])
        doc = dict(np.load(stale, allow_pickle=False))
        doc["g"] = doc["g"] + 1.0
        with open(stale, "wb") as f:
            np.savez(f, **doc)
        idx2 = DeltaIndex(max_delta_samples=4, persist_dir=persist)
        err = capsys.readouterr().err
        assert err.count("torn/stale delta-cache entry") == 2
        assert len(idx2) == 0  # both dropped -> those cohorts run cold
        assert not os.path.exists(torn) and not os.path.exists(stale)

    def test_mid_write_partial_is_swept_never_parsed(self, tmp_path):
        import os

        persist = str(tmp_path / "deltas")
        os.makedirs(persist)
        # A kill mid-persist leaves only the .tmp- partial (the rename
        # is atomic); a restart must sweep it silently.
        with open(
            os.path.join(persist, "delta-abc-def.npz.tmp-123"), "wb"
        ) as f:
            f.write(b"half a zip")
        idx = DeltaIndex(max_delta_samples=4, persist_dir=persist)
        assert len(idx) == 0
        assert os.listdir(persist) == []

    def test_drop_and_eviction_unlink_files(self, tmp_path):
        import os

        persist = str(tmp_path / "deltas")
        g = np.ones((64, 64), dtype=np.float32)  # 16 KiB per entry
        idx = DeltaIndex(
            max_delta_samples=4,
            max_bytes=64 * 1024,
            persist_dir=persist,
        )
        for i in range(6):  # 6 x 16 KiB > 64 KiB budget -> evictions
            idx.put(f"k{i}", (f"s{i}",), g)
        on_disk = [f for f in os.listdir(persist) if f.endswith(".npz")]
        assert len(on_disk) == len(idx) < 6
        entry = idx.resolve("k5", ("s5",))
        idx.drop(entry)
        assert not os.path.exists(
            os.path.join(
                persist, DeltaIndex._entry_filename("k5", ("s5",))
            )
        )
