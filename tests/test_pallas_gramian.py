"""Pallas Gramian kernel vs the einsum path (interpret mode on CPU)."""

import jax.numpy as jnp
import numpy as np

from spark_examples_tpu.ops import gramian
from spark_examples_tpu.ops.pallas_gramian import gramian_accumulate_pallas


def test_pallas_accumulate_matches_einsum():
    rng = np.random.default_rng(0)
    n, v = 512, 1024
    x = (rng.random((n, v)) < 0.3).astype(np.int8)
    g0 = rng.random((n, n)).astype(np.float32)

    got = gramian_accumulate_pallas(
        jnp.asarray(g0), jnp.asarray(x), interpret=True
    )
    want = g0 + np.asarray(gramian(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_pallas_multi_block_accumulation():
    rng = np.random.default_rng(1)
    n = 256
    g = jnp.zeros((n, n), jnp.float32)
    full = []
    for i in range(3):
        x = (rng.random((n, 512)) < 0.2).astype(np.int8)
        full.append(x)
        g = gramian_accumulate_pallas(g, jnp.asarray(x), interpret=True)
    want = np.concatenate(full, axis=1)
    want = want.astype(np.float32) @ want.T.astype(np.float32)
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-6)


def test_blockwise_pallas_path_matches(monkeypatch):
    """Exercise the gramian_blockwise pallas dispatch (interpret via CPU:
    force use_pallas=True with interpret-mode kernel)."""
    import spark_examples_tpu.ops.pallas_gramian as pg
    from spark_examples_tpu.ops import gramian_blockwise

    orig = pg.gramian_accumulate_pallas
    monkeypatch.setattr(
        pg,
        "gramian_accumulate_pallas",
        lambda g, x: orig(g, x, interpret=True),
    )
    rng = np.random.default_rng(2)
    x = (rng.random((100, 700)) < 0.3).astype(np.int8)  # both axes ragged
    blocks = [x[:, :300], x[:, 300:]]
    g = gramian_blockwise(iter(blocks), 100, use_pallas=True)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(gramian(x)), rtol=1e-6
    )


def test_pallas_sym_matches_einsum():
    from spark_examples_tpu.ops.pallas_gramian import (
        gramian_accumulate_pallas_sym,
    )

    rng = np.random.default_rng(3)
    n, v = 768, 1024  # 3x2 tile grid — even and odd tile rows
    x = (rng.random((n, v)) < 0.3).astype(np.int8)
    g0 = rng.random((n, n)).astype(np.float32)
    g0 = g0 + g0.T  # accumulator must be symmetric (G always is)

    got = gramian_accumulate_pallas_sym(
        jnp.asarray(g0), jnp.asarray(x), interpret=True
    )
    want = g0 + np.asarray(gramian(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_blockwise_sym_dispatch(monkeypatch):
    import spark_examples_tpu.ops.pallas_gramian as pg
    from spark_examples_tpu.ops import gramian_blockwise

    monkeypatch.setenv("SPARK_EXAMPLES_TPU_PALLAS", "sym")
    orig = pg._sym_accumulate_lower
    monkeypatch.setattr(
        pg,
        "_sym_accumulate_lower",
        lambda g, x: orig(g, x, interpret=True),
    )
    rng = np.random.default_rng(4)
    x = (rng.random((100, 700)) < 0.3).astype(np.int8)
    blocks = [x[:, :300], x[:, 300:]]
    g = gramian_blockwise(iter(blocks), 100, use_pallas=True)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(gramian(x)), rtol=1e-6
    )
