"""Differential fuzz: native JSONL parser vs the Python reference parse.

Contract under test (sources._CsrCohort): for ANY input file, the native
parser either produces arrays identical to the Python parser or refuses
(returns None / error) so the Python parser decides — including inputs
where Python itself raises. It must never silently diverge.
"""

import json
import os
import random

import numpy as np
import pytest

from spark_examples_tpu.genomics.sources import JsonlSource, _CsrCohort
from spark_examples_tpu.native import load

CALLSET_IDS = [f"cs-{i}" for i in range(6)]

pytestmark = pytest.mark.skipif(
    load() is None or not hasattr(load(), "parse_cohort_jsonl"),
    reason="native core unavailable",
)

CONTIG_POOL = [
    "17",
    "chr17",
    "chrX",
    "chrX_alt",
    "",
    "chr",
    "x17",
    "17x",
    "HLA-A",
    "chr0017",
    "ünicode",
    'quote"inside',
    "back\\slash",
]
AF_POOL = [
    ["0.25"],
    ["0.000001"],
    ["."],
    [""],
    [0.5],
    [1],
    ["1e-3"],
    ["nan"],
    [None],
    [],
    ["0.1", "0.9"],
]
VSID_POOL = ["vs1", "vs2", "", None, "ünicode-vs", 'v"s']
GT_POOL = [[0, 0], [0, 1], [1, 1], [-1, -1], [2, 0], [], [0], [1, -1, 0]]


def _random_record(rng):
    rec = {}
    if rng.random() < 0.95:
        rec["reference_name"] = rng.choice(CONTIG_POOL)
    if rng.random() < 0.95:
        rec["start"] = rng.randrange(0, 10_000_000)
    if rng.random() < 0.95:
        rec["end"] = rng.randrange(0, 10_000_000)
    if rng.random() < 0.6:
        rec["variant_set_id"] = rng.choice(VSID_POOL)
    if rng.random() < 0.5:
        rec["reference_bases"] = rng.choice(["A", "N", "ACGT", ""])
    if rng.random() < 0.4:
        rec["alternate_bases"] = rng.choice(
            [["G"], ["G", "T"], [], None, "AC", [None], 5]
        )
    if rng.random() < 0.1:
        rec["reference_bases"] = rng.choice([None, True, 7])
    if rng.random() < 0.6:
        info = {}
        if rng.random() < 0.8:
            info["AF"] = rng.choice(AF_POOL)
        if rng.random() < 0.3:
            info["OTHER"] = ["x", 1, None]
        rec["info"] = info
    if rng.random() < 0.85:
        calls = []
        for _ in range(rng.randrange(0, 5)):
            call = {}
            if rng.random() < 0.95:
                call["callset_id"] = rng.choice(
                    CALLSET_IDS + ["ghost", "üid"]
                )
            if rng.random() < 0.95:
                call["genotype"] = rng.choice(GT_POOL)
            if rng.random() < 0.2:
                call["phaseset"] = rng.choice(["ps1", ""])
            if rng.random() < 0.1:
                call["info"] = {"DP": [rng.randrange(0, 99)]}
            calls.append(call)
        rec["calls"] = calls
    return rec


def _adversarial_lines(rng):
    """Raw lines json.dumps cannot produce: duplicate keys, weird tokens,
    broken JSON. The native parser must refuse or match Python."""
    return [
        # duplicate extracted keys (json.loads: last-wins)
        '{"reference_name": "17", "start": 1, "calls": '
        '[{"callset_id": "cs-0", "genotype": [1]}], "calls": '
        '[{"callset_id": "cs-1", "genotype": [1]}]}',
        '{"reference_name": "17", "reference_name": "18", "start": 2, '
        '"calls": []}',
        '{"reference_name": "17", "start": 3, "start": 4, "calls": []}',
        '{"reference_name": "17", "start": 5, "info": {"AF": ["0.1"]}, '
        '"info": {}}',
        # invalid bare tokens / broken JSON (json.loads raises)
        '{"reference_name": "17", "start": 6, "junk": blah}',
        '{"reference_name": "17", "start": 7',
        '{"reference_name": "17", "start": 8, "info": {"AF": [0x10]}}',
        "not json at all",
        # non-JSON integers and float-grammar mismatches
        '{"reference_name": "17", "start": 012, "calls": []}',
        '{"reference_name": "17", "start": 1, "calls": '
        '[{"callset_id": "cs-0", "genotype": [01]}]}',
        '{"reference_name": "17", "start": 2, "info": {"AF": ["0x10"]}, '
        '"calls": []}',
        '{"reference_name": "17", "start": 3, "info": {"AF": ["1_5"]}, '
        '"calls": []}',
        '{"reference_name": "17", "start": 4, "info": {"AF": ["."]}, '
        '"calls": []}',
        # escapes in extracted strings (valid JSON; native must refuse)
        '{"reference_name": "chr\\u005f17", "start": 9, "calls": []}',
        '{"reference_name": "17", "start": 10, "variant_set_id": '
        '"a\\"b", "calls": []}',
        # whitespace/format variants (valid)
        '  {  "reference_name" : "17" , "start" : 11 , "calls" : [ ] }  ',
        '{"reference_name": "17", "start": 12, "extra": {"deep": '
        '[{"n": [1, 2, {"x": null}]}, true, false]}, "calls": []}',
    ]


def _compare(tmp_path, lines, tag):
    root = tmp_path / tag
    os.makedirs(root)
    (root / "callsets.json").write_text(
        json.dumps(
            [
                {"id": cid, "name": cid, "variant_set_id": "vs1"}
                for cid in CALLSET_IDS
            ]
        )
    )
    (root / "variants.jsonl").write_text(
        "\n".join(lines) + ("\n" if lines else "")
    )
    js = JsonlSource(str(root))
    native = _CsrCohort._parse_native(str(root), CALLSET_IDS)
    try:
        python = _CsrCohort._parse_python(js._open, CALLSET_IDS)
        python_raised = None
    except Exception as e:  # noqa: BLE001 — part of the contract
        python_raised = e
        python = None
    if python_raised is not None:
        # Python refuses the file: native must have refused too.
        assert native is None, (
            f"native accepted a file Python rejects ({python_raised!r})"
        )
        return
    if native is None:
        return  # conservative refusal is always allowed
    for name, a, b in zip(
        (
            "contig_table",
            "rec_contig",
            "starts",
            "vsid_table",
            "rec_vsid",
            "afs",
            "offsets",
            "ords",
            "extra_ids",
            "ends",
            "refs",
            "alts",
        ),
        native,
        python,
    ):
        if isinstance(a, list):
            assert a == b, (tag, name, a, b)
        else:
            np.testing.assert_array_equal(a, b, err_msg=f"{tag}:{name}")


@pytest.mark.parametrize("seed", range(12))
def test_random_cohorts_native_matches_or_refuses(tmp_path, seed):
    rng = random.Random(seed)
    n = rng.randrange(1, 60)
    ensure_ascii = rng.random() < 0.5
    lines = [
        json.dumps(_random_record(rng), ensure_ascii=ensure_ascii)
        for _ in range(n)
    ]
    _compare(tmp_path, lines, f"seed{seed}")


def test_adversarial_lines_one_per_file(tmp_path):
    rng = random.Random(99)
    for i, line in enumerate(_adversarial_lines(rng)):
        _compare(tmp_path, [line], f"adv{i}")


def test_adversarial_lines_mixed_with_valid(tmp_path):
    rng = random.Random(7)
    valid = [json.dumps(_random_record(rng)) for _ in range(5)]
    for i, line in enumerate(_adversarial_lines(rng)):
        _compare(tmp_path, valid + [line], f"mix{i}")
