"""Differential fuzz: native JSONL parser vs the Python reference parse.

Contract under test (sources._CsrCohort): for ANY input file, the native
parser either produces arrays identical to the Python parser or refuses
(returns None / error) so the Python parser decides — including inputs
where Python itself raises. It must never silently diverge.
"""

import json
import os
import random

import numpy as np
import pytest

from spark_examples_tpu.genomics.sources import JsonlSource, _CsrCohort
from spark_examples_tpu.native import force_fallback as _force_python_fallback
from spark_examples_tpu.native import load

CALLSET_IDS = [f"cs-{i}" for i in range(6)]

pytestmark = pytest.mark.skipif(
    load() is None or not hasattr(load(), "parse_cohort_jsonl"),
    reason="native core unavailable",
)

CONTIG_POOL = [
    "17",
    "chr17",
    "chrX",
    "chrX_alt",
    "",
    "chr",
    "x17",
    "17x",
    "HLA-A",
    "chr0017",
    "ünicode",
    'quote"inside',
    "back\\slash",
]
AF_POOL = [
    ["0.25"],
    ["0.000001"],
    ["."],
    [""],
    [0.5],
    [1],
    ["1e-3"],
    ["nan"],
    [None],
    [],
    ["0.1", "0.9"],
]
VSID_POOL = ["vs1", "vs2", "", None, "ünicode-vs", 'v"s']
GT_POOL = [[0, 0], [0, 1], [1, 1], [-1, -1], [2, 0], [], [0], [1, -1, 0]]


def _random_record(rng):
    rec = {}
    if rng.random() < 0.95:
        rec["reference_name"] = rng.choice(CONTIG_POOL)
    if rng.random() < 0.95:
        rec["start"] = rng.randrange(0, 10_000_000)
    if rng.random() < 0.95:
        rec["end"] = rng.randrange(0, 10_000_000)
    if rng.random() < 0.6:
        rec["variant_set_id"] = rng.choice(VSID_POOL)
    if rng.random() < 0.5:
        rec["reference_bases"] = rng.choice(["A", "N", "ACGT", ""])
    if rng.random() < 0.4:
        rec["alternate_bases"] = rng.choice(
            [["G"], ["G", "T"], [], None, "AC", [None], 5]
        )
    if rng.random() < 0.1:
        rec["reference_bases"] = rng.choice([None, True, 7])
    if rng.random() < 0.6:
        info = {}
        if rng.random() < 0.8:
            info["AF"] = rng.choice(AF_POOL)
        if rng.random() < 0.3:
            info["OTHER"] = ["x", 1, None]
        rec["info"] = info
    if rng.random() < 0.85:
        calls = []
        for _ in range(rng.randrange(0, 5)):
            call = {}
            if rng.random() < 0.95:
                call["callset_id"] = rng.choice(
                    CALLSET_IDS + ["ghost", "üid"]
                )
            if rng.random() < 0.95:
                call["genotype"] = rng.choice(GT_POOL)
            if rng.random() < 0.2:
                call["phaseset"] = rng.choice(["ps1", ""])
            if rng.random() < 0.1:
                call["info"] = {"DP": [rng.randrange(0, 99)]}
            calls.append(call)
        rec["calls"] = calls
    return rec


def _adversarial_lines(rng):
    """Raw lines json.dumps cannot produce: duplicate keys, weird tokens,
    broken JSON. The native parser must refuse or match Python."""
    return [
        # duplicate extracted keys (json.loads: last-wins)
        '{"reference_name": "17", "start": 1, "calls": '
        '[{"callset_id": "cs-0", "genotype": [1]}], "calls": '
        '[{"callset_id": "cs-1", "genotype": [1]}]}',
        '{"reference_name": "17", "reference_name": "18", "start": 2, '
        '"calls": []}',
        '{"reference_name": "17", "start": 3, "start": 4, "calls": []}',
        '{"reference_name": "17", "start": 5, "info": {"AF": ["0.1"]}, '
        '"info": {}}',
        # invalid bare tokens / broken JSON (json.loads raises)
        '{"reference_name": "17", "start": 6, "junk": blah}',
        '{"reference_name": "17", "start": 7',
        '{"reference_name": "17", "start": 8, "info": {"AF": [0x10]}}',
        "not json at all",
        # non-JSON integers and float-grammar mismatches
        '{"reference_name": "17", "start": 012, "calls": []}',
        '{"reference_name": "17", "start": 1, "calls": '
        '[{"callset_id": "cs-0", "genotype": [01]}]}',
        '{"reference_name": "17", "start": 2, "info": {"AF": ["0x10"]}, '
        '"calls": []}',
        '{"reference_name": "17", "start": 3, "info": {"AF": ["1_5"]}, '
        '"calls": []}',
        '{"reference_name": "17", "start": 4, "info": {"AF": ["."]}, '
        '"calls": []}',
        # escapes in extracted strings (valid JSON; native must refuse)
        '{"reference_name": "chr\\u005f17", "start": 9, "calls": []}',
        '{"reference_name": "17", "start": 10, "variant_set_id": '
        '"a\\"b", "calls": []}',
        # whitespace/format variants (valid)
        '  {  "reference_name" : "17" , "start" : 11 , "calls" : [ ] }  ',
        '{"reference_name": "17", "start": 12, "extra": {"deep": '
        '[{"n": [1, 2, {"x": null}]}, true, false]}, "calls": []}',
    ]


def _compare(tmp_path, lines, tag):
    root = tmp_path / tag
    os.makedirs(root)
    (root / "callsets.json").write_text(
        json.dumps(
            [
                {"id": cid, "name": cid, "variant_set_id": "vs1"}
                for cid in CALLSET_IDS
            ]
        )
    )
    (root / "variants.jsonl").write_text(
        "\n".join(lines) + ("\n" if lines else "")
    )
    js = JsonlSource(str(root))
    native = _CsrCohort._parse_native(str(root), CALLSET_IDS)
    try:
        python = _CsrCohort._parse_python(js._open, CALLSET_IDS)
        python_raised = None
    except Exception as e:  # noqa: BLE001 — part of the contract
        python_raised = e
        python = None
    if python_raised is not None:
        # Python refuses the file: native must have refused too.
        assert native is None, (
            f"native accepted a file Python rejects ({python_raised!r})"
        )
        return
    if native is None:
        return  # conservative refusal is always allowed
    for name, a, b in zip(
        (
            "contig_table",
            "rec_contig",
            "starts",
            "vsid_table",
            "rec_vsid",
            "afs",
            "offsets",
            "ords",
            "extra_ids",
            "ends",
            "refs",
            "alts",
        ),
        native,
        python,
    ):
        if isinstance(a, list):
            assert a == b, (tag, name, a, b)
        else:
            np.testing.assert_array_equal(a, b, err_msg=f"{tag}:{name}")


@pytest.mark.parametrize("seed", range(12))
def test_random_cohorts_native_matches_or_refuses(tmp_path, seed):
    rng = random.Random(seed)
    n = rng.randrange(1, 60)
    ensure_ascii = rng.random() < 0.5
    lines = [
        json.dumps(_random_record(rng), ensure_ascii=ensure_ascii)
        for _ in range(n)
    ]
    _compare(tmp_path, lines, f"seed{seed}")


def test_adversarial_lines_one_per_file(tmp_path):
    rng = random.Random(99)
    for i, line in enumerate(_adversarial_lines(rng)):
        _compare(tmp_path, [line], f"adv{i}")


def test_adversarial_lines_mixed_with_valid(tmp_path):
    rng = random.Random(7)
    valid = [json.dumps(_random_record(rng)) for _ in range(5)]
    for i, line in enumerate(_adversarial_lines(rng)):
        _compare(tmp_path, valid + [line], f"mix{i}")


class TestCsrToPackedBlocksFuzz:
    """Differential fuzz for the native packed-block scatter: for ANY
    CSR window, ``csr_to_packed_blocks`` must be byte-identical to the
    numpy reference (densify → ``np.packbits``) — the fallback path —
    and both must reject out-of-range indices identically. The packed
    bytes ARE the device feed, so a single divergent bit is a wrong G.
    """

    @staticmethod
    def _reference_pack(window_idx, lens, n_samples, block_variants):
        """Densify + packbits — the historical composition the packed
        path must reproduce bit-for-bit."""
        cols = np.repeat(np.arange(lens.size, dtype=np.int64), lens)
        x = np.zeros((n_samples, block_variants), dtype=np.int8)
        x[window_idx, cols] = 1
        return np.packbits(x.astype(bool), axis=1)

    def _both_paths(self, window_idx, lens, n_samples, block_variants):
        from spark_examples_tpu.arrays.blocks import packed_block_from_csr

        native = packed_block_from_csr(
            window_idx, lens, n_samples, block_variants
        )
        with _force_python_fallback():
            python = packed_block_from_csr(
                window_idx, lens, n_samples, block_variants
            )
        return native, python

    @pytest.mark.parametrize("seed", range(20))
    def test_random_windows_bit_identical(self, seed):
        rng = np.random.default_rng(seed)
        n_samples = int(rng.integers(1, 80))
        block_variants = int(rng.integers(1, 70))
        rows = int(rng.integers(0, block_variants + 1))
        # Duplicate indices within a variant allowed: both the dense
        # scatter and the bit-OR are idempotent, so they must agree.
        lens = rng.integers(0, n_samples + 1, rows)
        window_idx = (
            rng.integers(0, n_samples, int(lens.sum()), dtype=np.int64)
            if lens.sum()
            else np.zeros(0, np.int64)
        )
        want = self._reference_pack(
            window_idx, lens, n_samples, block_variants
        )
        native, python = self._both_paths(
            window_idx, lens, n_samples, block_variants
        )
        assert native.dtype == np.uint8 and native.shape == want.shape
        np.testing.assert_array_equal(native, want, err_msg=f"seed {seed}")
        np.testing.assert_array_equal(python, want, err_msg=f"seed {seed}")

    def test_empty_window(self):
        native, python = self._both_paths(
            np.zeros(0, np.int64), np.zeros(0, np.int64), 11, 16
        )
        assert native.shape == (11, 2) and not native.any()
        np.testing.assert_array_equal(native, python)

    def test_pad_columns_stay_zero(self):
        # 3 real variants in a 21-wide block (21 → 3 packed bytes, 5 pad
        # bits in the last byte): every pad bit must be zero — pad bits
        # are only inert in the Gramian if they ARE zero.
        lens = np.array([2, 0, 1], np.int64)
        idx = np.array([0, 4, 3], np.int64)
        want = self._reference_pack(idx, lens, 5, 21)
        native, python = self._both_paths(idx, lens, 5, 21)
        np.testing.assert_array_equal(native, want)
        np.testing.assert_array_equal(python, want)
        assert native[:, 0].max() > 0  # real bits landed
        # Columns 3.. of the bit-unpacked form are pad.
        assert not np.unpackbits(native, axis=1)[:, 3:].any()

    def test_max_density_rows(self):
        # Every sample carries every variant: all real bits set.
        n, bv = 9, 24
        lens = np.full(bv, n, np.int64)
        idx = np.tile(np.arange(n, dtype=np.int64), bv)
        native, python = self._both_paths(idx, lens, n, bv)
        assert (native == 0xFF).all()
        np.testing.assert_array_equal(native, python)

    @pytest.mark.parametrize("bad", [-1, 7, 99])
    def test_out_of_range_index_rejected(self, bad):
        from spark_examples_tpu.arrays.blocks import packed_block_from_csr

        lens = np.array([1], np.int64)
        idx = np.array([bad], np.int64)
        with pytest.raises(ValueError, match="out of range"):
            packed_block_from_csr(idx, lens, 7, 8)
        with _force_python_fallback():
            with pytest.raises(ValueError, match="out of range"):
                packed_block_from_csr(idx, lens, 7, 8)

    def test_native_kernel_rejects_out_of_range_directly(self):
        # The C routine's own guard (the Python wrapper checks first;
        # this pins the double-guard so a corrupt window can never
        # silently drop a carrier even if called raw).
        lib = load()
        idx = np.array([5], np.int64)
        offs = np.array([0, 1], np.int64)
        out = np.zeros((4, 1), np.uint8)
        rc = lib.csr_to_packed_blocks(
            idx.ctypes.data, offs.ctypes.data, 1, 4, 1, out.ctypes.data
        )
        assert rc == 1
