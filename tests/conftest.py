"""Test harness: force an 8-device virtual CPU mesh before any backend init.

Multi-chip TPU hardware is not available in CI; sharding tests run on a
virtual CPU mesh via ``--xla_force_host_platform_device_count=8`` (SURVEY.md
§4's multi-device test strategy).

Note: in the axon environment, ``sitecustomize.py`` imports jax at
interpreter startup with ``JAX_PLATFORMS=axon``, so the env var alone is
baked in before this conftest runs — ``jax.config.update`` is required (the
backend itself initializes lazily, so this still takes effect as long as no
test module touched a device at import time).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
