"""Unified telemetry layer: tracer, registry, manifest, validation.

Pins the contracts the observability subsystem ships on:

- registry counter/histogram semantics under concurrent writers (the
  transports feed them from shard-parallel ingest threads);
- Chrome-trace JSON schema round-trip — every emitted trace must pass
  ``scripts/validate_trace.py`` (the same check CI applies), i.e. load
  in Perfetto;
- ``StageTimer`` thread-safety (thread-local span stacks, locked
  accumulation) and its unchanged report block;
- ``IoStats`` parity: the exact ``report()`` format the reference pins
  (VariantsRDD.scala:168-180) AND the counters' visibility through the
  registry collector, including after the owning source is dropped;
- manifest emission from a real (tiny, CPU) CLI pipeline run with
  ``--trace-out/--metrics-out/--manifest-out`` — the acceptance shape:
  stage timings, the parity counters, and an RPC latency histogram, all
  schema-valid.
"""

from __future__ import annotations

import importlib.util
import json
import os
import threading

import pytest

from spark_examples_tpu import obs
from spark_examples_tpu.obs.metrics import MetricsRegistry
from spark_examples_tpu.obs.session import TelemetrySession
from spark_examples_tpu.obs.tracer import SpanTracer
from spark_examples_tpu.utils.stats import IoStats
from spark_examples_tpu.utils.tracing import StageTimer

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_trace",
        os.path.join(_REPO_ROOT, "scripts", "validate_trace.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


validate = _load_validator()


class TestMetricsRegistry:
    def test_counter_semantics_under_threads(self):
        reg = MetricsRegistry()
        c = reg.counter("req_total", "requests")
        n_threads, per_thread = 8, 2000

        def work():
            for _ in range(per_thread):
                c.inc()

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n_threads * per_thread

    def test_histogram_semantics_under_threads(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
        values = [0.05, 0.5, 5.0, 50.0]

        def work():
            for v in values:
                h.observe(v)

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert h.count == 8 * len(values)
        assert h.sum == pytest.approx(8 * sum(values))
        s = h.summary()
        assert s["count"] == 32
        assert s["min"] == pytest.approx(0.05)
        assert s["max"] == pytest.approx(50.0)
        assert 0.0 < s["p50"] <= 10.0

    def test_counters_only_go_up(self):
        c = MetricsRegistry().counter("c_total")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_kind_conflict_is_loud(self):
        reg = MetricsRegistry()
        reg.counter("x_total")
        with pytest.raises(ValueError):
            reg.gauge("x_total")

    def test_labels_partition_series(self):
        reg = MetricsRegistry()
        c = reg.counter("rpc_total")
        c.labels(transport="http").inc(2)
        c.labels(transport="grpc").inc(3)
        snap = reg.snapshot()
        assert snap["counters"]['rpc_total{transport="http"}'] == 2
        assert snap["counters"]['rpc_total{transport="grpc"}'] == 3

    def test_prometheus_exposition_is_schema_valid(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("a_total", "help text").inc(5)
        reg.gauge("g_now").set(-2.5)
        h = reg.histogram("h_seconds", "latency")
        h.labels(method="x").observe(0.3)
        path = str(tmp_path / "m.prom")
        reg.write_prometheus(path)
        assert validate.validate_metrics(path) == []
        text = open(path).read()
        assert "# TYPE a_total counter" in text
        assert 'h_seconds_bucket{method="x",le="+Inf"} 1' in text

    def test_jsonl_sink_appends_snapshots(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("a_total").inc()
        path = str(tmp_path / "m.jsonl")
        reg.write_jsonl(path)
        reg.counter("a_total").inc()
        reg.write_jsonl(path)
        lines = [json.loads(l) for l in open(path)]
        assert len(lines) == 2
        assert lines[0]["counters"]["a_total"] == 1
        assert lines[1]["counters"]["a_total"] == 2


class TestSpanTracer:
    def test_trace_schema_roundtrip_under_threads(self, tmp_path):
        tracer = SpanTracer()

        def work(i):
            with tracer.span("outer", worker=i):
                with tracer.span("inner"):
                    tracer.instant("mark", i=i)

        threads = [
            threading.Thread(target=work, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        path = str(tmp_path / "t.trace.json")
        tracer.write(path)
        assert validate.validate_trace(path) == []
        doc = json.load(open(path))
        names = [e["name"] for e in doc["traceEvents"]]
        assert names.count("outer") == 6
        assert names.count("inner") == 6
        assert names.count("mark") == 6
        # Aggregates: each name accumulated once per span.
        assert tracer.stage_counts()["outer"] == 6
        assert tracer.stage_seconds()["inner"] >= 0.0

    def test_span_stack_is_thread_local(self):
        tracer = SpanTracer()
        seen = {}
        gate = threading.Barrier(2)

        def a():
            with tracer.span("a"):
                gate.wait()
                seen["a"] = tracer.current_span()
                gate.wait()

        def b():
            with tracer.span("b"):
                gate.wait()
                seen["b"] = tracer.current_span()
                gate.wait()

        ta, tb = threading.Thread(target=a), threading.Thread(target=b)
        ta.start(), tb.start()
        ta.join(), tb.join()
        assert seen == {"a": "a", "b": "b"}

    def test_event_cap_counts_drops(self, tmp_path):
        tracer = SpanTracer(max_events=3)
        for i in range(10):
            tracer.instant(f"e{i}")
        doc = tracer.to_chrome()
        dropped = [
            e
            for e in doc["traceEvents"]
            if e["name"] == "tracer_events_dropped"
        ]
        assert dropped and dropped[0]["args"]["dropped"] == 7

    def test_ambient_helpers_noop_without_session(self):
        # No session active: module helpers must not record anywhere.
        assert not obs.collection_active()
        with obs.span("ghost"):
            obs.instant("ghost_mark")
        # A fresh session must not see pre-session ghosts.
        with TelemetrySession() as s:
            assert obs.collection_active()
        assert "ghost" not in s.tracer.stage_seconds()


class TestStageTimer:
    def test_report_format_unchanged(self):
        timer = StageTimer()
        with timer.stage("ingest"):
            timer.note("a note")
        with timer.stage("pca"):
            pass
        timer.note("orphan note")
        report = timer.report()
        lines = report.splitlines()
        assert lines[0] == "Stage wall-clock"
        assert lines[1] == "----------------"
        assert lines[2].startswith("ingest: ") and "%" in lines[2]
        assert lines[3] == "  a note"
        assert lines[4].startswith("pca: ")
        assert lines[5] == "orphan note"
        assert lines[6].startswith("total: ")

    def test_concurrent_stages_accumulate_safely(self):
        timer = StageTimer()
        n_threads, per_thread = 8, 50

        def work(i):
            for _ in range(per_thread):
                with timer.stage("shared"):
                    pass
                with timer.stage(f"own-{i}"):
                    timer.note(f"note-{i}")

        threads = [
            threading.Thread(target=work, args=(i,)) for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert timer.seconds["shared"] >= 0.0
        assert len(timer.seconds) == 1 + n_threads
        # Notes filed under the stage open on THEIR thread, never a
        # sibling's (the thread-local stack contract).
        for i in range(n_threads):
            assert timer.notes[f"own-{i}"] == [f"note-{i}"] * per_thread

    def test_stages_mirror_into_active_session(self):
        with TelemetrySession() as s:
            timer = StageTimer()
            with timer.stage("mirrored"):
                pass
        assert "mirrored" in s.tracer.stage_seconds()


class TestIoStatsRegistryBacking:
    def test_report_block_format_exact(self):
        stats = IoStats()
        stats.add(partitions=2, variants_read=7, reference_bases=100)
        assert stats.report() == (
            "Variants API stats\n"
            "------------------\n"
            "# of partitions: 2\n"
            "# of reference bases requested: 100\n"
            "# of API requests: 0\n"
            "# of unsuccessful responses: 0\n"
            "# of IO exceptions: 0\n"
            "# of variants read: 7\n"
            "# of reads read: 0\n"
        )

    def test_live_instance_visible_through_collector(self):
        reg = MetricsRegistry()
        before = reg.snapshot()["counters"][
            "genomics_io_variants_read_total"
        ]
        stats = IoStats()
        stats.add(variants_read=11)
        after = reg.snapshot()["counters"][
            "genomics_io_variants_read_total"
        ]
        assert after - before == 11
        del stats  # keep referenced until the second snapshot

    def test_untracked_merge_view_is_invisible_to_collector(self):
        # allreduce_host_stats builds a merged VIEW of per-source
        # counters; tracking it would double-count multi-host manifests.
        reg = MetricsRegistry()
        before = reg.snapshot()["counters"][
            "genomics_io_variants_read_total"
        ]
        src = IoStats()
        src.add(variants_read=9)
        merged = IoStats.untracked()
        merged.merge(src)
        after = reg.snapshot()["counters"][
            "genomics_io_variants_read_total"
        ]
        assert after - before == 9  # src only, never the merged copy
        del merged
        import gc

        gc.collect()
        final = reg.snapshot()["counters"][
            "genomics_io_variants_read_total"
        ]
        assert final - before == 9  # untracked never retires either
        del src

    def test_dropped_instance_counts_are_retired_not_lost(self):
        reg = MetricsRegistry()
        stats = IoStats()
        stats.add(requests=5)
        del stats
        import gc

        gc.collect()
        # The retired totals keep contributing after GC — the end-of-run
        # manifest flush happens after the driver drops its source.
        assert (
            reg.snapshot()["counters"]["genomics_io_requests_total"] >= 5
        )


class TestValidator:
    def test_malformed_trace_fails(self, tmp_path):
        path = tmp_path / "bad.trace.json"
        path.write_text(
            json.dumps(
                {"traceEvents": [{"name": "x", "ph": "X", "pid": 1}]}
            )
        )
        errs = validate.validate_trace(str(path))
        assert any("ts" in e for e in errs)
        assert any("dur" in e for e in errs)

    def test_malformed_metrics_fails(self, tmp_path):
        path = tmp_path / "bad.prom"
        path.write_text("this is { not prometheus\n")
        assert validate.validate_metrics(str(path)) != []

    def test_manifest_missing_keys_fails(self, tmp_path):
        path = tmp_path / "bad.manifest.json"
        path.write_text(json.dumps({"schema": "nope"}))
        errs = validate.validate_manifest(str(path))
        assert any("stages" in e for e in errs)

    def test_cli_entry_point_exit_codes(self, tmp_path, capsys):
        good = tmp_path / "ok.trace.json"
        t = SpanTracer()
        t.instant("x")
        t.write(str(good))
        assert validate.main(["--trace", str(good)]) == 0
        bad = tmp_path / "bad.trace.json"
        bad.write_text("{}")
        assert validate.main(["--trace", str(bad)]) == 1


class TestTelemetrySession:
    def test_artifacts_written_on_failure_path(self, tmp_path):
        trace = str(tmp_path / "f.trace.json")
        manifest = str(tmp_path / "f.manifest.json")
        with pytest.raises(RuntimeError):
            with TelemetrySession(
                trace_out=trace, manifest_out=manifest, command="boom"
            ):
                with obs.span("doomed"):
                    raise RuntimeError("simulated crash")
        assert validate.validate_trace(trace) == []
        mf = json.load(open(manifest))
        assert mf["outcome"] == "error"
        assert "doomed" in mf["stages"]

    def test_flush_telemetry_midrun(self, tmp_path):
        # The watchdog's fail-stop path: flush BEFORE os._exit.
        trace = str(tmp_path / "w.trace.json")
        with TelemetrySession(trace_out=trace):
            obs.instant("collective_watchdog_fired", phase="merge")
            obs.flush_telemetry(reason="test")
            assert validate.validate_trace(trace) == []
            doc = json.load(open(trace))
            names = [e["name"] for e in doc["traceEvents"]]
            assert "collective_watchdog_fired" in names

    def test_rpc_timer_feeds_session_registry(self):
        with TelemetrySession() as s:
            with obs.rpc_timer("test", "Op"):
                pass
            with pytest.raises(IOError):
                with obs.rpc_timer("test", "Op"):
                    raise IOError("boom")
        snap = s.registry.snapshot()
        key = 'genomics_rpc_latency_seconds{method="Op",transport="test"}'
        assert snap["histograms"][key]["count"] == 2
        err_key = 'genomics_rpc_errors_total{method="Op",transport="test"}'
        assert snap["counters"][err_key] == 1


class TestPipelineManifestEmission:
    """The acceptance shape: a CPU-only CLI pca run with all three
    outputs produces Perfetto-loadable trace JSON, a valid Prometheus
    dump, and a manifest with stage timings + parity counters + an RPC
    latency histogram."""

    @pytest.fixture(scope="class")
    def run_artifacts(self, tmp_path_factory):
        from spark_examples_tpu.cli.main import main

        tmp_path = tmp_path_factory.mktemp("obs_cli")
        paths = {
            "trace": str(tmp_path / "run.trace.json"),
            "metrics": str(tmp_path / "run.metrics.prom"),
            "manifest": str(tmp_path / "run.manifest.json"),
        }
        old = os.environ.get("SPARK_EXAMPLES_TPU_COMPILE_CACHE")
        os.environ["SPARK_EXAMPLES_TPU_COMPILE_CACHE"] = "0"
        try:
            rc = main(
                [
                    "pca",
                    "--fixture-samples",
                    "8",
                    "--fixture-variants",
                    "64",
                    "--output-path",
                    str(tmp_path / "out"),
                    "--trace-out",
                    paths["trace"],
                    "--metrics-out",
                    paths["metrics"],
                    "--manifest-out",
                    paths["manifest"],
                ]
            )
        finally:
            if old is None:
                os.environ.pop("SPARK_EXAMPLES_TPU_COMPILE_CACHE", None)
            else:
                os.environ["SPARK_EXAMPLES_TPU_COMPILE_CACHE"] = old
        assert rc == 0
        return paths

    def test_all_artifacts_schema_valid(self, run_artifacts):
        assert validate.validate_trace(run_artifacts["trace"]) == []
        assert validate.validate_metrics(run_artifacts["metrics"]) == []
        assert (
            validate.validate_manifest(run_artifacts["manifest"]) == []
        )

    def test_manifest_has_stage_timings(self, run_artifacts):
        mf = json.load(open(run_artifacts["manifest"]))
        for stage in ("run", "ingest+gramian", "pca", "emit"):
            assert stage in mf["stages"], mf["stages"].keys()
            assert mf["stages"][stage]["seconds"] >= 0.0
        assert mf["command"] == "pca"
        assert mf["config"]["fixture_samples"] == 8
        assert mf["environment"]["jax"]["backend"] == "cpu"

    def test_manifest_has_parity_counters(self, run_artifacts):
        mf = json.load(open(run_artifacts["manifest"]))
        for field in (
            "partitions",
            "reference_bases",
            "requests",
            "unsuccessful_responses",
            "io_exceptions",
            "variants_read",
            "reads_read",
        ):
            assert f"genomics_io_{field}_total" in mf["counters"]
        # The run read its 64 fixture variants (process-cumulative
        # counter: other tests may have contributed more).
        assert mf["counters"]["genomics_io_variants_read_total"] >= 64
        # The driver-merged job-end totals are exact per run (gauges in
        # the session-fresh registry, set at report_io_stats time).
        assert mf["gauges"]["genomics_io_merged_variants_read"] == 64

    def test_manifest_has_rpc_latency_histogram(self, run_artifacts):
        mf = json.load(open(run_artifacts["manifest"]))
        rpc = {
            k: v
            for k, v in mf["histograms"].items()
            if k.startswith("genomics_rpc_latency_seconds")
        }
        assert rpc, list(mf["histograms"])
        assert any(v["count"] >= 1 for v in rpc.values())

    def test_trace_has_driver_stages(self, run_artifacts):
        doc = json.load(open(run_artifacts["trace"]))
        names = {e["name"] for e in doc["traceEvents"]}
        assert {"run", "ingest+gramian", "pca", "emit"} <= names


class TestIngestSpanEmission:
    """The parallel ingest engine's sub-phase observability: a CLI run
    over a JSONL cohort (the CSR-direct route) must land `ingest.slice`
    / `ingest.build` / `ingest.put` spans on the timeline and the
    `ingest_blocks_built_total` / `ingest_block_build_seconds` series
    in the metrics dump — and every artifact must pass the validator's
    ingest schema checks."""

    @pytest.fixture(scope="class")
    def run_artifacts(self, tmp_path_factory):
        from spark_examples_tpu.cli.main import main
        from spark_examples_tpu.genomics.fixtures import synthetic_cohort

        tmp_path = tmp_path_factory.mktemp("obs_ingest")
        root = str(tmp_path / "cohort")
        synthetic_cohort(10, 60, seed=3).dump(root)
        paths = {
            "trace": str(tmp_path / "run.trace.json"),
            "metrics": str(tmp_path / "run.metrics.prom"),
            "manifest": str(tmp_path / "run.manifest.json"),
        }
        old = os.environ.get("SPARK_EXAMPLES_TPU_COMPILE_CACHE")
        os.environ["SPARK_EXAMPLES_TPU_COMPILE_CACHE"] = "0"
        try:
            rc = main(
                [
                    "pca",
                    "--input-path",
                    root,
                    "--block-variants",
                    "32",
                    "--ingest-workers",
                    "2",
                    "--trace-out",
                    paths["trace"],
                    "--metrics-out",
                    paths["metrics"],
                    "--manifest-out",
                    paths["manifest"],
                ]
            )
        finally:
            if old is None:
                os.environ.pop("SPARK_EXAMPLES_TPU_COMPILE_CACHE", None)
            else:
                os.environ["SPARK_EXAMPLES_TPU_COMPILE_CACHE"] = old
        assert rc == 0
        return paths

    def test_ingest_sub_phase_spans_present(self, run_artifacts):
        doc = json.load(open(run_artifacts["trace"]))
        names = {e["name"] for e in doc["traceEvents"]}
        assert {"ingest.slice", "ingest.build", "ingest.put"} <= names

    def test_ingest_metrics_present_with_mode_label(self, run_artifacts):
        prom = open(run_artifacts["metrics"]).read()
        blocks = [
            ln
            for ln in prom.splitlines()
            if ln.startswith("ingest_blocks_built_total")
        ]
        assert blocks and all('mode="' in ln for ln in blocks)
        assert "ingest_block_build_seconds_bucket" in prom
        assert "ingest_block_build_seconds_sum" in prom
        assert "ingest_block_build_seconds_count" in prom

    def test_artifacts_pass_ingest_schema_checks(self, run_artifacts):
        assert validate.validate_trace(run_artifacts["trace"]) == []
        assert validate.validate_metrics(run_artifacts["metrics"]) == []
        assert validate.validate_manifest(run_artifacts["manifest"]) == []

    def test_validator_rejects_unknown_ingest_span(self, tmp_path):
        path = tmp_path / "bad.trace.json"
        path.write_text(
            json.dumps(
                {
                    "traceEvents": [
                        {
                            "ph": "X",
                            "name": "ingest.densify",
                            "pid": 1,
                            "tid": 1,
                            "ts": 0,
                            "dur": 5,
                        }
                    ]
                }
            )
        )
        errs = validate.validate_trace(str(path))
        assert errs and "ingest.densify" in errs[0]

    def test_validator_rejects_modeless_ingest_counter(self, tmp_path):
        path = tmp_path / "bad.metrics.prom"
        path.write_text(
            "# HELP ingest_blocks_built_total blocks\n"
            "# TYPE ingest_blocks_built_total counter\n"
            "ingest_blocks_built_total 5\n"
        )
        errs = validate.validate_metrics(str(path))
        assert errs and "mode" in errs[0]

    def test_manifest_carries_build_histogram(self, run_artifacts):
        mf = json.load(open(run_artifacts["manifest"]))
        hists = {
            k: v
            for k, v in mf["histograms"].items()
            if k.startswith("ingest_block_build_seconds")
        }
        assert hists and any(v["count"] >= 1 for v in hists.values())


class TestTraceContext:
    """PR 16 job-scoped tracing: a trace id is a CONTEXT FIELD stamped
    onto every span/instant emitted under it — not a new span set."""

    def test_default_is_unbound(self):
        assert obs.current_trace_id() is None

    def test_binding_restores_and_none_inherits(self):
        with obs.trace_context("aaaa"):
            assert obs.current_trace_id() == "aaaa"
            # None = "keep whatever is bound": call sites never need a
            # conditional around the context manager.
            with obs.trace_context(None):
                assert obs.current_trace_id() == "aaaa"
            with obs.trace_context("bbbb"):
                assert obs.current_trace_id() == "bbbb"
            assert obs.current_trace_id() == "aaaa"
        assert obs.current_trace_id() is None

    def test_binding_is_thread_local(self):
        seen = []

        def other():
            seen.append(obs.current_trace_id())

        with obs.trace_context("aaaa"):
            t = threading.Thread(target=other)
            t.start()
            t.join()
        assert seen == [None]

    def test_spans_and_instants_carry_the_id_counters_do_not(self):
        with TelemetrySession() as session:
            with obs.trace_context("tid1"):
                with obs.span("fused_finish", n=1):
                    pass
                obs.instant("job_transition", scope="p", to="running")
                obs.counter("serving_queue_depth", depth=3.0)
            with obs.span("fused_finish", n=2):
                pass
            events = session.tracer.to_chrome()["traceEvents"]
        tagged = [
            ev
            for ev in events
            if isinstance(ev.get("args"), dict)
            and ev["args"].get("trace_id") == "tid1"
        ]
        assert {ev["ph"] for ev in tagged} == {"X", "i"}
        # Counter tracks must stay numeric-only (stacked-area
        # rendering) — never stamped.
        counters = [ev for ev in events if ev["ph"] == "C"]
        assert counters and all(
            "trace_id" not in ev["args"] for ev in counters
        )
        # The second span ran outside the context: untagged.
        untagged = [
            ev
            for ev in events
            if ev["ph"] == "X" and ev["args"].get("n") == 2
        ]
        assert untagged and "trace_id" not in untagged[0]["args"]

    def test_events_for_trace_filters_and_orders(self):
        with TelemetrySession() as session:
            with obs.trace_context("tidA"):
                with obs.span("fused_finish", leg=1):
                    pass
            with obs.trace_context("tidB"):
                with obs.span("fused_finish", leg=2):
                    pass
            with obs.trace_context("tidA"):
                obs.instant("job_transition", scope="p", to="done")
            evs = session.tracer.events_for_trace("tidA")
            assert [e["args"].get("leg", None) for e in evs] == [1, None]
            tss = [float(e["ts"]) for e in evs]
            assert tss == sorted(tss)
            assert session.tracer.events_for_trace("nope") == []

    def test_trace_carries_process_provenance(self):
        import socket as socket_mod

        with TelemetrySession() as session:
            with obs.span("fused_finish"):
                pass
            other = session.tracer.to_chrome()["otherData"]
        assert other["host"] == socket_mod.gethostname()
        assert other["pid"] == os.getpid()
        assert isinstance(other["trace_epoch_unix"], float)


class TestFlightRecorder:
    """The crash black box: per-thread overwrite rings, reasoned JSONL
    dumps, hook/handler chaining — always on once installed, cheap
    enough for production (one global read when off)."""

    @pytest.fixture(autouse=True)
    def _clean(self):
        from spark_examples_tpu.obs import flightrec

        flightrec.uninstall()
        yield
        flightrec.uninstall()

    def test_ring_overwrites_keeping_the_last_k(self):
        from spark_examples_tpu.obs import flightrec

        rec = flightrec.FlightRecorder(capacity_per_thread=8)
        for i in range(30):
            rec.note("instant", f"ev{i}", {"i": i})
        snap = rec.snapshot()
        assert len(snap) == 8
        # Exactly the last 8 survive; snapshot order is by timestamp,
        # which can tie at clock resolution — compare as a set.
        assert sorted(r["fields"]["i"] for r in snap) == list(range(22, 30))

    def test_threads_write_locklessly_and_merge_sorted(self):
        from spark_examples_tpu.obs import flightrec

        rec = flightrec.FlightRecorder(capacity_per_thread=64)

        def work(tag):
            for i in range(50):
                rec.note("metric", tag, {"i": i})

        threads = [
            threading.Thread(target=work, args=(f"t{k}",), name=f"w{k}")
            for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = rec.snapshot()
        assert len(snap) == 200
        assert {r["thread"] for r in snap} == {f"w{k}" for k in range(4)}
        tss = [r["ts_unix"] for r in snap]
        assert tss == sorted(tss)

    def test_dump_schema_and_atomicity(self, tmp_path):
        from spark_examples_tpu.obs import flightrec

        rec = flightrec.FlightRecorder()
        rec.note("span_begin", "job.run", {"job_id": "j-1"})
        rec.note("metric", "serving_jobs_total", {"delta": 1.0})
        rec.note("instant", "bad", {"obj": object()})  # unserializable
        path = str(tmp_path / "d" / "flightrec-test.jsonl")
        rec.dump(path, "test")
        lines = [json.loads(l) for l in open(path)]
        header, records = lines[0], lines[1:]
        assert header["schema"] == "spark_examples_tpu.flightrec/v1"
        assert header["reason"] == "test"
        assert header["pid"] == os.getpid()
        assert [r["name"] for r in records] == [
            "job.run",
            "serving_jobs_total",
            "bad",
        ]
        assert records[2]["unserializable_fields"] is True
        assert not os.path.exists(path + ".tmp")  # tmp+rename, no ruins

    def test_ambient_helpers_tap_the_recorder_without_a_session(
        self, tmp_path
    ):
        """The black box works with tracing OFF — that is its reason to
        exist: span/instant transitions and metric deltas land in the
        rings even when no telemetry session is active."""
        from spark_examples_tpu.obs import flightrec

        assert not obs.collection_active()
        flightrec.install(str(tmp_path), handle_signals=False)
        with obs.span("job.run", job_id="j-9"):
            obs.instant("job_transition", scope="p", to="running")
        reg = obs.get_registry()
        reg.counter("serving_jobs_total").labels(outcome="done").inc()
        snap = flightrec.get_recorder().snapshot()
        kinds = {(r["kind"], r["name"]) for r in snap}
        assert ("span_begin", "job.run") in kinds
        assert ("span_end", "job.run") in kinds
        assert ("instant", "job_transition") in kinds
        assert ("metric", "serving_jobs_total") in kinds
        path = flightrec.dump_now("watchdog")
        assert path and path.endswith("flightrec-watchdog.jsonl")
        assert os.path.exists(path)

    def test_install_is_idempotent_and_uninstall_restores(self, tmp_path):
        import sys

        from spark_examples_tpu.obs import flightrec

        prev_hook = sys.excepthook
        rec1 = flightrec.install(str(tmp_path), handle_signals=False)
        rec2 = flightrec.install(str(tmp_path / "other"), handle_signals=False)
        assert rec1 is rec2
        assert sys.excepthook is not prev_hook
        flightrec.uninstall()
        assert sys.excepthook is prev_hook
        assert flightrec.get_recorder() is None
        flightrec.note("instant", "after", None)  # no-op, no crash

    def test_excepthook_dumps_then_chains(self, tmp_path, capsys):
        import sys

        from spark_examples_tpu.obs import flightrec

        seen = []
        prev_hook = sys.excepthook
        sys.excepthook = lambda *a: seen.append(a)
        try:
            flightrec.install(str(tmp_path), handle_signals=False)
            flightrec.note("instant", "before_crash", None)
            try:
                raise RuntimeError("boom")
            except RuntimeError:
                sys.excepthook(*sys.exc_info())
            dump = os.path.join(str(tmp_path), "flightrec-exception.jsonl")
            assert os.path.exists(dump)
            lines = [json.loads(l) for l in open(dump)]
            assert lines[0]["reason"] == "exception"
            names = [r["name"] for r in lines[1:]]
            assert "before_crash" in names
            assert "unhandled_exception" in names
            assert len(seen) == 1  # the previous hook still ran
        finally:
            flightrec.uninstall()
            sys.excepthook = prev_hook

    def test_periodic_flusher_writes_last_snapshot(self, tmp_path):
        import time as time_mod

        from spark_examples_tpu.obs import flightrec

        flightrec.install(
            str(tmp_path), flush_interval_s=0.05, handle_signals=False
        )
        flightrec.note("instant", "tick", None)
        last = os.path.join(str(tmp_path), "flightrec-last.jsonl")
        deadline = time_mod.time() + 5
        while time_mod.time() < deadline and not os.path.exists(last):
            time_mod.sleep(0.02)
        assert os.path.exists(last), "periodic flusher never wrote"
        lines = [json.loads(l) for l in open(last)]
        assert lines[0]["reason"] == "periodic"


class TestScrapeWhileWriting:
    """PR 16 satellite: a /metrics scrape (to_prometheus) racing hot
    writers must neither tear a histogram triplet, block the writers,
    nor double-count — pinned with the lock-check backstop armed."""

    @pytest.fixture(autouse=True)
    def _lock_check(self, monkeypatch):
        monkeypatch.setenv("SPARK_EXAMPLES_TPU_LOCK_CHECK", "1")
        yield

    def test_concurrent_scrape_is_consistent(self):
        reg = MetricsRegistry()
        n_threads, per_thread = 4, 3000
        stop = threading.Event()
        scrapes = []
        errors = []

        def writer(k):
            c = reg.counter("scrape_race_total", "writes")
            g = reg.gauge("scrape_race_inflight", "now")
            h = reg.histogram(
                "scrape_race_seconds", "lat", buckets=(0.1, 1.0)
            )
            try:
                for i in range(per_thread):
                    c.labels(worker=str(k)).inc()
                    g.set(float(i))
                    h.observe(0.05 if i % 2 else 5.0)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        def scraper():
            try:
                while not stop.is_set():
                    scrapes.append(reg.to_prometheus())
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(k,))
            for k in range(n_threads)
        ]
        s = threading.Thread(target=scraper)
        s.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        s.join(timeout=10)
        assert not s.is_alive() and not errors, errors
        assert scrapes, "scraper never completed a pass"
        # No double-count / no lost writes: the final exposition sums
        # to exactly what the writers wrote.
        final = reg.to_prometheus()
        import re as re_mod

        totals = [
            float(m.group(1))
            for m in re_mod.finditer(
                r'scrape_race_total\{worker="\d+"\} ([0-9.e+]+)', final
            )
        ]
        assert sum(totals) == n_threads * per_thread
        assert f"scrape_race_seconds_count {n_threads * per_thread}" in final
        # No torn triplet in ANY mid-run scrape: bucket lines never
        # appear without their sum/count (the schema checker's rule,
        # applied to every racing exposition).
        for text in scrapes[-5:]:
            if "scrape_race_seconds_bucket" in text:
                assert "scrape_race_seconds_sum" in text
                assert "scrape_race_seconds_count" in text
