"""Network VariantSource/ReadSource over the HTTP genomics service.

Covers the VERDICT round-1 gaps: a networked streaming ingest source
(VariantsRDD.scala:205-235 analog), auth consumed by ingest
(Client.scala:49-61), and unsuccessful_responses fed on real failures.
"""

import numpy as np
import pytest

from spark_examples_tpu.genomics.auth import Credentials
from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
    synthetic_reads,
)
from spark_examples_tpu.genomics.service import (
    GenomicsServiceServer,
    HttpVariantSource,
)
from spark_examples_tpu.genomics.shards import shards_for_references
from spark_examples_tpu.genomics.sources import JsonlSource

REFS = "17:41196311:41277499"


@pytest.fixture()
def served_cohort():
    src = synthetic_cohort(8, 60, seed=9)
    reads = synthetic_reads(
        20, references="17:41200000:41210000", seed=9
    ).reads_records()
    # One record with an info map: HTTP and local reads must agree on the
    # info value shape too, not just the scalar fields.
    reads[0]["info"] = {"XT": ["U"], "NM": [0, 1]}
    src.add_reads(reads)
    server = GenomicsServiceServer(src).start()
    try:
        yield src, HttpVariantSource(f"http://127.0.0.1:{server.port}")
    finally:
        server.stop()


class TestStreamParity:
    def test_variants_match_local_jsonl(self, served_cohort, tmp_path):
        src, http = served_cohort
        src.dump(str(tmp_path / "cohort"))
        local = JsonlSource(str(tmp_path / "cohort"))
        shards = shards_for_references(REFS, 20_000)
        for shard in shards:
            got = list(
                http.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
            )
            want = list(
                local.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
            )
            assert got == want  # frozen dataclasses: field-exact
        assert http.stats.variants_read == 60
        assert http.stats.partitions == len(shards)
        assert http.stats.unsuccessful_responses == 0

    def test_reads_roundtrip(self, served_cohort, tmp_path):
        src, http = served_cohort
        src.dump(str(tmp_path / "cohort"))
        local = JsonlSource(str(tmp_path / "cohort"))
        for shard in shards_for_references("17:41200000:41210000", 5_000):
            got = list(http.stream_reads("", shard))
            want = list(local.stream_reads("", shard))
            assert got == want

    def test_callsets(self, served_cohort):
        src, http = served_cohort
        assert http.list_callsets(DEFAULT_VARIANT_SET_ID) == (
            src.list_callsets(DEFAULT_VARIANT_SET_ID)
        )


class TestFraming:
    def test_sentinel_shaped_record_cannot_spoof_end_frame(self):
        """Framing is type-prefixed: a served record whose bytes match any
        end-of-stream marker must round-trip as data, never terminate the
        stream early (round-2 ADVICE: the old framing was in-band)."""
        inner = synthetic_cohort(4, 10, seed=1)

        class ServesHostileRecords:
            def list_callsets(self, vsid):
                return inner.list_callsets(vsid)

            def stream_variants(self, vsid, shard):
                # Raw dicts pass through the server unwrapped; these are
                # the closest on-the-wire shapes to the framing tokens.
                yield {"__end__": True}
                yield from inner.stream_variants(vsid, shard)

            def stream_reads(self, rgsid, shard):
                return inner.stream_reads(rgsid, shard)

        server = GenomicsServiceServer(ServesHostileRecords()).start()
        try:
            http = HttpVariantSource(f"http://127.0.0.1:{server.port}")
            shard = shards_for_references(REFS, 100_000)[0]
            # At the wire layer all 11 records arrive — the sentinel-shaped
            # one as plain data, then the real variants; nothing truncates.
            recs = list(http._wire_variant_records("", shard))
            assert recs[0] == {"__end__": True}
            assert len(recs) == 11
            assert http.stats.io_exceptions == 0
        finally:
            server.stop()


class TestAuth:
    def test_token_required(self):
        src = synthetic_cohort(4, 10, seed=1)
        server = GenomicsServiceServer(src, token="sekrit").start()
        url = f"http://127.0.0.1:{server.port}"
        shard = shards_for_references(REFS, 100_000)[0]
        try:
            anonymous = HttpVariantSource(url)
            with pytest.raises(IOError, match="401"):
                list(anonymous.stream_variants("", shard))
            assert anonymous.stats.unsuccessful_responses == 1

            wrong = HttpVariantSource(
                url, credentials=Credentials("nope", "client-secrets")
            )
            with pytest.raises(IOError, match="401"):
                wrong.list_callsets("")
            assert wrong.stats.unsuccessful_responses == 1

            good = HttpVariantSource(
                url, credentials=Credentials("sekrit", "client-secrets")
            )
            assert len(list(good.stream_variants("", shard))) == 10
            assert good.stats.unsuccessful_responses == 0
        finally:
            server.stop()

    def test_midstream_failure_raises_not_truncates(self):
        """A source dying after the 200 is on the wire must abort the
        chunked stream so the client errors — never a silent partial
        shard feeding the Gramian."""
        inner = synthetic_cohort(4, 10, seed=1)

        class FailsMidStream:
            def list_callsets(self, vsid):
                return inner.list_callsets(vsid)

            def stream_variants(self, vsid, shard):
                for i, v in enumerate(
                    inner.stream_variants(vsid, shard)
                ):
                    if i == 3:
                        raise IOError("disk died mid-shard")
                    yield v

            def stream_reads(self, rgsid, shard):
                return inner.stream_reads(rgsid, shard)

        server = GenomicsServiceServer(FailsMidStream()).start()
        try:
            http = HttpVariantSource(f"http://127.0.0.1:{server.port}")
            shard = shards_for_references(REFS, 100_000)[0]
            with pytest.raises(IOError, match="aborted mid-shard"):
                list(http.stream_variants("", shard))
            assert http.stats.io_exceptions == 1
        finally:
            server.stop()

    def test_prestream_failure_is_unsuccessful_response(self):
        """Fault injection BEFORE any record: a clean 500 counted as an
        unsuccessful response (the reference's failed-request counter)."""
        src = synthetic_cohort(4, 10, seed=1)
        shard = shards_for_references(REFS, 100_000)[0]
        src._fail_once.add(shard)
        server = GenomicsServiceServer(src).start()
        try:
            http = HttpVariantSource(f"http://127.0.0.1:{server.port}")
            with pytest.raises(IOError, match="500"):
                list(http.stream_variants("", shard))
            assert http.stats.unsuccessful_responses == 1
            # Idempotent manifest: the retry succeeds (fault cleared).
            assert len(list(http.stream_variants("", shard))) == 10
        finally:
            server.stop()

    def test_transport_failure_counts_io_exceptions(self):
        src = synthetic_cohort(4, 10, seed=1)
        server = GenomicsServiceServer(src).start()
        url = f"http://127.0.0.1:{server.port}"
        server.stop()  # port now closed: no response at all
        http = HttpVariantSource(url, timeout=5)
        shard = shards_for_references(REFS, 100_000)[0]
        with pytest.raises(IOError):
            list(http.stream_variants("", shard))
        assert http.stats.io_exceptions == 1
        assert http.stats.unsuccessful_responses == 0


class TestPipelineOverNetwork:
    def test_pca_driver_matches_local(self, served_cohort):
        src, http = served_cohort
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            bases_per_partition=20_000,
            block_variants=32,
        )
        remote = VariantsPcaDriver(conf, http).run()
        local = VariantsPcaDriver(
            conf, synthetic_cohort(8, 60, seed=9)
        ).run()
        assert [r[0] for r in remote] == [r[0] for r in local]
        np.testing.assert_allclose(
            np.array([r[1:] for r in remote]),
            np.array([r[1:] for r in local]),
            atol=1e-6,
        )
