"""Network VariantSource/ReadSource over the HTTP genomics service.

Covers the VERDICT round-1 gaps: a networked streaming ingest source
(VariantsRDD.scala:205-235 analog), auth consumed by ingest
(Client.scala:49-61), and unsuccessful_responses fed on real failures.
"""

import numpy as np
import pytest

from spark_examples_tpu.genomics.auth import Credentials
from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
    synthetic_reads,
)
from spark_examples_tpu.genomics.service import (
    GenomicsServiceServer,
    HttpVariantSource,
)
from spark_examples_tpu.genomics.shards import shards_for_references
from spark_examples_tpu.genomics.sources import JsonlSource

REFS = "17:41196311:41277499"


@pytest.fixture()
def served_cohort():
    src = synthetic_cohort(8, 60, seed=9)
    reads = synthetic_reads(
        20, references="17:41200000:41210000", seed=9
    ).reads_records()
    # One record with an info map: HTTP and local reads must agree on the
    # info value shape too, not just the scalar fields.
    reads[0]["info"] = {"XT": ["U"], "NM": [0, 1]}
    src.add_reads(reads)
    server = GenomicsServiceServer(src).start()
    try:
        yield src, HttpVariantSource(f"http://127.0.0.1:{server.port}")
    finally:
        server.stop()


class TestStreamParity:
    def test_variants_match_local_jsonl(self, served_cohort, tmp_path):
        src, http = served_cohort
        src.dump(str(tmp_path / "cohort"))
        local = JsonlSource(str(tmp_path / "cohort"))
        shards = shards_for_references(REFS, 20_000)
        for shard in shards:
            got = list(
                http.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
            )
            want = list(
                local.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
            )
            assert got == want  # frozen dataclasses: field-exact
        assert http.stats.variants_read == 60
        assert http.stats.partitions == len(shards)
        assert http.stats.unsuccessful_responses == 0

    def test_reads_roundtrip(self, served_cohort, tmp_path):
        src, http = served_cohort
        src.dump(str(tmp_path / "cohort"))
        local = JsonlSource(str(tmp_path / "cohort"))
        for shard in shards_for_references("17:41200000:41210000", 5_000):
            got = list(http.stream_reads("", shard))
            want = list(local.stream_reads("", shard))
            assert got == want

    def test_callsets(self, served_cohort):
        src, http = served_cohort
        assert http.list_callsets(DEFAULT_VARIANT_SET_ID) == (
            src.list_callsets(DEFAULT_VARIANT_SET_ID)
        )


class TestFraming:
    def test_sentinel_shaped_record_cannot_spoof_end_frame(self):
        """Framing is type-prefixed: a served record whose bytes match any
        end-of-stream marker must round-trip as data, never terminate the
        stream early (round-2 ADVICE: the old framing was in-band)."""
        inner = synthetic_cohort(4, 10, seed=1)

        class ServesHostileRecords:
            def list_callsets(self, vsid):
                return inner.list_callsets(vsid)

            def stream_variants(self, vsid, shard):
                # Raw dicts pass through the server unwrapped; these are
                # the closest on-the-wire shapes to the framing tokens.
                yield {"__end__": True}
                yield from inner.stream_variants(vsid, shard)

            def stream_reads(self, rgsid, shard):
                return inner.stream_reads(rgsid, shard)

        server = GenomicsServiceServer(ServesHostileRecords()).start()
        try:
            http = HttpVariantSource(f"http://127.0.0.1:{server.port}")
            shard = shards_for_references(REFS, 100_000)[0]
            # At the wire layer all 11 records arrive — the sentinel-shaped
            # one as plain data, then the real variants; nothing truncates.
            recs = list(http._wire_variant_records("", shard))
            assert recs[0] == {"__end__": True}
            assert len(recs) == 11
            assert http.stats.io_exceptions == 0
        finally:
            server.stop()


class TestAuth:
    def test_token_required(self):
        src = synthetic_cohort(4, 10, seed=1)
        server = GenomicsServiceServer(src, token="sekrit").start()
        url = f"http://127.0.0.1:{server.port}"
        shard = shards_for_references(REFS, 100_000)[0]
        try:
            anonymous = HttpVariantSource(url)
            with pytest.raises(IOError, match="401"):
                list(anonymous.stream_variants("", shard))
            assert anonymous.stats.unsuccessful_responses == 1

            wrong = HttpVariantSource(
                url, credentials=Credentials("nope", "client-secrets")
            )
            with pytest.raises(IOError, match="401"):
                wrong.list_callsets("")
            assert wrong.stats.unsuccessful_responses == 1

            good = HttpVariantSource(
                url, credentials=Credentials("sekrit", "client-secrets")
            )
            assert len(list(good.stream_variants("", shard))) == 10
            assert good.stats.unsuccessful_responses == 0
        finally:
            server.stop()

    def test_midstream_failure_raises_not_truncates(self):
        """A source dying after the 200 is on the wire must abort the
        chunked stream so the client errors — never a silent partial
        shard feeding the Gramian."""
        inner = synthetic_cohort(4, 10, seed=1)

        class FailsMidStream:
            def list_callsets(self, vsid):
                return inner.list_callsets(vsid)

            def stream_variants(self, vsid, shard):
                for i, v in enumerate(
                    inner.stream_variants(vsid, shard)
                ):
                    if i == 3:
                        raise IOError("disk died mid-shard")
                    yield v

            def stream_reads(self, rgsid, shard):
                return inner.stream_reads(rgsid, shard)

        server = GenomicsServiceServer(FailsMidStream()).start()
        try:
            http = HttpVariantSource(f"http://127.0.0.1:{server.port}")
            shard = shards_for_references(REFS, 100_000)[0]
            with pytest.raises(IOError, match="aborted mid-shard"):
                list(http.stream_variants("", shard))
            assert http.stats.io_exceptions == 1
        finally:
            server.stop()

    def test_prestream_failure_is_unsuccessful_response(self):
        """Fault injection BEFORE any record: a clean 500 counted as an
        unsuccessful response (the reference's failed-request counter)."""
        src = synthetic_cohort(4, 10, seed=1)
        shard = shards_for_references(REFS, 100_000)[0]
        src._fail_once.add(shard)
        server = GenomicsServiceServer(src).start()
        try:
            http = HttpVariantSource(f"http://127.0.0.1:{server.port}")
            with pytest.raises(IOError, match="500"):
                list(http.stream_variants("", shard))
            assert http.stats.unsuccessful_responses == 1
            # Idempotent manifest: the retry succeeds (fault cleared).
            assert len(list(http.stream_variants("", shard))) == 10
        finally:
            server.stop()

    def test_transport_failure_counts_io_exceptions(self):
        src = synthetic_cohort(4, 10, seed=1)
        server = GenomicsServiceServer(src).start()
        url = f"http://127.0.0.1:{server.port}"
        server.stop()  # port now closed: no response at all
        http = HttpVariantSource(url, timeout=5)
        shard = shards_for_references(REFS, 100_000)[0]
        with pytest.raises(IOError):
            list(http.stream_variants("", shard))
        assert http.stats.io_exceptions == 1
        assert http.stats.unsuccessful_responses == 0


class _CountingSource:
    """Wraps a source, counting data-plane stream calls — the probe for
    'the second cached run must not re-fetch /variants'."""

    def __init__(self, inner):
        self._inner = inner
        self.variant_streams = 0
        self.read_streams = 0
        self.exports = 0

    def list_callsets(self, vsid):
        return self._inner.list_callsets(vsid)

    def stream_variants(self, vsid, shard):
        self.variant_streams += 1
        return self._inner.stream_variants(vsid, shard)

    def stream_reads(self, rgsid, shard):
        self.read_streams += 1
        return self._inner.stream_reads(rgsid, shard)

    def cohort_identity(self):
        return self._inner.cohort_identity()

    def export_lines(self, name):
        self.exports += 1
        return self._inner.export_lines(name)


class TestWireEfficiency:
    def test_streams_are_gzip_encoded(self):
        """The client advertises gzip and the server honors it — JSONL
        compresses ~10×, the HTTP analog of the reference's binary gRPC
        streaming (VariantsRDD.scala:26,210-211)."""
        import urllib.request

        src = synthetic_cohort(8, 200, seed=3)
        server = GenomicsServiceServer(src).start()
        try:
            url = (
                f"http://127.0.0.1:{server.port}/variants?"
                "contig=17&start=41196311&end=41277499"
            )
            req = urllib.request.Request(url)
            req.add_header("Accept-Encoding", "gzip")
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.headers.get("Content-Encoding") == "gzip"
                gz_bytes = len(resp.read())
            with urllib.request.urlopen(url, timeout=30) as resp:
                assert resp.headers.get("Content-Encoding") is None
                raw_bytes = len(resp.read())
            assert gz_bytes < raw_bytes / 4  # JSONL compresses well
        finally:
            server.stop()

    def test_gzip_stream_parity(self, served_cohort, tmp_path):
        # The default client path IS gzip now; parity against the local
        # source (TestStreamParity) covers decode correctness. Here:
        # a plain-text client against the same server must agree too.
        src, http = served_cohort

        class NoGzip(HttpVariantSource):
            def _request(self, path, params, stream=False):
                import urllib.request

                from spark_examples_tpu.genomics.service import urlencode

                url = f"{self.base_url}{path}?{urlencode(params)}"
                self.stats.add(requests=1)
                return urllib.request.urlopen(url, timeout=30)

        plain = NoGzip(http.base_url)
        shard = shards_for_references(REFS, 100_000)[0]
        assert list(plain.stream_variants(DEFAULT_VARIANT_SET_ID, shard)) \
            == list(http.stream_variants(DEFAULT_VARIANT_SET_ID, shard))


class TestSidecarExport:
    """Binary CSR sidecar shipped with the mirror: remote cold runs skip
    the client-side parse entirely (the last wire-efficiency tier —
    at BASELINE-4 scale a 2.7 GB npz download replaces a 57.7 GB parse)."""

    REFS = "17:41196311:41277499"

    def _served_jsonl(self, tmp_path, seed=9):
        inner = synthetic_cohort(8, 60, seed=seed)
        inner.dump(str(tmp_path / "srv"))
        jsonl = JsonlSource(str(tmp_path / "srv"))
        server = GenomicsServiceServer(jsonl).start()
        return jsonl, server

    def _carrying(self, source, shards):
        from spark_examples_tpu.genomics.callsets import CallsetIndex

        indexes = CallsetIndex.from_source(
            source, [DEFAULT_VARIANT_SET_ID]
        ).indexes
        return [
            list(idx)
            for s in shards
            for idx in source.stream_carrying(
                DEFAULT_VARIANT_SET_ID, s, indexes, None
            )
        ]

    def test_mirror_ships_sidecar_and_skips_parse(
        self, tmp_path, monkeypatch
    ):
        from spark_examples_tpu.genomics import sources as S

        jsonl, server = self._served_jsonl(tmp_path)
        try:
            # Server-side sidecar built up front; afterwards ANY parse in
            # this process means the client ignored the shipped sidecar.
            assert jsonl.ensure_sidecar() is not None

            def no_parse(*a, **k):
                raise AssertionError(
                    "client parsed despite a shipped sidecar"
                )

            monkeypatch.setattr(
                S._CsrCohort, "_parse_native", staticmethod(no_parse)
            )
            monkeypatch.setattr(
                S._CsrCohort, "_parse_python", staticmethod(no_parse)
            )
            url = f"http://127.0.0.1:{server.port}"
            client = HttpVariantSource(
                url, cache_dir=str(tmp_path / "cache"), cold_stream=False
            )
            shards = shards_for_references(self.REFS, 30_000)
            got = self._carrying(client, shards)
        finally:
            server.stop()
        want = self._carrying(
            JsonlSource(str(tmp_path / "srv")), shards
        )
        assert got == want
        (mirror_root,) = [
            d
            for d in (tmp_path / "cache").iterdir()
            if d.name.startswith("cohort-")
        ]
        assert (mirror_root / S.SIDECAR_BASENAME).exists()
        assert (mirror_root / S.MIRROR_SIDECAR_OK).read_text() == (
            mirror_root / S.MIRROR_IDENTITY_FILE
        ).read_text()

    def test_tampered_sidecar_ok_falls_back_to_rebuild(self, tmp_path):
        from spark_examples_tpu.genomics import sources as S

        jsonl, server = self._served_jsonl(tmp_path)
        try:
            assert jsonl.ensure_sidecar() is not None
            url = f"http://127.0.0.1:{server.port}"
            client = HttpVariantSource(
                url, cache_dir=str(tmp_path / "cache"), cold_stream=False
            )
            shards = shards_for_references(self.REFS, 30_000)
            self._carrying(client, shards)  # populate the mirror
        finally:
            server.stop()
        (mirror_root,) = [
            d
            for d in (tmp_path / "cache").iterdir()
            if d.name.startswith("cohort-")
        ]
        # An untrusted marker must force a local rebuild — and the
        # rebuild must produce identical results.
        (mirror_root / S.MIRROR_SIDECAR_OK).write_text("tampered")
        rebuilt = JsonlSource(str(mirror_root))
        got = self._carrying(rebuilt, shards)
        want = self._carrying(
            JsonlSource(str(tmp_path / "srv")), shards
        )
        assert got == want

    def test_fixture_server_without_sidecar_still_mirrors(self, tmp_path):
        from spark_examples_tpu.genomics import sources as S

        inner = synthetic_cohort(8, 60, seed=9)
        server = GenomicsServiceServer(inner).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            client = HttpVariantSource(
                url, cache_dir=str(tmp_path / "cache"), cold_stream=False
            )
            shards = shards_for_references(self.REFS, 30_000)
            got = self._carrying(client, shards)
        finally:
            server.stop()
        assert got  # mirror works; sidecar simply absent
        (mirror_root,) = [
            d
            for d in (tmp_path / "cache").iterdir()
            if d.name.startswith("cohort-")
        ]
        assert not (mirror_root / S.MIRROR_SIDECAR_OK).exists()


class TestMirrorCache:
    def _served(self, seed=9):
        inner = synthetic_cohort(8, 60, seed=seed)
        counting = _CountingSource(inner)
        server = GenomicsServiceServer(counting).start()
        return inner, counting, server

    def test_second_run_fetches_nothing(self, tmp_path):
        inner, counting, server = self._served()
        try:
            url = f"http://127.0.0.1:{server.port}"
            shards = shards_for_references(REFS, 20_000)

            first = HttpVariantSource(
                url, cache_dir=str(tmp_path), cold_stream=False
            )
            got1 = [
                v
                for s in shards
                for v in first.stream_variants(DEFAULT_VARIANT_SET_ID, s)
            ]
            assert counting.variant_streams == 0  # mirror, not per-shard
            assert counting.exports > 0

            counting.exports = 0
            second = HttpVariantSource(
                url, cache_dir=str(tmp_path), cold_stream=False
            )
            got2 = [
                v
                for s in shards
                for v in second.stream_variants(DEFAULT_VARIANT_SET_ID, s)
            ]
            assert got1 == got2
            # THE cache property: zero data-plane traffic on a repeat run.
            assert counting.variant_streams == 0
            assert counting.exports == 0
        finally:
            server.stop()

    def test_mirror_parity_with_local_jsonl(self, tmp_path):
        inner, counting, server = self._served()
        try:
            url = f"http://127.0.0.1:{server.port}"
            shards = shards_for_references(REFS, 20_000)
            cached = HttpVariantSource(
                url, cache_dir=str(tmp_path / "cache"), cold_stream=False
            )
            inner.dump(str(tmp_path / "local"))
            local = JsonlSource(str(tmp_path / "local"))
            for shard in shards:
                assert list(
                    cached.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
                ) == list(
                    local.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
                )
        finally:
            server.stop()

    def test_changed_cohort_changes_identity(self, tmp_path):
        inner, counting, server = self._served()
        try:
            url = f"http://127.0.0.1:{server.port}"
            shard = shards_for_references(REFS, 100_000)[0]
            a = HttpVariantSource(
                url, cache_dir=str(tmp_path), cold_stream=False
            )
            n_before = len(
                list(a.stream_variants(DEFAULT_VARIANT_SET_ID, shard))
            )
        finally:
            server.stop()
        # Same URL, different cohort: the stale mirror must NOT serve.
        inner2, counting2, server2 = self._served(seed=77)
        try:
            url = f"http://127.0.0.1:{server2.port}"
            shard = shards_for_references(REFS, 100_000)[0]
            b = HttpVariantSource(
                url, cache_dir=str(tmp_path), cold_stream=False
            )
            got = list(b.stream_variants(DEFAULT_VARIANT_SET_ID, shard))
            want = list(
                inner2.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
            )
            assert got == want
            assert counting2.exports > 0  # re-mirrored, not reused
            # Stale sibling mirrors are pruned after a successful
            # download, so cache_dir cannot grow without bound.
            mirrors = [
                d
                for d in (tmp_path).iterdir()
                if d.name.startswith("cohort-")
            ]
            assert len(mirrors) == 1
        finally:
            server2.stop()

    def test_no_identity_degrades_to_direct_streaming(self, tmp_path):
        src = synthetic_cohort(4, 10, seed=1)  # no _CountingSource: the
        server = GenomicsServiceServer(src).start()  # fixture HAS identity;
        try:  # hide it with a wrapper exposing only the stream protocol
            class Opaque:
                def list_callsets(self, vsid):
                    return src.list_callsets(vsid)

                def stream_variants(self, vsid, shard):
                    return src.stream_variants(vsid, shard)

                def stream_reads(self, rgsid, shard):
                    return src.stream_reads(rgsid, shard)

            server.stop()
            server2 = GenomicsServiceServer(Opaque()).start()
            try:
                url = f"http://127.0.0.1:{server2.port}"
                http = HttpVariantSource(
                    url, cache_dir=str(tmp_path), cold_stream=False
                )
                shard = shards_for_references(REFS, 100_000)[0]
                assert (
                    len(list(http.stream_variants("", shard))) == 10
                )
            finally:
                server2.stop()
        finally:
            server.stop()


class TestPipelineOverNetwork:
    def test_pca_driver_matches_local(self, served_cohort):
        src, http = served_cohort
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            bases_per_partition=20_000,
            block_variants=32,
        )
        remote = VariantsPcaDriver(conf, http).run()
        local = VariantsPcaDriver(
            conf, synthetic_cohort(8, 60, seed=9)
        ).run()
        assert [r[0] for r in remote] == [r[0] for r in local]
        np.testing.assert_allclose(
            np.array([r[1:] for r in remote]),
            np.array([r[1:] for r in local]),
            atol=1e-6,
        )


class TestLineIndexServing:
    """Round-5 at-scale serving (verdict ask #4): the byte-offset line
    index replaces the whole-file parsed index for uncompressed cohorts —
    O(24 B/record) server memory and zero-parse raw-line serving, the
    behavior BASELINE-4 (57.7 GB) requires."""

    def _cohort_dir(self, tmp_path):
        src = synthetic_cohort(8, 60, seed=9)
        root = str(tmp_path / "c")
        src.dump(root)
        return root

    def test_windowed_stream_matches_parsed_index(self, tmp_path):
        root = self._cohort_dir(tmp_path)
        indexed = JsonlSource(root)
        assert indexed._line_index() is not None  # uncompressed → indexed
        parsed = JsonlSource(root)
        parsed._lineidx = False  # force the whole-file parsed route
        for shard in shards_for_references(REFS, 20_000):
            assert list(
                indexed.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
            ) == list(
                parsed.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
            )

    def test_line_index_persists_and_reloads(self, tmp_path):
        import os

        from spark_examples_tpu.genomics.sources import LINEIDX_BASENAME

        root = self._cohort_dir(tmp_path)
        JsonlSource(root)._line_index()
        assert os.path.exists(os.path.join(root, LINEIDX_BASENAME))
        reloaded = JsonlSource(root)._line_index()
        assert reloaded.total == 60
        # ensure_serving_index is what serve-cohort pre-warms with.
        assert JsonlSource(root).ensure_serving_index() == 60

    def test_raw_lines_parse_to_streamed_records(self, tmp_path):
        import json as json_mod

        root = self._cohort_dir(tmp_path)
        src = JsonlSource(root)
        for shard in shards_for_references(REFS, 20_000):
            raw = [
                json_mod.loads(line)
                for line in src.stream_variant_lines(
                    DEFAULT_VARIANT_SET_ID, shard
                )
            ]
            assert raw == list(src._shard_records(shard))

    def test_served_raw_passthrough_parity(self, tmp_path):
        """A jsonl-backed SERVER takes the zero-parse raw-line path; the
        HTTP client must see record-identical variants."""
        root = self._cohort_dir(tmp_path)
        server = GenomicsServiceServer(JsonlSource(root)).start()
        try:
            http = HttpVariantSource(f"http://127.0.0.1:{server.port}")
            local = JsonlSource(root)
            for shard in shards_for_references(REFS, 20_000):
                got = list(
                    http.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
                )
                want = list(
                    local.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
                )
                assert got == want
        finally:
            server.stop()

    def test_gz_cohort_still_serves_via_parsed_index(self, tmp_path):
        import gzip as gzip_mod
        import os

        root = self._cohort_dir(tmp_path)
        jsonl = os.path.join(root, "variants.jsonl")
        with open(jsonl, "rb") as f:
            data = f.read()
        with gzip_mod.open(jsonl + ".gz", "wb") as f:
            f.write(data)
        os.unlink(jsonl)
        src = JsonlSource(root)
        assert src._line_index() is None  # no byte addressing into gzip
        total = sum(
            1
            for shard in shards_for_references(REFS, 20_000)
            for _ in src.stream_variant_lines(DEFAULT_VARIANT_SET_ID, shard)
        )
        assert total == 60


class TestLightMirror:
    def test_light_mirror_serves_fused_pca_without_jsonl(self, tmp_path):
        """--mirror-mode light: only callsets + the binary sidecar come
        down (at BASELINE-4 scale, 2.7 GB instead of 57.7 GB) and the
        default fused pca path runs entirely from them."""
        import os

        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        src = synthetic_cohort(8, 60, seed=9)
        root = str(tmp_path / "srv")
        src.dump(root)
        server = GenomicsServiceServer(JsonlSource(root)).start()
        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            references=REFS,
            bases_per_partition=20_000,
            block_variants=16,
        )
        try:
            http = HttpVariantSource(
                f"http://127.0.0.1:{server.port}",
                cache_dir=str(tmp_path / "cache"),
                cold_stream=False,
                mirror_mode="light",
            )
            remote = VariantsPcaDriver(conf, http).run()
        finally:
            server.stop()
        local = VariantsPcaDriver(conf, JsonlSource(root)).run()
        np.testing.assert_allclose(
            np.array([r[1:] for r in remote]),
            np.array([r[1:] for r in local]),
            atol=1e-5,
        )
        mirrors = [
            d
            for d in os.listdir(tmp_path / "cache")
            if d.startswith("cohort-")
        ]
        assert len(mirrors) == 1
        mirror_root = tmp_path / "cache" / mirrors[0]
        assert not os.path.exists(mirror_root / "variants.jsonl")
        assert os.path.exists(mirror_root / ".variants.csr.npz")
        # Second source over the same cache: /identity resolves the
        # cache key, then every stream comes from the cached sidecar —
        # exactly one request total, no re-download.
        server2 = GenomicsServiceServer(JsonlSource(root)).start()
        try:
            http2 = HttpVariantSource(
                f"http://127.0.0.1:{server2.port}",
                cache_dir=str(tmp_path / "cache"),
                cold_stream=False,
                mirror_mode="light",
            )
            remote2 = VariantsPcaDriver(conf, http2).run()
        finally:
            server2.stop()
        assert [r[0] for r in remote2] == [r[0] for r in local]

    def test_light_mirror_requires_sidecar_export(self, tmp_path):
        """A server that cannot export a sidecar fails the light mirror
        loudly instead of leaving a husk that serves nothing."""
        src = synthetic_cohort(8, 60, seed=9)  # fixture: no sidecar file

        class NoSidecar:
            def __getattr__(self, name):
                if name in ("ensure_sidecar",):
                    raise AttributeError(name)
                return getattr(src, name)

        server = GenomicsServiceServer(NoSidecar()).start()
        try:
            http = HttpVariantSource(
                f"http://127.0.0.1:{server.port}",
                cache_dir=str(tmp_path / "cache"),
                cold_stream=False,
                mirror_mode="light",
            )
            with pytest.raises(IOError, match="light mirror"):
                http.stream_variants(
                    DEFAULT_VARIANT_SET_ID,
                    shards_for_references(REFS, 20_000)[0],
                ).__next__()
        finally:
            server.stop()


class TestLineIndexContigSpellings:
    def test_mixed_chr_spellings_land_in_one_segment(self, tmp_path):
        """'chr17' and '17' records must serve as ONE contig from the
        line index, exactly as the parsed index treats them — a spelling
        split would silently drop whichever segment lost the dict slot."""
        import json as json_mod
        import os

        root = tmp_path / "c"
        os.makedirs(root)
        recs = [
            {"reference_name": "chr17", "start": 100, "end": 101,
             "calls": []},
            {"reference_name": "17", "start": 200, "end": 201,
             "calls": []},
            {"reference_name": "chr17", "start": 300, "end": 301,
             "calls": []},
        ]
        with open(root / "variants.jsonl", "w") as f:
            for r in recs:
                f.write(json_mod.dumps(r) + "\n")
        with open(root / "callsets.json", "w") as f:
            f.write("[]")
        src = JsonlSource(str(root))
        from spark_examples_tpu.genomics.shards import Shard

        lines = list(
            src.stream_variant_lines("", Shard("17", 0, 1000))
        )
        assert len(lines) == 3
        starts = sorted(json_mod.loads(l)["start"] for l in lines)
        assert starts == [100, 200, 300]


class TestLightMirrorUpgrade:
    def test_full_mode_upgrades_existing_light_mirror(self, tmp_path):
        """A cache populated light must serve a later --mirror-mode full
        consumer by fetching the missing interchange files in place —
        not crash it on cache internals."""
        import os

        src = synthetic_cohort(8, 60, seed=9)
        root = str(tmp_path / "srv")
        src.dump(root)
        server = GenomicsServiceServer(JsonlSource(root)).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            cache = str(tmp_path / "cache")
            light = HttpVariantSource(
                url, cache_dir=cache, mirror_mode="light", cold_stream=False
            )
            shard = shards_for_references(REFS, 20_000)[0]
            indexes = {
                c.id: i
                for i, c in enumerate(
                    light.list_callsets(DEFAULT_VARIANT_SET_ID)
                )
            }
            # Populate the light mirror (fused tier touch).
            list(
                light.stream_carrying(
                    DEFAULT_VARIANT_SET_ID, shard, indexes, None
                )
            )
            mirror_root = [
                d
                for d in os.listdir(cache)
                if d.startswith("cohort-")
            ][0]
            assert not os.path.exists(
                os.path.join(cache, mirror_root, "variants.jsonl")
            )
            # Full-mode consumer over the same cache: upgrade + records.
            full = HttpVariantSource(
                url, cache_dir=cache, mirror_mode="full", cold_stream=False
            )
            got = list(
                full.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
            )
            want = list(
                JsonlSource(root).stream_variants(
                    DEFAULT_VARIANT_SET_ID, shard
                )
            )
            assert got == want
            assert os.path.exists(
                os.path.join(cache, mirror_root, "variants.jsonl")
            )
        finally:
            server.stop()

    def test_light_mirror_record_streaming_error_is_actionable(
        self, tmp_path
    ):
        """Without the upgrade (light mode again), record streaming off
        a light mirror explains itself instead of raising a raw
        cache-internal FileNotFoundError."""
        import os

        src = synthetic_cohort(8, 60, seed=9)
        root = str(tmp_path / "srv")
        src.dump(root)
        server = GenomicsServiceServer(JsonlSource(root)).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            cache = str(tmp_path / "cache")
            light = HttpVariantSource(
                url, cache_dir=cache, mirror_mode="light", cold_stream=False
            )
            shard = shards_for_references(REFS, 20_000)[0]
            indexes = {
                c.id: i
                for i, c in enumerate(
                    light.list_callsets(DEFAULT_VARIANT_SET_ID)
                )
            }
            list(
                light.stream_carrying(
                    DEFAULT_VARIANT_SET_ID, shard, indexes, None
                )
            )
            light2 = HttpVariantSource(
                url, cache_dir=cache, mirror_mode="light", cold_stream=False
            )
            with pytest.raises(FileNotFoundError, match="LIGHT"):
                list(
                    light2.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
                )
        finally:
            server.stop()
