"""Elastic checkpointing: Spark-task-analog units, any-world-size resume.

The reference gets executor-loss recovery free from Spark task
re-execution (SURVEY.md §2.10 elasticity row); the non-elastic checkpoint
modes here pin snapshots to the process grid, so a shrunken cluster could
not resume them. These tests pin the elastic contract:

- lane snapshots are atomic, self-describing, and de-overlap
  deterministically after any crash window of the merge protocol;
- a single-process elastic run matches the plain pipeline bit-for-bit and
  resume never re-ingests covered units;
- THE DRILL: a two-process run where one worker dies permanently
  mid-ingest fail-stops (never hangs), and a relaunch with ONE process
  claims both processes' lanes, re-executes only the dead worker's
  remaining units, and matches the uninterrupted single-process result
  bit-for-bit — the dead host's manifest share is re-sliced onto the
  survivor, which Spark calls task re-execution.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.genomics.shards import shards_for_references
from spark_examples_tpu.models.pca import VariantsPcaDriver
from spark_examples_tpu.utils import elastic
from spark_examples_tpu.utils.config import PcaConfig


class TestUnitRanges:
    def test_exact_division(self):
        assert elastic.unit_ranges(6, 2) == [(0, 2), (2, 4), (4, 6)]

    def test_ragged_tail(self):
        assert elastic.unit_ranges(5, 2) == [(0, 2), (2, 4), (4, 5)]

    def test_every_clamped_to_one(self):
        assert elastic.unit_ranges(2, 0) == [(0, 1), (1, 2)]

    def test_empty_manifest(self):
        assert elastic.unit_ranges(0, 4) == []


class TestLanes:
    def test_roundtrip(self, tmp_path):
        g = np.arange(9.0, dtype=np.float32).reshape(3, 3)
        elastic.save_lane(str(tmp_path), g, [2, 0], "d1")
        lanes = elastic.load_lanes(str(tmp_path), "d1", 3)
        assert len(lanes) == 1
        assert lanes[0].units == frozenset({0, 2})
        np.testing.assert_array_equal(lanes[0].load_g(), g)

    def test_digest_and_shape_mismatch_ignored(self, tmp_path):
        elastic.save_lane(str(tmp_path), np.zeros((3, 3)), [0], "d1")
        assert elastic.load_lanes(str(tmp_path), "other", 3) == []
        assert elastic.load_lanes(str(tmp_path), "d1", 4) == []

    def test_absent_dir(self, tmp_path):
        assert elastic.load_lanes(str(tmp_path / "nope"), "d", 3) == []

    def test_subset_discarded(self, tmp_path):
        """The merge-protocol crash residue: superset lane + stale subsets
        → only the superset survives, each unit counted once."""
        g1 = np.ones((2, 2), np.float32)
        elastic.save_lane(str(tmp_path), g1, [0], "d")
        elastic.save_lane(str(tmp_path), g1, [1], "d")
        elastic.save_lane(str(tmp_path), 3 * g1, [0, 1], "d")  # merged
        lanes = elastic.load_lanes(str(tmp_path), "d", 2)
        assert len(lanes) == 1
        assert lanes[0].units == frozenset({0, 1})
        np.testing.assert_array_equal(lanes[0].load_g(), 3 * g1)

    def test_partial_overlap_discarded_with_warning(self, tmp_path, capsys):
        g = np.ones((2, 2), np.float32)
        elastic.save_lane(str(tmp_path), g, [0, 1], "d")
        elastic.save_lane(str(tmp_path), g, [1, 2], "d")  # cannot arise
        lanes = elastic.load_lanes(str(tmp_path), "d", 2)
        assert len(lanes) == 1
        assert "partially overlaps" in capsys.readouterr().err

    def test_unreadable_lane_ignored(self, tmp_path, capsys):
        (tmp_path / "lane-deadbeef.npz").write_bytes(b"not a zip")
        elastic.save_lane(str(tmp_path), np.zeros((2, 2)), [0], "d")
        lanes = elastic.load_lanes(str(tmp_path), "d", 2)
        assert len(lanes) == 1
        assert "unreadable" in capsys.readouterr().err

    def test_merge_supersede_deletes_old(self, tmp_path):
        g = np.ones((2, 2), np.float32)
        p1 = elastic.save_lane(str(tmp_path), g, [0], "d")
        p2 = elastic.save_lane(str(tmp_path), g, [1], "d")
        merged = elastic.merge_and_supersede(
            str(tmp_path), 2 * g, [0, 1], "d", [p1, p2]
        )
        assert os.path.exists(merged)
        assert not os.path.exists(p1) and not os.path.exists(p2)
        lanes = elastic.load_lanes(str(tmp_path), "d", 2)
        assert len(lanes) == 1 and lanes[0].units == frozenset({0, 1})

    def test_prune_stale_lanes(self, tmp_path):
        g = np.ones((2, 2), np.float32)
        old = elastic.save_lane(str(tmp_path), g, [0], "old-digest")
        sub = elastic.save_lane(str(tmp_path), g, [1], "d")
        live = elastic.save_lane(str(tmp_path), 2 * g, [1, 2], "d")
        bad = tmp_path / "lane-ffff.npz"
        bad.write_bytes(b"garbage")
        kept = elastic.load_lanes(str(tmp_path), "d", 2)
        removed = elastic.prune_stale_lanes(str(tmp_path), "d", kept)
        assert removed == 2  # stale digest + superseded subset
        assert not os.path.exists(old) and not os.path.exists(sub)
        assert os.path.exists(live)
        assert bad.exists()  # unreadable files stay as evidence

    def test_prune_tmp_orphans_age_gated(self, tmp_path):
        """A save killed mid-write leaves a .npz.tmp orphan; prune removes
        it once it is clearly not an in-flight peer write."""
        stale = tmp_path / "tmpabc123.npz.tmp"
        stale.write_bytes(b"half-written")
        os.utime(stale, (1, 1))  # ancient
        fresh = tmp_path / "tmpdef456.npz.tmp"
        fresh.write_bytes(b"in flight")
        removed = elastic.prune_stale_lanes(str(tmp_path), "d", [])
        assert removed == 1
        assert not stale.exists()
        assert fresh.exists()  # could be a live peer's write — kept

    def test_corrupt_payload_detected_lazily(self, tmp_path):
        """Metadata reads fine, the compressed g member is corrupt: the
        lane lists normally (lazy load) and only load_g raises."""
        g = (
            np.random.default_rng(0)
            .random((64, 64))
            .astype(np.float32)
        )
        path = elastic.save_lane(str(tmp_path), g, [0], "d")
        data = bytearray(open(path, "rb").read())
        i = data.find(b"g.npy")
        assert i > 0
        for off in range(i + 60, i + 90):
            data[off] ^= 0xFF
        open(path, "wb").write(bytes(data))
        lanes = elastic.load_lanes(str(tmp_path), "d", 64)
        assert len(lanes) == 1  # metadata members intact
        with pytest.raises(Exception):
            lanes[0].load_g()

    def test_lane_without_g_shape_still_loads(self, tmp_path):
        """Back-compat: lanes written before the g_shape member existed
        must keep resuming (payload decompressed once as fallback)."""
        import tempfile

        g = np.ones((3, 3), np.float32)
        fd, tmp = tempfile.mkstemp(dir=str(tmp_path), suffix=".npz.tmp")
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(
                f,
                g=g,
                units=np.asarray([1], np.int64),
                run_digest=np.bytes_(b"d"),
            )
        os.replace(tmp, str(tmp_path / "lane-oldformat.npz"))
        lanes = elastic.load_lanes(str(tmp_path), "d", 3)
        assert len(lanes) == 1 and lanes[0].units == frozenset({1})
        np.testing.assert_array_equal(lanes[0].load_g(), g)

    def test_fingerprint_order_independent(self, tmp_path):
        g = np.zeros((2, 2))
        elastic.save_lane(str(tmp_path), g, [0], "d")
        elastic.save_lane(str(tmp_path), g, [1], "d")
        lanes = elastic.load_lanes(str(tmp_path), "d", 2)
        assert elastic.lane_view_fingerprint(
            lanes
        ) == elastic.lane_view_fingerprint(list(reversed(lanes)))


class TestLaneProtocolProperty:
    """Hypothesis torture of the lane crash-safety invariant: under ANY
    interleaving of per-process unit completions, merges, and crashes
    (a crash = the supersede deletions are arbitrarily partially
    applied), the surviving lanes are pairwise disjoint, every lane's
    payload matches its declared unit set exactly, and completing the
    uncovered units always reconstructs the full-work Gramian — no unit
    lost, none double-counted."""

    def test_random_crash_interleavings(self, tmp_path):
        hypothesis = pytest.importorskip("hypothesis")
        from hypothesis import given, settings, strategies as st

        n_units = 6

        def unit_vec(u):
            g = np.zeros((n_units, 1), np.float32)
            g[u, 0] = 1.0
            return g

        # A scenario: per process, an assignment of units and a crash
        # point (how many of its units it completed, and whether its
        # final merge's deletions were applied fully/partially/not).
        proc = st.tuples(
            st.integers(0, n_units),  # units completed by this process
            st.integers(0, 2),  # 0=deletions done, 1=partial, 2=none
        )
        scenarios = st.lists(proc, min_size=1, max_size=3)

        @settings(max_examples=40, deadline=None)
        @given(scenarios=scenarios, data=st.data())
        def run(scenarios, data):
            import shutil
            import tempfile

            d = tempfile.mkdtemp(dir=str(tmp_path))
            try:
                # Deal units round-robin to processes.
                world = len(scenarios)
                for p, (completed, crash_mode) in enumerate(scenarios):
                    mine = list(range(n_units))[p::world]
                    covered = []
                    g = np.zeros((n_units, 1), np.float32)
                    own = []
                    for u in mine[: min(completed, len(mine))]:
                        covered.append(u)
                        g = g + unit_vec(u)
                        new = elastic.save_lane(d, g, covered, "dig")
                        # Crash-window modeling: deletions of superseded
                        # lanes applied fully, partially, or not at all.
                        if crash_mode == 0:
                            for old in own:
                                os.remove(old)
                            own = [new]
                        elif crash_mode == 1 and own:
                            keep = data.draw(
                                st.integers(0, len(own) - 1)
                            )
                            for i, old in enumerate(own):
                                if i != keep:
                                    os.remove(old)
                            own = [own[keep], new]
                        else:
                            own = own + [new]

                lanes = elastic.load_lanes(d, "dig", n_units)
                seen = set()
                total = np.zeros((n_units, 1), np.float32)
                for lane in lanes:
                    assert lane.units.isdisjoint(seen)  # never double
                    seen |= lane.units
                    payload = lane.load_g()
                    # Payload must be EXACTLY the sum of its declared
                    # units' contributions.
                    want = np.zeros((n_units, 1), np.float32)
                    for u in lane.units:
                        want += unit_vec(u)
                    np.testing.assert_array_equal(payload, want)
                    total += payload
                # Completing the uncovered units reconstructs all work.
                for u in range(n_units):
                    if u not in seen:
                        total += unit_vec(u)
                np.testing.assert_array_equal(
                    total, np.ones((n_units, 1), np.float32)
                )
            finally:
                shutil.rmtree(d, ignore_errors=True)

        run()


def _conf(tmp_path, **kw):
    base = dict(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,  # BRCA1 region → 5 shards
        block_variants=64,
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=2,  # → 3 units: [0,2) [2,4) [4,5)
        elastic_checkpoint=True,
    )
    base.update(kw)
    return PcaConfig(**base)


def _plain_gramian(n=12, v=100):
    driver = VariantsPcaDriver(
        PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            bases_per_partition=20_000,
            block_variants=64,
        ),
        synthetic_cohort(n, v),
    )
    data = driver.get_data()
    calls = driver.get_calls([driver.filter_dataset(d) for d in data])
    return np.asarray(driver.get_similarity_matrix(calls))


class TestElasticValidation:
    def test_requires_checkpoint_dir(self):
        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            elastic_checkpoint=True,
        )
        with pytest.raises(ValueError, match="--checkpoint-dir"):
            VariantsPcaDriver(conf, synthetic_cohort(4, 10))

    def test_multi_dataset_needs_keyed_source(self, tmp_path):
        """Multi-dataset elastic requires the fused keyed ingest; a
        source without stream_carrying_keyed errors before any work."""

        class Bare:
            def __init__(self, inner):
                self._inner = inner
                self.stats = inner.stats

            def list_callsets(self, vsid):
                return self._inner.list_callsets(vsid)

            def stream_variants(self, vsid, shard):
                return self._inner.stream_variants(vsid, shard)

        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID, "other"],
            checkpoint_dir=str(tmp_path),
            elastic_checkpoint=True,
        )
        driver = VariantsPcaDriver(
            conf, Bare(synthetic_cohort(4, 10))
        )
        with pytest.raises(ValueError, match="stream_carrying_keyed"):
            driver.get_similarity_matrix_checkpointed()


class TestElasticPipeline:
    def test_matches_plain(self, tmp_path):
        driver = VariantsPcaDriver(_conf(tmp_path), synthetic_cohort(12, 100))
        g = np.asarray(driver.get_similarity_matrix_checkpointed())
        np.testing.assert_array_equal(g, _plain_gramian())

    def test_resume_skips_covered_units(self, tmp_path):
        conf = _conf(tmp_path)
        g1 = np.asarray(
            VariantsPcaDriver(
                conf, synthetic_cohort(12, 100)
            ).get_similarity_matrix_checkpointed()
        )
        src2 = synthetic_cohort(12, 100)
        g2 = np.asarray(
            VariantsPcaDriver(
                conf, src2
            ).get_similarity_matrix_checkpointed()
        )
        assert src2.stats.partitions == 0  # nothing re-streamed
        np.testing.assert_array_equal(g1, g2)

    def test_resume_after_failure_matches_plain(self, tmp_path):
        conf = _conf(tmp_path)
        shards = shards_for_references(conf.references, 20_000)
        src = synthetic_cohort(12, 100)
        src._fail_once.add(shards[3])  # inside unit 1 ([2,4))
        with pytest.raises(IOError):
            VariantsPcaDriver(
                conf, src
            ).get_similarity_matrix_checkpointed()
        # Unit 0 completed and is on disk as a lane.
        lanes = os.listdir(os.path.join(conf.checkpoint_dir, "elastic"))
        assert len(lanes) == 1

        src2 = synthetic_cohort(12, 100)
        g = np.asarray(
            VariantsPcaDriver(
                conf, src2
            ).get_similarity_matrix_checkpointed()
        )
        # Units 1 and 2 re-ingested (3 shards), unit 0's 2 shards skipped.
        assert src2.stats.partitions == 3
        np.testing.assert_array_equal(g, _plain_gramian())

    def test_changed_round_width_invalidates(self, tmp_path):
        """Unit boundaries depend on checkpoint_every; the digest pins it
        so lanes from a different width are never mixed in."""
        conf = _conf(tmp_path)
        VariantsPcaDriver(
            conf, synthetic_cohort(12, 100)
        ).get_similarity_matrix_checkpointed()
        conf2 = _conf(tmp_path, checkpoint_every=3)
        src = synthetic_cohort(12, 100)
        g = np.asarray(
            VariantsPcaDriver(
                conf2, src
            ).get_similarity_matrix_checkpointed()
        )
        assert src.stats.partitions == 5  # full re-ingest, no stale reuse
        np.testing.assert_array_equal(g, _plain_gramian())
        # The old width's lanes were pruned — only the new run's remain.
        lane_files = [
            f
            for f in os.listdir(
                os.path.join(conf2.checkpoint_dir, "elastic")
            )
            if f.startswith("lane-")
        ]
        assert len(lane_files) == 1

    def test_corrupt_claimed_lane_reexecuted(
        self, tmp_path, monkeypatch, capsys
    ):
        """A claimed lane whose payload fails to decompress is warned
        about and its units re-executed — resume never dies on it."""
        from zipfile import BadZipFile

        conf = _conf(tmp_path)
        VariantsPcaDriver(
            conf, synthetic_cohort(12, 100)
        ).get_similarity_matrix_checkpointed()

        def boom(self):
            raise BadZipFile("Bad CRC-32 for file 'g.npy'")

        monkeypatch.setattr(elastic.Lane, "load_g", boom)
        src = synthetic_cohort(12, 100)
        g = np.asarray(
            VariantsPcaDriver(
                conf, src
            ).get_similarity_matrix_checkpointed()
        )
        monkeypatch.undo()
        assert src.stats.partitions == 5  # every unit re-ingested
        np.testing.assert_array_equal(g, _plain_gramian())
        assert "unreadable" in capsys.readouterr().err

    def test_full_driver_run_elastic(self, tmp_path):
        result = VariantsPcaDriver(
            _conf(tmp_path), synthetic_cohort(15, 120)
        ).run()
        plain = VariantsPcaDriver(
            PcaConfig(
                variant_set_ids=[DEFAULT_VARIANT_SET_ID], block_variants=64
            ),
            synthetic_cohort(15, 120),
        ).run()
        np.testing.assert_allclose(
            np.array([r[1:] for r in result]),
            np.array([r[1:] for r in plain]),
            atol=1e-4,
        )


# ---------------------------------------------------------------------------
# THE DRILL: two processes, one dies permanently, one-process resume.
# ---------------------------------------------------------------------------

pytestmark_multihost = pytest.mark.skipif(
    os.environ.get("SPARK_EXAMPLES_TPU_SKIP_MULTIHOST") == "1",
    reason="multihost tests disabled",
)

_SHRINK_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.genomics.shards import shards_for_references
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    pid = jax.process_index()
    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        checkpoint_dir=sys.argv[1],
        checkpoint_every=1,  # 5 shards -> 5 units
        elastic_checkpoint=True,
        collective_timeout=8.0,
    )
    source = synthetic_cohort(10, 80, seed=5)
    if pid == 1:
        # Permanent death mid-ingest: process 1's units are 1 and 3; it
        # finishes unit 1 (lane on disk), then dies at unit 3's shard.
        shards = shards_for_references(conf.references, 20_000)
        orig = source._shard_items
        def dying(shard):
            if shard == shards[3]:
                os._exit(13)
            return orig(shard)
        source._shard_items = dying
    driver = VariantsPcaDriver(conf, source)
    driver.get_similarity_matrix_checkpointed()
    os._exit(0)  # unreachable: pid 1 dies; pid 0 fail-stops in allreduce
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytestmark_multihost
def test_elastic_shrink_world_resume(tmp_path):
    """A worker dies for good mid-run; the survivor fail-stops rather than
    hanging; relaunching with HALF the world size resumes from both
    processes' lanes and re-executes only the dead worker's remaining
    unit. Final Gramian is bit-equal to the uninterrupted pipeline."""
    script = tmp_path / "worker.py"
    script.write_text(_SHRINK_WORKER)
    ck_dir = tmp_path / "ck"

    port = _free_port()
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ),
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(ck_dir)],
            env={**env, "JAX_PROCESS_ID": str(i)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(2)
    ]
    try:
        logs = [p.communicate(timeout=240)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    # Process 1 died on purpose; process 0 must NOT hang or succeed — the
    # collective watchdog (exit 77) or the coordination-service heartbeat
    # terminates it, whichever fires first.
    assert procs[1].returncode == 13, logs[1][-1500:]
    assert procs[0].returncode not in (0, None), logs[0][-1500:]

    # Lanes on disk: process 0 covered units {0,2,4}, process 1 covered
    # {1} before dying — unit 3 is the only one left.
    lanes = elastic.load_lanes(
        str(ck_dir / "elastic"), _drill_digest(), 10
    )
    covered = set()
    for lane in lanes:
        covered |= lane.units
    assert covered == {0, 1, 2, 4}

    # Resume at world size ONE: claims all lanes, ingests only unit 3.
    src = synthetic_cohort(10, 80, seed=5)
    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        checkpoint_dir=str(ck_dir),
        checkpoint_every=1,
        elastic_checkpoint=True,
    )
    g = np.asarray(
        VariantsPcaDriver(conf, src).get_similarity_matrix_checkpointed()
    )
    assert src.stats.partitions == 1  # exactly the dead worker's unit

    plain = VariantsPcaDriver(
        PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            bases_per_partition=20_000,
            block_variants=32,
        ),
        synthetic_cohort(10, 80, seed=5),
    )
    data = plain.get_data()
    calls = plain.get_calls([plain.filter_dataset(d) for d in data])
    g_plain = np.asarray(plain.get_similarity_matrix(calls))
    np.testing.assert_array_equal(g, g_plain)


class TestContigAlignedUnits:
    class _S:
        def __init__(self, contig):
            self.contig = contig

    def _shards(self, *contigs):
        return [self._S(c) for c in contigs]

    def test_packs_runs_up_to_every(self):
        s = self._shards("1", "1", "2", "2", "3")
        assert elastic.unit_ranges_contig_aligned(s, 4) == [(0, 4), (4, 5)]

    def test_never_splits_a_run(self):
        s = self._shards("1", "1", "1", "2")
        # Contig 1's run (3 shards) exceeds every=2: one oversized unit.
        assert elastic.unit_ranges_contig_aligned(s, 2) == [(0, 3), (3, 4)]

    def test_single_contig_single_unit(self):
        s = self._shards("17", "17", "17", "17", "17")
        assert elastic.unit_ranges_contig_aligned(s, 2) == [(0, 5)]

    def test_empty(self):
        assert elastic.unit_ranges_contig_aligned([], 2) == []


class TestElasticMultiDataset:
    """Elastic checkpointing of multi-dataset JOINS via contig-aligned
    units — the reference's only join resume was the all-or-nothing
    objectFile (VariantsCommon.scala:52-55)."""

    REFS = "17:41196311:41236311,20:100000:140000"  # 2 contigs, 4 shards

    def _merged(self):
        from spark_examples_tpu.genomics.sources import FixtureSource

        a = synthetic_cohort(
            8, 60, references=self.REFS, variant_set_id="setA", seed=1
        )
        b = synthetic_cohort(
            8, 60, references=self.REFS, variant_set_id="setB", seed=1
        )
        return FixtureSource(
            variants=a._variants + b._variants,
            callsets=a._callsets + b._callsets,
        )

    def _conf(self, tmp_path, **kw):
        kw.setdefault("references", self.REFS)
        kw.setdefault("variant_set_ids", ["setA", "setB"])
        return _conf(tmp_path, **kw)

    def _plain_join_gramian(self):
        driver = VariantsPcaDriver(
            PcaConfig(
                variant_set_ids=["setA", "setB"],
                references=self.REFS,
                bases_per_partition=20_000,
                block_variants=64,
            ),
            self._merged(),
        )
        return np.asarray(
            driver.get_similarity_matrix(driver.get_calls_fused_multi())
        )

    def test_matches_plain_join(self, tmp_path):
        conf = self._conf(tmp_path)
        g = np.asarray(
            VariantsPcaDriver(
                conf, self._merged()
            ).get_similarity_matrix_checkpointed()
        )
        np.testing.assert_array_equal(g, self._plain_join_gramian())

    def test_crash_and_resume_bit_equal(self, tmp_path):
        conf = self._conf(tmp_path)
        shards = conf.shards()
        assert len(shards) == 4  # 2 runs of 2 → units (0,2) and (2,4)
        src = self._merged()
        src._fail_once.add(shards[2])  # first shard of unit 1
        with pytest.raises(IOError):
            VariantsPcaDriver(
                conf, src
            ).get_similarity_matrix_checkpointed()

        src2 = self._merged()
        g = np.asarray(
            VariantsPcaDriver(
                conf, src2
            ).get_similarity_matrix_checkpointed()
        )
        # Unit 0 (contig 17) was banked; only contig 20's unit re-runs —
        # 2 shards × 2 dataset streams.
        assert src2.stats.partitions == 4
        np.testing.assert_array_equal(g, self._plain_join_gramian())

    def test_resume_skips_everything_when_done(self, tmp_path):
        conf = self._conf(tmp_path)
        VariantsPcaDriver(
            conf, self._merged()
        ).get_similarity_matrix_checkpointed()
        src = self._merged()
        VariantsPcaDriver(conf, src).get_similarity_matrix_checkpointed()
        assert src.stats.partitions == 0

    def test_nonunique_contig_runs_rejected(self, tmp_path):
        conf = self._conf(
            tmp_path,
            references="17:41196311:41216311,20:100000:120000,"
            "17:41216311:41236311",  # contig 17 appears as two runs
        )
        with pytest.raises(ValueError, match="contiguous manifest run"):
            VariantsPcaDriver(
                conf, self._merged()
            ).get_similarity_matrix_checkpointed()

    def test_full_driver_run(self, tmp_path):
        result = VariantsPcaDriver(
            self._conf(tmp_path), self._merged()
        ).run()
        assert len(result) == 16
        assert {r[0].split("-")[0] for r in result} == {"setA", "setB"}


class TestElasticOverNetwork:
    def test_server_outage_then_resume(self, tmp_path):
        """Cross-feature drill: elastic checkpointing over NETWORK ingest
        (the reference's executors-stream-from-API shape). The serving
        process dies mid-run; completed units are on disk as lanes; a
        fresh server + fresh client resume and fetch ONLY the remaining
        units, matching the local pipeline bit-for-bit."""
        from spark_examples_tpu.genomics.service import (
            GenomicsServiceServer,
            HttpVariantSource,
        )

        cohort = synthetic_cohort(12, 100)
        server = GenomicsServiceServer(cohort).start()
        url = f"http://127.0.0.1:{server.port}"

        class DiesBeforeShard(HttpVariantSource):
            """Client whose server vanishes before the k-th shard.

            The outage is injected on BOTH fused tiers: the driver picks
            stream_carrying_csr when a source offers it (round 5 added
            it to HttpVariantSource), stream_carrying otherwise.
            """

            def __init__(self, url, die_at):
                super().__init__(url)
                self._die_at = die_at
                self._seen = 0

            def _tick(self):
                self._seen += 1
                if self._seen == self._die_at:
                    server.stop()  # outage mid-run

            def stream_carrying(self, vsid, shard, indexes, min_af):
                self._tick()
                yield from super().stream_carrying(
                    vsid, shard, indexes, min_af
                )

            def stream_carrying_csr(self, vsid, shard, indexes, min_af):
                self._tick()
                return super().stream_carrying_csr(
                    vsid, shard, indexes, min_af
                )

        conf = _conf(tmp_path, checkpoint_every=1, ingest_workers=1)
        dying = DiesBeforeShard(url, die_at=4)
        with pytest.raises(IOError):
            VariantsPcaDriver(
                conf, dying
            ).get_similarity_matrix_checkpointed()
        lanes = os.listdir(os.path.join(conf.checkpoint_dir, "elastic"))
        assert len(lanes) >= 1  # units before the outage are banked

        # Fresh server over the same cohort; fresh client; resume.
        server2 = GenomicsServiceServer(cohort).start()
        try:
            http = HttpVariantSource(f"http://127.0.0.1:{server2.port}")
            g = np.asarray(
                VariantsPcaDriver(
                    conf, http
                ).get_similarity_matrix_checkpointed()
            )
            assert http.stats.partitions == 2  # only uncovered units
        finally:
            server2.stop()
        np.testing.assert_array_equal(g, _plain_gramian())


class TestElasticCrashPointSweep:
    @pytest.mark.parametrize("fail_shard", [0, 1, 2, 3, 4])
    def test_resume_bit_equal_from_any_crash_point(
        self, tmp_path, fail_shard
    ):
        """Property drill: whatever shard the crash lands on, resume
        completes and the Gramian is bit-equal to the plain pipeline."""
        conf = _conf(tmp_path, checkpoint_every=1)
        shards = shards_for_references(conf.references, 20_000)
        src = synthetic_cohort(12, 100)
        src._fail_once.add(shards[fail_shard])
        with pytest.raises(IOError):
            VariantsPcaDriver(
                conf, src
            ).get_similarity_matrix_checkpointed()
        src2 = synthetic_cohort(12, 100)
        g = np.asarray(
            VariantsPcaDriver(
                conf, src2
            ).get_similarity_matrix_checkpointed()
        )
        # Exactly the shards at/after the crash point re-ingest.
        assert src2.stats.partitions == len(shards) - fail_shard
        np.testing.assert_array_equal(g, _plain_gramian())


_UNSHARED_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    # Each process gets its OWN checkpoint dir — the misconfiguration the
    # write-probe must catch BEFORE any ingest happens.
    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        checkpoint_dir=sys.argv[1] + f"-{jax.process_index()}",
        checkpoint_every=1,
        elastic_checkpoint=True,
        collective_timeout=30.0,
    )
    source = synthetic_cohort(10, 80, seed=5)
    try:
        VariantsPcaDriver(conf, source).get_similarity_matrix_checkpointed()
    except RuntimeError as e:
        assert "probe" in str(e), e
        assert source.stats.partitions == 0  # caught before any ingest
        os._exit(21)
    os._exit(0)
    """
)


@pytestmark_multihost
def test_elastic_unshared_dir_detected_before_work(tmp_path):
    """A checkpoint dir that is not actually shared must be detected by
    the write-probe BEFORE any ingest — not after a crash, when each
    host's lanes would already be stranded on local disks."""
    script = tmp_path / "worker.py"
    script.write_text(_UNSHARED_WORKER)

    port = _free_port()
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ),
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(tmp_path / "ck")],
            env={**env, "JAX_PROCESS_ID": str(i)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(2)
    ]
    try:
        logs = [p.communicate(timeout=240)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert [p.returncode for p in procs] == [21, 21], (
        logs[0][-1500:],
        logs[1][-1500:],
    )


def _drill_digest() -> str:
    from spark_examples_tpu.genomics.shards import manifest_digest

    shards = shards_for_references("17:41196311:41277499", 20_000)
    return (
        f"{manifest_digest(shards)}|{DEFAULT_VARIANT_SET_ID}"
        f"|af=None|every=1|elastic"
    )
