"""Property-based tests (hypothesis) for the semantic-critical transforms.

SURVEY.md §4 calls for property/golden tests of every pure transform;
these cover the invariants that example-based tests can miss.
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from spark_examples_tpu.genomics.hashing import _murmur3_py, murmur3_x64_128
from spark_examples_tpu.genomics.shards import shards_for_references
from spark_examples_tpu.genomics.types import normalize_contig
from spark_examples_tpu.ops import double_center, gramian


class TestMurmurProperties:
    @given(st.binary(max_size=200))
    @settings(max_examples=200, deadline=None)
    def test_native_matches_python_reference(self, data):
        from spark_examples_tpu.native import load

        if load() is None:
            pytest.skip("native library unavailable — parity not testable")
        assert murmur3_x64_128(data) == _murmur3_py(data)

    @given(st.binary(min_size=1, max_size=64), st.integers(0, 511))
    @settings(max_examples=100, deadline=None)
    def test_bit_flip_changes_digest(self, data, bit):
        bit = bit % (len(data) * 8)
        flipped = bytearray(data)
        flipped[bit // 8] ^= 1 << (bit % 8)
        assert murmur3_x64_128(data) != murmur3_x64_128(bytes(flipped))


class TestContigProperties:
    @given(st.text(alphabet="abcdefghijklmnopqrstuvwxyz", max_size=5),
           st.integers(0, 99))
    @settings(max_examples=100, deadline=None)
    def test_lower_prefix_plus_digits_keeps_digits(self, prefix, num):
        # Any [a-z]* prefix followed by digits normalizes to the digits —
        # the full generality of the reference regex, not just "chr".
        assert normalize_contig(f"{prefix}{num}") == str(num)

    @given(st.text(min_size=1, max_size=10))
    @settings(max_examples=200, deadline=None)
    def test_never_crashes_and_drops_or_keeps(self, name):
        out = normalize_contig(name)
        if out is not None:
            assert out == "" or out.isdigit()


class TestShardProperties:
    @given(
        st.integers(0, 10_000_000),
        st.integers(1, 5_000_000),
        st.integers(1, 1000),
    )
    @settings(max_examples=100, deadline=None)
    def test_windows_partition_the_range_exactly(self, start, length, n_shards):
        end = start + length
        bps = max(1, -(-length // n_shards))  # cap shard count at ~1000
        shards = shards_for_references(f"7:{start}:{end}", bps)
        assert shards[0].start == start and shards[-1].end == end
        for a, b in zip(shards, shards[1:]):
            assert a.end == b.start  # adjacent, no gaps/overlap
        assert sum(s.range for s in shards) == length
        # STRICT: every position belongs to exactly one shard, found by
        # index arithmetic (no O(n_shards) scan).
        for pos in {start, end - 1, start + length // 2}:
            k = (pos - start) // bps
            assert shards[k].start <= pos < shards[k].end
            if k + 1 < len(shards):
                assert not (shards[k + 1].start <= pos < shards[k + 1].end)


class TestGramianProperties:
    @given(st.integers(1, 12), st.integers(1, 40), st.integers(0, 2**31 - 1))
    @settings(max_examples=15, deadline=None)  # each new shape recompiles
    def test_gramian_symmetric_psd_diag_dominant(self, n, v, seed):
        rng = np.random.default_rng(seed)
        x = (rng.random((n, v)) < 0.4).astype(np.int8)
        g = np.asarray(gramian(x))
        assert np.array_equal(g, g.T)
        # diagonal = per-sample variant counts; off-diag ≤ min(diag_i, diag_j)
        d = np.diag(g)
        assert (g <= np.minimum.outer(d, d) + 1e-6).all()
        w = np.linalg.eigvalsh(g.astype(np.float64))
        assert w.min() >= -1e-6  # PSD

    @given(st.integers(2, 10), st.integers(0, 2**31 - 1))
    @settings(max_examples=15, deadline=None)  # each new shape recompiles
    def test_double_center_idempotent_and_zero_mean(self, n, seed):
        rng = np.random.default_rng(seed)
        g = rng.random((n, n))
        g = g + g.T
        c1 = np.asarray(double_center(g))
        c2 = np.asarray(double_center(c1))
        np.testing.assert_allclose(c1, c2, atol=1e-4)  # idempotent
        np.testing.assert_allclose(c1.mean(0), 0, atol=1e-5)


class TestCsrBlockEquivalence:
    """blocks_from_csr ≡ blocks_from_calls over ARBITRARY ragged shard
    streams — beyond the cohort-shaped parity test: empty shards
    (None), empty windows, variants spilling across block boundaries,
    widths far from multiples of 8."""

    @given(
        st.lists(  # per-shard: list of per-variant carrier lists
            st.one_of(
                st.none(),
                st.lists(
                    st.lists(
                        st.integers(0, 10), min_size=1, max_size=6,
                        unique=True,
                    ),
                    max_size=9,
                ),
            ),
            max_size=6,
        ),
        st.integers(1, 7),  # block width
    )
    @settings(max_examples=60, deadline=None)
    def test_csr_blocks_bit_identical(self, shards, width):
        import numpy as np

        from spark_examples_tpu.arrays.blocks import (
            blocks_from_calls,
            blocks_from_csr,
        )

        n = 11

        def pairs():
            for sh in shards:
                if sh is None:
                    yield None
                    continue
                nonempty = [c for c in sh if c]
                if not nonempty:
                    yield None
                    continue
                offs = np.zeros(len(nonempty) + 1, dtype=np.int64)
                for i, c in enumerate(nonempty):
                    offs[i + 1] = offs[i] + len(c)
                idx = np.concatenate(
                    [np.asarray(c, dtype=np.int64) for c in nonempty]
                )
                yield idx, offs

        # blocks_from_calls receives the SAME rows the CSR pairs carry
        # (carrying streams drop empty variants before both tiers).
        flat_nonempty = [c for sh in shards if sh for c in sh if c]
        want = list(blocks_from_calls(iter(flat_nonempty), n, width))
        got = list(blocks_from_csr(pairs(), n, width))
        assert len(got) == len(want)
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)
