"""Search-variants example drivers (SearchVariantsExample parity)."""


from spark_examples_tpu.genomics.sources import FixtureSource
from spark_examples_tpu.models.search_variants import (
    search_variants_brca1,
    search_variants_klotho,
)


def _fixture():
    # Mix of variant records and reference-matching blocks ("N" ref, no
    # alternates), as the Platinum Genomes gVCF-style sets contain.
    variants = [
        {
            "reference_name": "chr13",
            "start": 33628137,
            "end": 33628138,
            "reference_bases": "T",
            "alternate_bases": ["G"],
            "variant_set_id": "vs",
            "calls": [
                {"callset_id": "c1", "genotype": [0, 1]},
            ],
        },
        {
            "reference_name": "chr13",
            "start": 33628137,
            "end": 33628200,
            "reference_bases": "N",
            "variant_set_id": "vs",
            "calls": [],
        },
        {
            "reference_name": "chr17",
            "start": 41196400,
            "end": 41196401,
            "reference_bases": "A",
            "alternate_bases": ["C"],
            "variant_set_id": "vs",
        },
        {
            "reference_name": "chr17",
            "start": 41196500,
            "end": 41196600,
            "reference_bases": "N",
            "variant_set_id": "vs",
        },
    ]
    return FixtureSource(variants=variants)


def test_klotho_counts_and_roundtrip(capsys):
    lines = search_variants_klotho(_fixture(), "vs")
    assert lines[0] == "We have 2 records that overlap Klotho."
    assert lines[1] == "But only 1 records are of a variant."
    assert lines[2] == "The other 1 records are reference-matching blocks."
    assert "Reference: 13 @ 33628137" in lines
    out = capsys.readouterr().out
    assert "We have 2 records" in out


def test_brca1_counts(capsys):
    lines = search_variants_brca1(_fixture(), "vs")
    assert lines[0] == "We have 2 records that overlap BRCA1."
    # BRCA1 keys the split on referenceBases != "N".
    assert lines[1] == "But only 1 records are of a variant."


def test_cli_search_variants(capsys):
    from spark_examples_tpu.cli.main import main

    rc = main(
        [
            "search-variants-klotho",
            "--fixture-samples",
            "5",
            "--fixture-variants",
            "3",
            "--references",
            "chr13:33628137:33628138",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "records that overlap Klotho" in out


def test_examples_on_generated_gvcf_cohort(capsys):
    """Generated cohorts with gVCF reference blocks exercise both count
    branches of the example drivers."""
    from spark_examples_tpu.genomics.fixtures import synthetic_cohort

    src = synthetic_cohort(
        5,
        20,
        references="13:33628000:33629000",
        reference_blocks_every=4,
    )
    lines = search_variants_klotho(
        src, "fixture-platinum", references="13:33628000:33629000"
    )
    assert lines[0] == "We have 20 records that overlap Klotho."
    assert lines[1] == "But only 15 records are of a variant."
    assert lines[2] == "The other 5 records are reference-matching blocks."
