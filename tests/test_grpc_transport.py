"""gRPC/HTTP-2 server-streaming transport parity and semantics.

The reference's bulk channel is protobuf-over-gRPC server streaming
(VariantsRDD.scala:26,210-211); this suite pins the gRPC transport to
the same record-for-record results as the local and HTTP tiers, plus
the auth and error-accounting semantics the reference's client wrapper
feeds its accumulators from (VariantsRDD.scala:199-203).
"""

import numpy as np
import pytest

from spark_examples_tpu.genomics.auth import Credentials
from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
    synthetic_reads,
)
from spark_examples_tpu.genomics.grpc_transport import (
    GrpcGenomicsServer,
    GrpcVariantSource,
    grpc_available,
)
from spark_examples_tpu.genomics.shards import shards_for_references
from spark_examples_tpu.genomics.sources import JsonlSource

pytestmark = pytest.mark.skipif(
    not grpc_available(), reason="grpcio not installed"
)

REFS = "17:41196311:41277499"


@pytest.fixture()
def grpc_cohort():
    src = synthetic_cohort(8, 60, seed=9)
    src.add_reads(
        synthetic_reads(
            20, references="17:41200000:41210000", seed=9
        ).reads_records()
    )
    server = GrpcGenomicsServer(src).start()
    client = GrpcVariantSource(f"grpc://127.0.0.1:{server.port}")
    try:
        yield src, client
    finally:
        client.close()
        server.stop()


class TestGrpcStreamParity:
    def test_variants_match_local_jsonl(self, grpc_cohort, tmp_path):
        src, rpc = grpc_cohort
        src.dump(str(tmp_path / "cohort"))
        local = JsonlSource(str(tmp_path / "cohort"))
        shards = shards_for_references(REFS, 20_000)
        for shard in shards:
            got = list(rpc.stream_variants(DEFAULT_VARIANT_SET_ID, shard))
            want = list(
                local.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
            )
            assert got == want  # frozen dataclasses: field-exact
        assert rpc.stats.variants_read == 60
        assert rpc.stats.partitions == len(shards)
        assert rpc.stats.unsuccessful_responses == 0

    def test_reads_roundtrip(self, grpc_cohort, tmp_path):
        src, rpc = grpc_cohort
        src.dump(str(tmp_path / "cohort"))
        local = JsonlSource(str(tmp_path / "cohort"))
        for shard in shards_for_references("17:41200000:41210000", 5_000):
            assert list(rpc.stream_reads("", shard)) == list(
                local.stream_reads("", shard)
            )

    def test_callsets_and_identity(self, grpc_cohort):
        src, rpc = grpc_cohort
        assert rpc.list_callsets(DEFAULT_VARIANT_SET_ID) == (
            src.list_callsets(DEFAULT_VARIANT_SET_ID)
        )
        # Identity parity (the mirror cache key); fixtures expose one.
        assert rpc.cohort_identity() == src.cohort_identity()
        assert rpc.cohort_identity() is not None

    def test_identity_less_source_yields_none(self):
        inner = synthetic_cohort(4, 10, seed=1)

        class NoIdentity:
            def list_callsets(self, vsid):
                return inner.list_callsets(vsid)

            def stream_variants(self, vsid, shard):
                return inner.stream_variants(vsid, shard)

            def stream_reads(self, rgsid, shard):
                return inner.stream_reads(rgsid, shard)

        server = GrpcGenomicsServer(NoIdentity()).start()
        client = GrpcVariantSource(f"grpc://127.0.0.1:{server.port}")
        try:
            # Served NOT_FOUND → None (degrade like the HTTP client),
            # counted as a served non-OK status.
            assert client.cohort_identity() is None
            assert client.stats.unsuccessful_responses == 1
        finally:
            client.close()
            server.stop()

    def test_jsonl_backed_server_takes_raw_line_path(self, tmp_path):
        """A jsonl-backed gRPC server streams raw bytes off the line
        index — parity must hold through the zero-parse path too."""
        src = synthetic_cohort(8, 60, seed=9)
        root = str(tmp_path / "c")
        src.dump(root)
        backing = JsonlSource(root)
        assert backing._line_index() is not None
        server = GrpcGenomicsServer(backing).start()
        client = GrpcVariantSource(f"grpc://127.0.0.1:{server.port}")
        try:
            local = JsonlSource(root)
            for shard in shards_for_references(REFS, 20_000):
                assert list(
                    client.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
                ) == list(
                    local.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
                )
        finally:
            client.close()
            server.stop()


class TestGrpcAuthAndErrors:
    def test_token_required(self):
        src = synthetic_cohort(4, 10, seed=1)
        server = GrpcGenomicsServer(src, token="sekrit").start()
        shard = shards_for_references(REFS, 100_000)[0]
        try:
            anonymous = GrpcVariantSource(f"grpc://127.0.0.1:{server.port}")
            with pytest.raises(IOError, match="UNAUTHENTICATED"):
                list(anonymous.stream_variants("", shard))
            assert anonymous.stats.unsuccessful_responses == 1
            anonymous.close()

            good = GrpcVariantSource(
                f"grpc://127.0.0.1:{server.port}",
                credentials=Credentials("sekrit", "client-secrets"),
            )
            assert len(list(good.stream_variants("", shard))) == 10
            assert good.stats.unsuccessful_responses == 0
            good.close()
        finally:
            server.stop()

    def test_midstream_failure_is_status_not_truncation(self):
        """gRPC's framing turns a server abort mid-stream into a STATUS
        on the client — the property the HTTP layer hand-rolls with its
        end frame."""
        inner = synthetic_cohort(4, 10, seed=1)

        class FailsMidStream:
            def list_callsets(self, vsid):
                return inner.list_callsets(vsid)

            def stream_variants(self, vsid, shard):
                for i, v in enumerate(inner.stream_variants(vsid, shard)):
                    if i == 3:
                        raise IOError("disk died mid-shard")
                    yield v

            def stream_reads(self, rgsid, shard):
                return inner.stream_reads(rgsid, shard)

        server = GrpcGenomicsServer(FailsMidStream()).start()
        client = GrpcVariantSource(f"grpc://127.0.0.1:{server.port}")
        shard = shards_for_references(REFS, 100_000)[0]
        try:
            with pytest.raises(IOError):
                list(client.stream_variants("", shard))
            assert (
                client.stats.unsuccessful_responses
                + client.stats.io_exceptions
                == 1
            )
        finally:
            client.close()
            server.stop()

    def test_dead_server_counts_io_exception(self):
        client = GrpcVariantSource("grpc://127.0.0.1:1", timeout=3)
        shard = shards_for_references(REFS, 100_000)[0]
        with pytest.raises(IOError):
            list(client.stream_variants("", shard))
        assert client.stats.io_exceptions == 1
        client.close()


class TestGrpcPipeline:
    def test_pca_driver_over_grpc_matches_local(self, tmp_path):
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        src = synthetic_cohort(8, 60, seed=9)
        root = str(tmp_path / "c")
        src.dump(root)
        server = GrpcGenomicsServer(JsonlSource(root)).start()
        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            references=REFS,
            bases_per_partition=20_000,
            block_variants=16,
        )
        client = GrpcVariantSource(f"grpc://127.0.0.1:{server.port}")
        try:
            remote = VariantsPcaDriver(conf, client).run()
        finally:
            client.close()
            server.stop()
        local = VariantsPcaDriver(conf, JsonlSource(root)).run()
        np.testing.assert_allclose(
            np.array([r[1:] for r in remote]),
            np.array([r[1:] for r in local]),
            atol=1e-5,
        )


class TestGrpcPcaBackendSeam:
    def test_compute_pca_matches_tcp_bridge(self):
        """The dense-math seam over gRPC (SURVEY §7.6's 'small gRPC
        service') returns the same coordinates as the newline-JSON TCP
        bridge for the same call stream."""
        from spark_examples_tpu.bridge.backend import (
            PcaBridgeClient,
            PcaBridgeServer,
            TpuPcaBackend,
        )

        calls = [[0, 1, 2], [0, 1], [1, 2], [3, 4, 5], [3, 4], [4, 5],
                 [0, 1, 2], [3, 4, 5]]
        backend = TpuPcaBackend(block_variants=16)
        grpc_server = GrpcGenomicsServer(
            synthetic_cohort(4, 10, seed=1), pca_backend=backend
        ).start()
        tcp_server = PcaBridgeServer(TpuPcaBackend(block_variants=16)).start()
        rpc = GrpcVariantSource(f"grpc://127.0.0.1:{grpc_server.port}")
        tcp = PcaBridgeClient(port=tcp_server.port)
        try:
            got_c, got_v = rpc.compute_pca(iter(calls), 6, 2, batch_size=3)
            want_c, want_v = tcp.compute(iter(calls), 6, 2, batch_size=3)
            np.testing.assert_allclose(got_c, want_c, atol=1e-6)
            np.testing.assert_allclose(got_v, want_v, atol=1e-6)
        finally:
            tcp.close()
            rpc.close()
            grpc_server.stop()
            tcp_server.stop()

    def test_compute_pca_validation_error_is_status(self):
        from spark_examples_tpu.bridge.backend import TpuPcaBackend

        server = GrpcGenomicsServer(
            synthetic_cohort(4, 10, seed=1),
            pca_backend=TpuPcaBackend(block_variants=16),
        ).start()
        client = GrpcVariantSource(f"grpc://127.0.0.1:{server.port}")
        try:
            with pytest.raises(IOError, match="INVALID_ARGUMENT"):
                client.compute_pca(iter([[0, 1]]), 6, 0)  # num_pc < 1
        finally:
            client.close()
            server.stop()
