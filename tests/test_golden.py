"""Committed golden end-to-end output — cross-round numeric drift anchor.

SURVEY.md §4 calls for a BRCA1-sized golden fixture reproducing the
emitResult output. The golden TSV was produced by the full pipeline
(fixture seed 0, 64 samples × 500 variants, ``--precise`` host-f64 path)
and committed; any change that shifts principal coordinates beyond 1e-6
against it is either a deliberate semantic change (regenerate the golden
and say so in the commit) or a regression.
"""

import os

import numpy as np

from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.models.pca import VariantsPcaDriver
from spark_examples_tpu.utils.config import PcaConfig

GOLDEN = os.path.join(
    os.path.dirname(__file__), "golden", "brca1_cohort64_seed0-pca.tsv"
)


def _load(path):
    rows = {}
    with open(path) as f:
        for line in f:
            name, pc1, pc2, dataset = line.rstrip("\n").split("\t")
            rows[name] = (float(pc1), float(pc2), dataset)
    return rows


def test_pipeline_matches_committed_golden(tmp_path):
    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        output_path=str(tmp_path / "out"),
        precise=True,
        block_variants=64,
    )
    VariantsPcaDriver(conf, synthetic_cohort(64, 500, seed=0)).run()

    got = _load(str(tmp_path / "out-pca.tsv"))
    want = _load(GOLDEN)
    assert got.keys() == want.keys()
    for name in want:
        np.testing.assert_allclose(
            got[name][:2], want[name][:2], atol=1e-6, err_msg=name
        )
        assert got[name][2] == want[name][2]


JOIN_GOLDEN = os.path.join(
    os.path.dirname(__file__), "golden", "join_cohort32_seed4-pca.tsv"
)


def test_two_dataset_join_matches_committed_golden(tmp_path):
    """Cross-round anchor for the multi-dataset identity join: two
    identical 32-sample cohorts under different variant-set ids joined
    through the full driver (--precise), pinned to 1e-6. Sanity property
    baked into the fixture: each sample's setA/setB twins must land at
    the same coordinates."""
    from spark_examples_tpu.genomics.sources import FixtureSource

    a = synthetic_cohort(32, 300, variant_set_id="setA", seed=4)
    b = synthetic_cohort(32, 300, variant_set_id="setB", seed=4)
    merged = FixtureSource(
        variants=a._variants + b._variants,
        callsets=a._callsets + b._callsets,
    )
    conf = PcaConfig(
        variant_set_ids=["setA", "setB"],
        precise=True,
        block_variants=64,
        output_path=str(tmp_path / "join"),
    )
    VariantsPcaDriver(conf, merged).run()

    def load_multi(path):
        rows = {}
        with open(path) as f:
            for line in f:
                name, pc1, pc2, dataset = line.rstrip("\n").split("\t")
                rows[(name, dataset)] = (float(pc1), float(pc2))
        return rows

    got = load_multi(str(tmp_path / "join-pca.tsv"))
    want = load_multi(JOIN_GOLDEN)
    assert got.keys() == want.keys()
    for key in want:
        np.testing.assert_allclose(
            got[key], want[key], atol=1e-6, err_msg=str(key)
        )
    # Twin-coordinate sanity: the same sample in both sets coincides.
    for (name, dataset), (pc1, pc2) in got.items():
        np.testing.assert_allclose(
            (pc1, pc2), got[(name, "setA")], atol=1e-9, err_msg=name
        )
