"""Bridge tests: the PcaBackend seam over a real socket."""

import numpy as np

from spark_examples_tpu.bridge import (
    PcaBridgeClient,
    PcaBridgeServer,
    TpuPcaBackend,
)
from spark_examples_tpu.ops import mllib_principal_components_reference


def _random_calls(n, v, seed=0):
    rng = np.random.default_rng(seed)
    return [
        list(rng.choice(n, size=rng.integers(1, n), replace=False))
        for _ in range(v)
    ]


def _golden(calls, n, k):
    x = np.zeros((n, len(calls)))
    for col, idx in enumerate(calls):
        x[idx, col] = 1
    return mllib_principal_components_reference(x @ x.T, k)[0]


def test_inprocess_backend_matches_golden():
    calls = _random_calls(17, 120)
    coords, eigvals = TpuPcaBackend(block_variants=32).compute(
        iter(calls), 17, 2
    )
    np.testing.assert_allclose(coords, _golden(calls, 17, 2), atol=1e-4)
    assert eigvals.shape == (2,)


def test_socket_bridge_round_trip():
    calls = _random_calls(11, 60, seed=2)
    server = PcaBridgeServer(TpuPcaBackend(block_variants=16)).start()
    try:
        client = PcaBridgeClient(port=server.port)
        coords, _ = client.compute(calls, 11, 2, batch_size=7)
        client.close()
        np.testing.assert_allclose(coords, _golden(calls, 11, 2), atol=1e-4)
    finally:
        server.stop()


def test_bridge_error_on_missing_init():
    import json
    import socket

    server = PcaBridgeServer(TpuPcaBackend()).start()
    try:
        sock = socket.create_connection(("127.0.0.1", server.port))
        f = sock.makefile("rwb")
        f.write(b'{"cmd": "finish"}\n')
        f.flush()
        resp = json.loads(f.readline())
        assert "error" in resp
        sock.close()
    finally:
        server.stop()


def test_bridge_invalid_num_pc_reported():
    server = PcaBridgeServer(TpuPcaBackend()).start()
    try:
        client = PcaBridgeClient(port=server.port)
        import pytest

        with pytest.raises(RuntimeError, match="num_pc"):
            client.compute([[0]], 3, 0)
        client.close()
    finally:
        server.stop()


def test_external_driver_example_script(tmp_path):
    """The examples/ client script runs end-to-end against a live server."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    server = PcaBridgeServer(TpuPcaBackend(block_variants=64)).start()
    try:
        out = subprocess.run(
            [
                sys.executable,
                os.path.join(root, "examples", "external_driver_pca.py"),
                "--port",
                str(server.port),
                "--samples",
                "8",
                "--variants",
                "40",
            ],
            env={**os.environ, "PYTHONPATH": root, "JAX_PLATFORMS": "cpu"},
            capture_output=True,
            timeout=120,
            text=True,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        lines = [
            l for l in out.stdout.strip().split("\n") if "\t" in l
        ]
        assert len(lines) == 8  # one coordinate row per sample
    finally:
        server.stop()


def test_bridge_from_cpp_client(tmp_path):
    """Cross the seam from a FOREIGN runtime: a C++ TCP client speaks the
    newline-JSON protocol against a live server — the reference's
    JVM-driver-delegates-dense-math role (variants_pca.py:162-182) without
    any Python on the client side."""
    import os
    import shutil
    import subprocess

    gxx = shutil.which("g++")
    if gxx is None:
        import pytest

        pytest.skip("g++ not available")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(root, "examples", "pca_bridge_client.cpp")
    binary = tmp_path / "pca_bridge_client"
    subprocess.run(
        [gxx, "-O2", "-std=c++17", "-o", str(binary), src],
        check=True,
        capture_output=True,
        timeout=120,
    )
    server = PcaBridgeServer(TpuPcaBackend(block_variants=16)).start()
    try:
        out = subprocess.run(
            [str(binary), str(server.port)],
            capture_output=True,
            timeout=120,
            text=True,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "bridge ok" in out.stdout
    finally:
        server.stop()


def test_bridge_from_jvm_client(tmp_path):
    """Cross the seam from the runtime it exists for: a dependency-free
    Java client speaks the newline-JSON protocol against a live server —
    the reference's JVM driver delegating its dense math
    (variants_pca.py:162-182). Compiles and runs only where a JDK exists
    (none ships in this image — BASELINE.md); on any JVM-bearing host the
    suite proves the cross-language twin end-to-end."""
    import os
    import shutil
    import subprocess

    javac, java = shutil.which("javac"), shutil.which("java")
    if javac is None or java is None:
        import pytest

        pytest.skip("no JDK on this host (javac/java not found)")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(root, "examples", "PcaBridgeClient.java")
    subprocess.run(
        [javac, "-d", str(tmp_path), src],
        check=True,
        capture_output=True,
        timeout=120,
    )
    server = PcaBridgeServer(TpuPcaBackend(block_variants=16)).start()
    try:
        out = subprocess.run(
            [java, "-cp", str(tmp_path), "PcaBridgeClient", str(server.port)],
            capture_output=True,
            timeout=120,
            text=True,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "bridge ok (jvm)" in out.stdout
    finally:
        server.stop()
