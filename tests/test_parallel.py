"""Sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_examples_tpu.ops import gramian, double_center, principal_components
from spark_examples_tpu.parallel import (
    gramian_variant_parallel,
    make_mesh,
    sharded_gramian_blockwise,
    sharded_pcoa,
    topk_eig_randomized,
)


@pytest.fixture
def x_small():
    rng = np.random.default_rng(0)
    return (rng.random((32, 256)) < 0.3).astype(np.int8)


class TestMesh:
    def test_default_mesh_all_devices(self):
        mesh = make_mesh()
        assert mesh.devices.size == 8
        assert mesh.axis_names == ("data",)

    def test_spec_mesh(self):
        mesh = make_mesh("data:4,model:2")
        assert mesh.shape == {"data": 4, "model": 2}

    def test_oversized_spec_rejected(self):
        with pytest.raises(ValueError, match="needs 16"):
            make_mesh("data:16")


class TestShardedGramian:
    def test_variant_parallel_matches_dense(self, x_small):
        mesh = make_mesh("data:8")
        g = gramian_variant_parallel(jnp.asarray(x_small), mesh)
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(gramian(x_small))
        )

    def test_blockwise_sharded_matches_dense_1d(self, x_small):
        mesh = make_mesh("data:8")
        blocks = [x_small[:, i : i + 64] for i in range(0, 256, 64)]
        g = sharded_gramian_blockwise(blocks, 32, mesh)
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(gramian(x_small))
        )

    def test_blockwise_sharded_matches_dense_2d(self, x_small):
        mesh = make_mesh("data:4,model:2")
        blocks = [x_small[:, i : i + 64] for i in range(0, 256, 64)]
        g = sharded_gramian_blockwise(blocks, 32, mesh)
        # G must actually be laid out across the mesh.
        assert len(g.sharding.device_set) == 8
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(gramian(x_small))
        )

    def test_blockwise_sharded_packed_bit_identical(self, x_small):
        """The bit-packed feed (the production default in the model) must
        be bit-identical to the unpacked sharded path, including a block
        width (100) that is neither a multiple of 8 nor of the mesh's
        variant-axis divisor — pad bytes unpack to inert zero columns."""
        mesh = make_mesh("data:4,model:2")
        ragged = [x_small[:, :100], x_small[:, 100:200], x_small[:, 200:]]
        want = np.asarray(gramian(x_small))
        got = sharded_gramian_blockwise(ragged, 32, mesh, packed=True)
        assert len(got.sharding.device_set) == 8
        np.testing.assert_array_equal(np.asarray(got), want)


class TestShardedEig:
    def test_randomized_topk_matches_eigh(self):
        rng = np.random.default_rng(5)
        q, _ = np.linalg.qr(rng.random((64, 64)))
        w = np.linspace(50, 0.01, 64) * np.sign(rng.random(64) - 0.2)
        c = (q * w) @ q.T
        c = np.asarray(double_center(c), dtype=np.float32)

        exact_v, exact_w = principal_components(c, 3)
        rand_v, rand_w = topk_eig_randomized(jnp.asarray(c), 3, iters=60)
        np.testing.assert_allclose(
            np.asarray(rand_w), np.asarray(exact_w), rtol=1e-3
        )
        np.testing.assert_allclose(
            np.abs(np.asarray(rand_v)), np.abs(np.asarray(exact_v)), atol=1e-3
        )

    def test_sharded_pcoa_dense_path(self, x_small):
        mesh = make_mesh("data:4,model:2")
        blocks = [x_small[:, i : i + 64] for i in range(0, 256, 64)]
        g = sharded_gramian_blockwise(blocks, 32, mesh)
        coords, w = sharded_pcoa(g, 2, mesh)
        golden, _ = principal_components(
            np.asarray(double_center(np.asarray(gramian(x_small)))), 2
        )
        np.testing.assert_allclose(
            np.asarray(coords), np.asarray(golden), atol=1e-4
        )

    def test_sharded_pcoa_randomized_path(self, x_small):
        mesh = make_mesh("data:4,model:2")
        g = gramian(x_small)
        g = jax.device_put(
            g, NamedSharding(mesh, P("data", "model"))
        )
        coords, w = sharded_pcoa(g, 2, mesh, dense_eigh_limit=8)
        golden, _ = principal_components(
            np.asarray(double_center(np.asarray(gramian(x_small)))), 2
        )
        np.testing.assert_allclose(
            np.abs(np.asarray(coords)), np.abs(golden), atol=1e-2
        )


class TestDriverWithMesh:
    def test_pca_driver_sharded(self):
        from spark_examples_tpu.genomics.fixtures import (
            DEFAULT_VARIANT_SET_ID,
            synthetic_cohort,
        )
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID], block_variants=64
        )
        mesh = make_mesh("data:4,model:2")
        source = synthetic_cohort(24, 200)
        result = VariantsPcaDriver(conf, source, mesh=mesh).run()

        conf2 = PcaConfig(variant_set_ids=[DEFAULT_VARIANT_SET_ID])
        unsharded = VariantsPcaDriver(
            conf2, synthetic_cohort(24, 200)
        ).run()
        a = np.array([r[1:] for r in result])
        b = np.array([r[1:] for r in unsharded])
        np.testing.assert_allclose(a, b, atol=1e-4)


class TestReviewRegressions:
    def test_sharded_gramian_nondivisible_n(self):
        """N=23 on an 8-way mesh: padding must make the mesh path work for
        arbitrary cohort sizes."""
        rng = np.random.default_rng(9)
        x = (rng.random((23, 128)) < 0.3).astype(np.int8)
        mesh = make_mesh("data:4,model:2")
        blocks = [x[:, i : i + 32] for i in range(0, 128, 32)]
        g = sharded_gramian_blockwise(blocks, 23, mesh)
        assert g.shape == (23, 23)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(gramian(x)))

    def test_sharded_gramian_float_blocks_compute_in_float(self):
        """Out-of-trace dtype resolution must key off the block's REAL
        dtype: a fractional float block (imputed dosages) computes its
        exact f32 product, never a silent int8 truncation (round-4
        review finding on the resolve hoist)."""
        mesh = make_mesh("data:4,model:2")
        xb = np.full((8, 16), 0.5, np.float32)
        g = sharded_gramian_blockwise([xb], 8, mesh)
        np.testing.assert_allclose(
            np.asarray(g), np.full((8, 8), 4.0, np.float32)
        )

    def test_driver_mesh_uses_sharded_pcoa_nondivisible(self):
        from spark_examples_tpu.genomics.fixtures import (
            DEFAULT_VARIANT_SET_ID,
            synthetic_cohort,
        )
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID], block_variants=64
        )
        mesh = make_mesh("data:8")
        result = VariantsPcaDriver(
            conf, synthetic_cohort(23, 150), mesh=mesh
        ).run()
        assert len(result) == 23

    def test_randomized_meets_parity_bar_on_realistic_spectrum(self):
        """Population-structure cohorts (the real workload) converge far
        below the 1e-4 parity bar — the basis for trusting the randomized
        path at N where dense eigh is infeasible."""
        rng = np.random.default_rng(0)
        n, v = 1024, 8192
        groups = rng.integers(0, 3, size=n)
        af = rng.beta(0.4, 1.2, size=(3, v))
        x = (rng.random((n, v)) < af[groups]).astype(np.int8)
        c = np.asarray(
            double_center(np.asarray(gramian(x), np.float64))
        ).astype(np.float32)

        exact_v, _ = principal_components(c.astype(np.float64), 2)
        rand_v, _ = topk_eig_randomized(jnp.asarray(c), 2, iters=15)
        err = np.abs(
            np.abs(np.asarray(rand_v)) - np.abs(np.asarray(exact_v))
        ).max()
        assert err < 1e-4, err


class TestAdaptiveEig:
    """Opt-in tol-based convergence for the randomized eig path."""

    @staticmethod
    def _structured_c(n=1024, v=8192, seed=0):
        rng = np.random.default_rng(seed)
        groups = rng.integers(0, 3, size=n)
        af = rng.beta(0.4, 1.2, size=(3, v))
        x = (rng.random((n, v)) < af[groups]).astype(np.int8)
        return np.asarray(
            double_center(np.asarray(gramian(x), np.float64))
        ).astype(np.float32)

    def test_tol_zero_bit_identical_to_fixed(self):
        """With an unreachable tol and the cap a chunk multiple, the
        adaptive path applies the exact same operation sequence as the
        fixed sweep — bit-identical output."""
        c = jnp.asarray(self._structured_c(n=256, v=2048))
        fixed_v, fixed_w = topk_eig_randomized(c, 2, iters=20)
        adapt_v, adapt_w = topk_eig_randomized(
            c, 2, iters=20, tol=0.0, check_every=5
        )
        np.testing.assert_array_equal(
            np.asarray(fixed_v), np.asarray(adapt_v)
        )
        np.testing.assert_array_equal(
            np.asarray(fixed_w), np.asarray(adapt_w)
        )

    def test_converges_early_and_meets_parity_bar(self):
        """On a sharp population-structure spectrum the adaptive sweep
        stops well before the cap and still clears the 1e-4 bar."""
        from spark_examples_tpu.utils.tracing import StageTimer

        c = self._structured_c()
        exact_v, _ = principal_components(c.astype(np.float64), 2)
        timer = StageTimer()
        rand_v, _ = topk_eig_randomized(
            jnp.asarray(c), 2, iters=60, tol=1e-6, timer=timer
        )
        err = np.abs(
            np.abs(np.asarray(rand_v)) - np.abs(np.asarray(exact_v))
        ).max()
        assert err < 1e-4, err
        note = [
            n
            for notes in timer.notes.values()
            for n in notes
            if "randomized eig" in n
        ]
        assert len(note) == 1
        used = int(note[0].split(":")[1].split("/")[0])
        assert used < 60  # converged before the cap

    def test_sharded_pcoa_threads_eig_tol(self):
        """eig_tol flows through sharded_pcoa's randomized branch.

        Structured spectrum (population groups): the randomized path is
        rotation-fragile on flat random spectra by design — the same
        reason test_sharded_pcoa_randomized_path compares at 1e-2.
        """
        rng = np.random.default_rng(3)
        groups = rng.integers(0, 3, size=96)
        af = rng.beta(0.4, 1.2, size=(3, 2048))
        x = (rng.random((96, 2048)) < af[groups]).astype(np.int8)
        g = np.asarray(gramian(x), np.float32)
        mesh = make_mesh()
        exact, _ = sharded_pcoa(
            jnp.asarray(g), 2, mesh, dense_eigh_limit=1024
        )
        approx, _ = sharded_pcoa(
            jnp.asarray(g),
            2,
            mesh,
            dense_eigh_limit=8,  # force the randomized branch
            eig_tol=1e-7,
        )
        err = np.abs(
            np.abs(np.asarray(approx)) - np.abs(np.asarray(exact))
        ).max()
        assert err < 1e-4, err


def test_cli_pca_with_mesh_flag(capsys, tmp_path):
    from spark_examples_tpu.cli.main import main

    rc = main(
        [
            "pca",
            "--fixture-samples",
            "13",
            "--fixture-variants",
            "90",
            "--mesh-shape",
            "data:4,model:2",
            "--output-path",
            str(tmp_path / "mesh"),
        ]
    )
    assert rc == 0
    assert "Matrix size: 13" in capsys.readouterr().out
    assert (tmp_path / "mesh-pca.tsv").exists()


class TestSpectralGapWarning:
    """Flat spectra must be loud, not silently unstable (round-2 verdict:
    a weakly structured cohort gets a rotation-ambiguous PC2 from dense
    eigh and randomized eig alike — detect it at runtime)."""

    @staticmethod
    def _matrix_with_spectrum(w, seed=3):
        rng = np.random.default_rng(seed)
        q, _ = np.linalg.qr(rng.random((len(w), len(w))))
        return ((q * w) @ q.T).astype(np.float32)

    def test_degenerate_gap_warns(self):
        from spark_examples_tpu.parallel import SpectralGapWarning

        c = self._matrix_with_spectrum(
            np.array([10.0, 5.0, 4.999] + [0.01] * 29)
        )
        with pytest.warns(SpectralGapWarning, match=r"\|λ3\|/\|λ2\|"):
            topk_eig_randomized(jnp.asarray(c), 2, iters=40)

    def test_separated_gap_silent(self):
        import warnings

        from spark_examples_tpu.parallel import SpectralGapWarning

        c = self._matrix_with_spectrum(
            np.array([10.0, 5.0, 1.0] + [0.01] * 29)
        )
        with warnings.catch_warnings():
            warnings.simplefilter("error", SpectralGapWarning)
            topk_eig_randomized(jnp.asarray(c), 2, iters=40)

    def test_gap_ratio_lands_in_stage_report(self):
        from spark_examples_tpu.utils.tracing import StageTimer

        timer = StageTimer()
        c = self._matrix_with_spectrum(
            np.array([10.0, 5.0, 1.0] + [0.01] * 29)
        )
        with timer.stage("pca"):
            topk_eig_randomized(jnp.asarray(c), 2, iters=40, timer=timer)
        report = timer.report()
        assert "spectral gap" in report
        assert "0.2" in timer.notes["pca"][0]  # |λ3|/|λ2| = 1/5

    def test_dense_paths_also_detect_degeneracy(self):
        """The dense-eigh branches (sharded_pcoa small-N, the default
        single-host pcoa) must be as loud on a flat spectrum as the
        randomized path — review finding round 3."""
        from spark_examples_tpu.ops.pcoa import check_spectral_gap
        from spark_examples_tpu.parallel import SpectralGapWarning

        # Build the near-degenerate pair INSIDE the centering-invariant
        # subspace (eigvecs ⊥ 1), so double_center leaves the flat gap
        # intact on the way into the dense branch.
        rng = np.random.default_rng(3)
        a = rng.random((32, 32))
        a -= a.mean(axis=0, keepdims=True)  # columns ⊥ ones
        q, _ = np.linalg.qr(a)
        w = np.array([10.0, 5.0, 4.999] + [0.01] * 28)
        c = ((q[:, :31] * w) @ q[:, :31].T).astype(np.float32)

        mesh = make_mesh("data:4,model:2")
        g = jax.device_put(c, NamedSharding(mesh, P("data", "model")))
        with pytest.warns(SpectralGapWarning):
            sharded_pcoa(g, 2, mesh)  # n=32 <= limit: dense branch

        vecs, vals = principal_components(jnp.asarray(c), 3)
        with pytest.warns(SpectralGapWarning):
            check_spectral_gap(np.asarray(vals), 2)


def test_ring_reduction_matches_psum():
    from spark_examples_tpu.parallel import gramian_variant_parallel_ring

    rng = np.random.default_rng(21)
    x = (rng.random((16, 256)) < 0.3).astype(np.int8)
    mesh = make_mesh("data:8")
    ring = np.asarray(gramian_variant_parallel_ring(jnp.asarray(x), mesh))
    psum = np.asarray(gramian_variant_parallel(jnp.asarray(x), mesh))
    np.testing.assert_array_equal(ring, psum)
    np.testing.assert_array_equal(ring, np.asarray(gramian(x)))

    # Float-valued X (dosages): replicas must still be bitwise canonical.
    xf = rng.random((16, 256)).astype(np.float32)
    ringf = gramian_variant_parallel_ring(jnp.asarray(xf), mesh)
    shards = [np.asarray(s.data) for s in ringf.addressable_shards]
    for sh in shards[1:]:
        np.testing.assert_array_equal(shards[0], sh)
