"""Checkpoint/resume: shard-group Gramian snapshots."""

import numpy as np
import pytest

from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.genomics.shards import (
    manifest_digest,
    shards_for_references,
)
from spark_examples_tpu.models.pca import VariantsPcaDriver
from spark_examples_tpu.utils.checkpoint import load_snapshot, save_snapshot
from spark_examples_tpu.utils.config import PcaConfig


def _conf(tmp_path, **kw):
    return PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,  # BRCA1 region → 5 shards
        block_variants=64,
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=2,
        **kw,
    )


class TestSnapshotRoundTrip:
    def test_save_load(self, tmp_path):
        g = np.arange(9.0).reshape(3, 3)
        save_snapshot(str(tmp_path), g, shards_done=4, run_digest="abc")
        ck = load_snapshot(str(tmp_path), "abc", 3)
        assert ck is not None and ck.shards_done == 4
        np.testing.assert_array_equal(ck.g, g)

    def test_digest_mismatch_ignored(self, tmp_path):
        save_snapshot(str(tmp_path), np.zeros((2, 2)), 1, "abc")
        assert load_snapshot(str(tmp_path), "other", 2) is None
        assert load_snapshot(str(tmp_path), "abc", 5) is None

    def test_absent_dir(self, tmp_path):
        assert load_snapshot(str(tmp_path / "nope"), "x", 2) is None


class TestCheckpointedPipeline:
    def test_checkpointed_matches_plain(self, tmp_path):
        conf = _conf(tmp_path)
        driver = VariantsPcaDriver(conf, synthetic_cohort(15, 120))
        result = driver.run()

        plain_conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID], block_variants=64
        )
        plain = VariantsPcaDriver(
            plain_conf, synthetic_cohort(15, 120)
        ).run()
        np.testing.assert_allclose(
            np.array([r[1:] for r in result]),
            np.array([r[1:] for r in plain]),
            atol=1e-4,
        )

    def test_resume_skips_completed_shards(self, tmp_path):
        conf = _conf(tmp_path)
        src = synthetic_cohort(12, 100)
        driver = VariantsPcaDriver(conf, src)
        g_full = np.asarray(driver.get_similarity_matrix_checkpointed())
        partitions_full_run = src.stats.partitions

        # Fresh driver + fresh source: snapshot says all shards done, so
        # resume must not re-ingest anything.
        src2 = synthetic_cohort(12, 100)
        driver2 = VariantsPcaDriver(conf, src2)
        g_resumed = np.asarray(driver2.get_similarity_matrix_checkpointed())
        assert src2.stats.partitions == 0  # nothing re-streamed
        np.testing.assert_array_equal(g_full, g_resumed)

    def test_resume_after_partial_failure(self, tmp_path):
        """Kill ingest mid-run via fault injection; resume completes and
        matches the uninterrupted result."""
        conf = _conf(tmp_path)
        shards = shards_for_references(conf.references, 20_000)
        src = synthetic_cohort(12, 100)
        src._fail_once.add(shards[3])  # fails inside the second group
        driver = VariantsPcaDriver(conf, src)
        with pytest.raises(IOError):
            driver.get_similarity_matrix_checkpointed()

        # First group (2 shards) was snapshotted before the failure.
        digest = (
            f"{manifest_digest(shards)}|{DEFAULT_VARIANT_SET_ID}|af=None"
        )
        ck = load_snapshot(conf.checkpoint_dir, digest, 12)
        assert ck is not None and ck.shards_done == 2

        # Resume on a fresh driver (fault cleared) → identical Gramian.
        src2 = synthetic_cohort(12, 100)
        driver2 = VariantsPcaDriver(conf, src2)
        g = np.asarray(driver2.get_similarity_matrix_checkpointed())

        plain = VariantsPcaDriver(
            PcaConfig(
                variant_set_ids=[DEFAULT_VARIANT_SET_ID],
                bases_per_partition=20_000,
                block_variants=64,
            ),
            synthetic_cohort(12, 100),
        )
        data = plain.get_data()
        calls = plain.get_calls([plain.filter_dataset(d) for d in data])
        g_plain = np.asarray(plain.get_similarity_matrix(calls))
        np.testing.assert_array_equal(g, g_plain)
