"""Unified resilience layer: retry policies, breakers, fault plane, chaos.

Three layers of coverage:

1. Unit: RetryPolicy backoff/budget/Retry-After semantics, the
   per-transport classification tables, circuit-breaker transitions,
   FaultPlan determinism and rule matching.
2. Integration: the HTTP tier retrying 503s and shedding through an
   open breaker, the gRPC per-read idle timeout and bind-failure check,
   oauth retry classification, the watchdog exit-77 fail-stop, the
   light-mirror upgrade TOCTOU re-verify.
3. Chaos harness (the acceptance bar): the full CPU pipeline runs under
   seeded fault plans — transport errors, mid-stream worker death, torn
   checkpoint/lane writes — and the results are NUMERICALLY IDENTICAL
   to the fault-free run, with the injected faults and breaker
   transitions visible in trace/metrics artifacts that
   ``scripts/validate_trace.py`` validates. A randomized soak
   (``-m slow``; ``scripts/chaos_soak.sh``) fuzzes the same invariant.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

from spark_examples_tpu import resilience
from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.genomics.service import (
    GenomicsServiceServer,
    HttpVariantSource,
)
from spark_examples_tpu.genomics.shards import shards_for_references
from spark_examples_tpu.genomics.sources import JsonlSource
from spark_examples_tpu.models.pca import VariantsPcaDriver
from spark_examples_tpu.obs.session import TelemetrySession
from spark_examples_tpu.resilience import (
    Budget,
    BreakerSet,
    CircuitBreaker,
    CircuitOpenError,
    FaultPlan,
    FaultRule,
    InjectedFault,
    RetryDecision,
    RetryPolicy,
    call_with_retry,
    classify_grpc,
    classify_http,
    classify_ingest,
    classify_oauth,
    faults,
)
from spark_examples_tpu.utils.config import PcaConfig

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _lock_check_enabled():
    """The *_locked runtime backstop (docs/CONCURRENCY.md) is ON for
    the resilience suite too: the kill-resume chaos scenarios drive
    the serving tier's lock-protected paths hard, and a discipline
    violation must fail at its call site, not as a torn journal."""
    prev = os.environ.get("SPARK_EXAMPLES_TPU_LOCK_CHECK")
    os.environ["SPARK_EXAMPLES_TPU_LOCK_CHECK"] = "1"
    yield
    if prev is None:
        os.environ.pop("SPARK_EXAMPLES_TPU_LOCK_CHECK", None)
    else:
        os.environ["SPARK_EXAMPLES_TPU_LOCK_CHECK"] = prev


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_trace",
        os.path.join(_REPO_ROOT, "scripts", "validate_trace.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


validate = _load_validator()

REFS = "17:41196311:41277499"


# -- unit: retry policy -------------------------------------------------------


class TestRetryPolicy:
    def test_backoff_grows_exponentially_and_caps(self):
        p = RetryPolicy(
            base_delay=0.1, multiplier=2.0, max_delay=0.5, jitter=0.0
        )
        delays = [p.backoff_delay(k) for k in (1, 2, 3, 4, 5)]
        assert delays == pytest.approx([0.1, 0.2, 0.4, 0.5, 0.5])

    def test_jitter_stays_within_fraction(self):
        import random

        p = RetryPolicy(base_delay=1.0, jitter=0.25, max_delay=10.0)
        rng = random.Random(7)
        for _ in range(200):
            d = p.backoff_delay(1, rng)
            assert 0.75 <= d <= 1.25

    def test_retries_then_succeeds(self):
        calls, sleeps = [], []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("flaky")
            return "ok"

        out = call_with_retry(
            fn,
            RetryPolicy(max_attempts=4, jitter=0.0, base_delay=0.01),
            classify_ingest,
            sleep=sleeps.append,
        )
        assert out == "ok"
        assert len(calls) == 3
        assert len(sleeps) == 2

    def test_non_retryable_raises_on_first_attempt(self):
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("data error")

        with pytest.raises(ValueError):
            call_with_retry(
                fn,
                RetryPolicy(max_attempts=5, base_delay=0.0),
                classify_ingest,
                sleep=lambda s: None,
            )
        assert len(calls) == 1

    def test_attempts_exhausted_raises_last_error(self):
        calls = []

        def fn():
            calls.append(1)
            raise OSError(f"fail {len(calls)}")

        with pytest.raises(OSError, match="fail 3"):
            call_with_retry(
                fn,
                RetryPolicy(max_attempts=3, jitter=0.0, base_delay=0.0),
                classify_ingest,
                sleep=lambda s: None,
            )
        assert len(calls) == 3

    def test_deadline_budget_draws_down(self):
        """Attempts stop when the wall-clock budget runs dry, even with
        attempts remaining — the per-shard budget semantics."""
        now = [0.0]
        budget = Budget(1.0, clock=lambda: now[0])
        calls = []

        def fn():
            calls.append(1)
            now[0] += 0.6  # each attempt burns 0.6s of the 1s budget
            raise OSError("slow failure")

        with pytest.raises(OSError):
            call_with_retry(
                fn,
                RetryPolicy(max_attempts=10, jitter=0.0, base_delay=0.0),
                classify_ingest,
                budget=budget,
                sleep=lambda s: None,
            )
        assert len(calls) == 2  # third attempt would start past deadline

    def test_retry_after_hint_overrides_backoff(self):
        sleeps = []
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                raise OSError("throttled")
            return "ok"

        def classify(exc):
            return RetryDecision(True, "throttle", delay_hint=1.23)

        call_with_retry(
            fn,
            RetryPolicy(max_attempts=3, jitter=0.0, base_delay=99.0),
            classify,
            sleep=sleeps.append,
        )
        assert sleeps == [1.23]

    def test_retry_after_hint_is_capped_by_max_delay(self):
        """A server-directed hour-long Retry-After must not park a
        worker thread: the policy's own ceiling caps it."""
        sleeps = []
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                raise OSError("throttled hard")
            return "ok"

        call_with_retry(
            fn,
            RetryPolicy(max_attempts=3, jitter=0.0, max_delay=2.0),
            lambda e: RetryDecision(True, "x", delay_hint=3600.0),
            sleep=sleeps.append,
        )
        assert sleeps == [2.0]

    def test_retry_after_ignored_when_policy_says_so(self):
        sleeps = []
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                raise OSError("throttled")
            return "ok"

        call_with_retry(
            fn,
            RetryPolicy(
                max_attempts=3,
                jitter=0.0,
                base_delay=0.5,
                honor_retry_after=False,
            ),
            lambda e: RetryDecision(True, "x", delay_hint=9.0),
            sleep=sleeps.append,
        )
        assert sleeps == [0.5]


class TestBudget:
    def test_unbounded_never_exhausts(self):
        b = Budget(None)
        assert not b.exhausted()
        assert b.remaining() == float("inf")

    def test_draws_down_with_clock(self):
        now = [0.0]
        b = Budget(2.0, clock=lambda: now[0])
        assert b.remaining() == pytest.approx(2.0)
        now[0] = 1.5
        assert b.remaining() == pytest.approx(0.5)
        now[0] = 2.5
        assert b.exhausted()


class TestClassifiers:
    @staticmethod
    def _served(code, retry_after=None):
        from spark_examples_tpu.genomics.service import _ServedHttpError

        err = IOError(f"/x: HTTP {code}")
        err.__cause__ = _ServedHttpError(code, "x", retry_after)
        return err

    def test_http_transport_error_retries(self):
        assert classify_http(IOError("connection reset")).retryable

    def test_http_infrastructural_statuses_retry(self):
        for code in (429, 502, 503, 504):
            d = classify_http(self._served(code))
            assert d.retryable, code

    def test_http_retry_after_travels_on_the_decision(self):
        d = classify_http(self._served(503, retry_after=7.0))
        assert d.delay_hint == 7.0

    def test_http_application_statuses_do_not_retry(self):
        # 500 included: the genomics service maps deterministic source
        # errors to 500, and a bad shard re-requested stays bad.
        for code in (400, 401, 404, 500):
            assert not classify_http(self._served(code)).retryable, code

    def test_http_circuit_open_is_not_retryable(self):
        assert not classify_http(CircuitOpenError("e", 1.0)).retryable

    def test_oauth_5xx_and_transport_retry_4xx_denials_do_not(self):
        from urllib.error import HTTPError, URLError

        def http_error(code):
            return HTTPError("http://t", code, "x", {}, None)

        assert classify_oauth(http_error(500)).retryable
        assert classify_oauth(http_error(503)).retryable
        assert classify_oauth(http_error(429)).retryable
        assert not classify_oauth(http_error(400)).retryable
        assert not classify_oauth(http_error(401)).retryable
        assert classify_oauth(URLError("refused")).retryable
        assert classify_oauth(OSError("reset")).retryable

    def test_grpc_codes(self):
        grpc = pytest.importorskip("grpc")

        class Fake(Exception):
            def __init__(self, code):
                self._code = code

            def code(self):
                return self._code

        assert classify_grpc(Fake(grpc.StatusCode.UNAVAILABLE)).retryable
        assert classify_grpc(
            Fake(grpc.StatusCode.DEADLINE_EXCEEDED)
        ).retryable
        assert not classify_grpc(
            Fake(grpc.StatusCode.UNAUTHENTICATED)
        ).retryable
        assert not classify_grpc(Fake(grpc.StatusCode.NOT_FOUND)).retryable
        assert not classify_grpc(
            Fake(grpc.StatusCode.INVALID_ARGUMENT)
        ).retryable

    def test_ingest_io_and_wire_corruption_retry(self):
        assert classify_ingest(IOError("stream aborted")).retryable
        assert classify_ingest(
            json.JSONDecodeError("bad", "doc", 0)
        ).retryable
        assert not classify_ingest(ValueError("shape")).retryable


# -- unit: circuit breaker ----------------------------------------------------


class TestCircuitBreaker:
    @staticmethod
    def _breaker(**kw):
        now = [0.0]
        kw.setdefault("failure_threshold", 3)
        kw.setdefault("cooldown_s", 10.0)
        b = CircuitBreaker("test-endpoint", clock=lambda: now[0], **kw)
        return b, now

    def test_opens_after_threshold_and_sheds(self):
        b, _ = self._breaker()
        for _ in range(3):
            b.before_call()
            b.record_failure()
        assert b.state == "open"
        with pytest.raises(CircuitOpenError):
            b.before_call()

    def test_success_resets_failure_count(self):
        b, _ = self._breaker()
        for _ in range(2):
            b.before_call()
            b.record_failure()
        b.before_call()
        b.record_success()
        for _ in range(2):
            b.before_call()
            b.record_failure()
        assert b.state == "closed"

    def test_half_open_probe_closes_on_success(self):
        b, now = self._breaker()
        for _ in range(3):
            b.record_failure()
        now[0] = 11.0  # past the cooldown: the next call is the probe
        b.before_call()
        assert b.state == "half_open"
        b.record_success()
        assert b.state == "closed"
        b.before_call()  # closed again: calls pass freely

    def test_half_open_probe_reopens_on_failure(self):
        b, now = self._breaker()
        for _ in range(3):
            b.record_failure()
        now[0] = 11.0
        b.before_call()
        b.record_failure()
        assert b.state == "open"
        with pytest.raises(CircuitOpenError):
            b.before_call()  # cooldown re-armed from t=11
        now[0] = 22.0
        b.before_call()  # next probe window
        assert b.state == "half_open"

    def test_half_open_concurrent_probes_bounded(self):
        b, now = self._breaker(half_open_probes=1)
        for _ in range(3):
            b.record_failure()
        now[0] = 11.0
        b.before_call()  # the one admitted probe
        with pytest.raises(CircuitOpenError):
            b.before_call()  # a second concurrent probe sheds

    def test_half_open_probe_answered_by_application_error_closes(self):
        """A non-retryable failure means the endpoint ANSWERED: a
        half-open probe that gets a served 404 must close the circuit
        (transport is alive), never leak the probe slot and wedge the
        breaker half-open forever."""
        b, now = self._breaker()
        for _ in range(3):
            b.record_failure()
        now[0] = 11.0

        calls = []

        def fn():
            calls.append(1)
            raise ValueError("served application error")

        with pytest.raises(ValueError):
            call_with_retry(
                fn,
                RetryPolicy(max_attempts=3, base_delay=0.0),
                classify_ingest,  # ValueError → non-retryable
                breaker=b,
                sleep=lambda s: None,
            )
        assert len(calls) == 1
        assert b.state == "closed"
        b.before_call()  # traffic flows again

    def test_half_open_probe_outcomes_are_observable(self):
        """Probe admission/release/verdicts land on the metrics surface
        (breaker_probe_total{endpoint,outcome}) and the timeline —
        without them, shed-vs-probe behavior is invisible and a wedged
        half-open breaker looks exactly like a probing one."""
        with TelemetrySession() as session:
            b, now = self._breaker(half_open_probes=1)
            for _ in range(3):
                b.record_failure()
            now[0] = 11.0
            b.before_call()   # admitted
            b.release_probe()  # released (abandoned, no verdict)
            b.before_call()   # admitted again
            b.record_failure()  # failure → re-open
            now[0] = 22.0
            b.before_call()   # admitted
            b.record_success()  # success → closed
            counter = session.registry.counter("breaker_probe_total")

            def n(outcome):
                return counter.labels(
                    endpoint="test-endpoint", outcome=outcome
                ).value

            assert n("admitted") == 3
            assert n("released") == 1
            assert n("failure") == 1
            assert n("success") == 1
            probes = [
                e
                for e in session.tracer.to_chrome()["traceEvents"]
                if e["name"] == "breaker_probe"
            ]
            assert [p["args"]["outcome"] for p in probes] == [
                "admitted",
                "released",
                "admitted",
                "failure",
                "admitted",
                "success",
            ]

    def test_release_probe_returns_the_slot_without_verdict(self):
        """An abandoned probe (no success/failure recorded) gives its
        slot back so the next caller can probe."""
        b, now = self._breaker(half_open_probes=1)
        for _ in range(3):
            b.record_failure()
        now[0] = 11.0
        b.before_call()  # probe admitted, then abandoned
        b.release_probe()
        b.before_call()  # the slot is free again (no shed)
        assert b.state == "half_open"

    def test_breaker_set_keys_per_endpoint(self):
        s = BreakerSet("http:", failure_threshold=1, cooldown_s=60.0)
        s.get("/variants").record_failure()
        assert s.get("/variants").state == "open"
        assert s.get("/callsets").state == "closed"
        assert s.states() == {"/variants": "open", "/callsets": "closed"}


# -- unit: fault plane --------------------------------------------------------


class TestFaultPlan:
    def test_inject_is_noop_without_plan(self):
        faults.clear_plan()
        faults.inject("transport.http.request", key="/variants")  # no raise

    def test_error_rule_fires_once_then_exhausts(self):
        plan = FaultPlan(
            rules=[FaultRule(site="a.b", kind="error", times=1)]
        )
        with pytest.raises(InjectedFault):
            plan.inject("a.b")
        plan.inject("a.b")  # exhausted: no-op
        assert plan.fired_total == 1

    def test_site_glob_and_key_match(self):
        plan = FaultPlan(
            rules=[
                FaultRule(
                    site="transport.*",
                    kind="error",
                    times=None,
                    match="shard-7",
                )
            ]
        )
        plan.inject("transport.http.request", key="shard-3")  # no match
        with pytest.raises(InjectedFault):
            plan.inject("transport.grpc.stream", key="shard-7")
        plan.inject("ingest.shard", key="shard-7")  # site mismatch
        assert plan.fired_total == 1

    def test_after_skips_early_hits(self):
        plan = FaultPlan(
            rules=[FaultRule(site="s", kind="error", times=1, after=2)]
        )
        plan.inject("s")
        plan.inject("s")
        with pytest.raises(InjectedFault):
            plan.inject("s")

    def test_probability_draws_are_deterministic_per_seed(self):
        def pattern(seed):
            plan = FaultPlan(
                seed=seed,
                rules=[
                    FaultRule(
                        site="s", kind="error", probability=0.5, times=None
                    )
                ],
            )
            out = []
            for _ in range(64):
                try:
                    plan.inject("s")
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        a, b, c = pattern(1), pattern(1), pattern(2)
        assert a == b  # same seed, same decisions
        assert a != c  # a different seed decides differently
        assert 8 < sum(a) < 56  # p=0.5 actually mixes

    def test_json_spec_roundtrip_and_env_activation(self, tmp_path):
        spec = {
            "seed": 3,
            "rules": [
                {"site": "ingest.shard", "kind": "stall", "stall_s": 0.01}
            ],
        }
        inline = FaultPlan.from_spec(json.dumps(spec))
        assert inline.seed == 3 and inline.to_dict()["rules"][0][
            "site"
        ] == "ingest.shard"
        path = tmp_path / "plan.json"
        path.write_text(json.dumps(spec))
        from_file = FaultPlan.from_spec(str(path))
        assert from_file.to_dict()["seed"] == 3
        env = {resilience.FAULT_PLAN_ENV: json.dumps(spec)}
        from_env = faults.plan_from_env(env)
        assert from_env is not None and from_env.seed == 3
        assert faults.plan_from_env({}) is None

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="kind"):
            FaultRule(site="s", kind="explode")

    def test_wrap_lines_truncate_corrupt_stall_error(self):
        lines = [b"l0", b"l1", b"l2"]

        def run(rule):
            plan = FaultPlan(rules=[rule])
            return list(
                faults.wrap_lines("st", iter(lines), plan=plan)
            )

        assert run(
            FaultRule(site="st", kind="truncate", at_line=1)
        ) == [b"l0"]
        corrupted = run(FaultRule(site="st", kind="corrupt", at_line=1))
        assert corrupted[0] == b"l0" and corrupted[2] == b"l2"
        assert corrupted[1] != b"l1" and b"corrupt" in corrupted[1]
        assert run(
            FaultRule(site="st", kind="stall", at_line=0, stall_s=0.0)
        ) == lines
        with pytest.raises(InjectedFault):
            run(FaultRule(site="st", kind="error", at_line=2))

    def test_active_plan_scopes_and_restores(self):
        plan = FaultPlan(rules=[FaultRule(site="s", kind="error")])
        assert faults.current_plan() is None
        with faults.active_plan(plan):
            assert faults.current_plan() is plan
            with pytest.raises(InjectedFault):
                faults.inject("s")
        assert faults.current_plan() is None


# -- integration: HTTP tier ---------------------------------------------------


class _ScriptedHttpServer:
    """Serves /callsets: the first ``fail_first`` requests get ``code``
    (with optional Retry-After), the rest succeed with an empty list."""

    def __init__(self, fail_first=2, code=503, retry_after="0"):
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                srv.requests.append(self.path)
                if len(srv.requests) <= srv.fail_first:
                    body = b"try later"
                    self.send_response(srv.code)
                    if srv.retry_after is not None:
                        self.send_header("Retry-After", srv.retry_after)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                body = b"[]\n"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.fail_first = fail_first
        self.code = code
        self.retry_after = retry_after
        self.requests = []
        self._server = HTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self._server.server_port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class TestHttpRetryIntegration:
    def test_503_with_retry_after_is_retried_to_success(self):
        srv = _ScriptedHttpServer(fail_first=2, code=503, retry_after="0")
        try:
            http = HttpVariantSource(
                srv.url,
                retry_policy=RetryPolicy(
                    max_attempts=4, base_delay=0.01, jitter=0.0
                ),
            )
            assert http.list_callsets("") == []
            assert len(srv.requests) == 3
            # A retried-to-success request is NOT an unsuccessful
            # response — the accumulator counts outcomes, not attempts.
            assert http.stats.unsuccessful_responses == 0
            assert http.stats.io_exceptions == 0
        finally:
            srv.stop()

    def test_exhausted_retries_surface_the_served_status(self):
        srv = _ScriptedHttpServer(fail_first=99, code=503)
        try:
            http = HttpVariantSource(
                srv.url,
                retry_policy=RetryPolicy(
                    max_attempts=3, base_delay=0.01, jitter=0.0
                ),
            )
            with pytest.raises(IOError, match="503"):
                http.list_callsets("")
            assert len(srv.requests) == 3
            assert http.stats.unsuccessful_responses == 1
        finally:
            srv.stop()

    def test_404_is_an_answer_not_a_retry(self):
        srv = _ScriptedHttpServer(fail_first=99, code=404)
        try:
            http = HttpVariantSource(srv.url)
            with pytest.raises(IOError, match="404"):
                http.list_callsets("")
            assert len(srv.requests) == 1
        finally:
            srv.stop()

    def test_breaker_opens_and_sheds_against_dead_endpoint(self):
        http = HttpVariantSource(
            "http://127.0.0.1:1",  # nothing listens here
            timeout=2,
            retry_policy=RetryPolicy(
                max_attempts=2, base_delay=0.01, jitter=0.0
            ),
            breakers=BreakerSet(
                "t:", failure_threshold=2, cooldown_s=60.0
            ),
        )
        with pytest.raises(IOError):
            http.list_callsets("")  # 2 attempts = 2 failures → open
        with pytest.raises(CircuitOpenError):
            http.list_callsets("")  # shed instantly, no socket touched
        assert http.stats.io_exceptions == 2


# -- integration: gRPC tier ---------------------------------------------------


grpc_missing = False
try:
    import grpc  # noqa: F401
except ImportError:  # pragma: no cover - grpcio is in the test image
    grpc_missing = True


@pytest.mark.skipif(grpc_missing, reason="grpcio not installed")
class TestGrpcResilience:
    def test_idle_timeout_cancels_wedged_stream(self):
        from spark_examples_tpu.genomics.grpc_transport import (
            GrpcGenomicsServer,
            GrpcVariantSource,
        )

        inner = synthetic_cohort(4, 10, seed=1)
        release = threading.Event()

        class WedgesMidStream:
            def list_callsets(self, vsid):
                return inner.list_callsets(vsid)

            def stream_variants(self, vsid, shard):
                it = inner.stream_variants(vsid, shard)
                yield next(it)
                # Connected but delivering nothing: keepalive stays
                # happy, only the per-read idle deadline can see this.
                release.wait(30)

            def stream_reads(self, rgsid, shard):
                return inner.stream_reads(rgsid, shard)

        server = GrpcGenomicsServer(WedgesMidStream()).start()
        client = GrpcVariantSource(
            f"grpc://127.0.0.1:{server.port}", idle_timeout=0.5
        )
        shard = shards_for_references(REFS, 100_000)[0]
        try:
            with pytest.raises(IOError, match="wedged"):
                list(client.stream_variants("", shard))
            assert client.stats.io_exceptions == 1
        finally:
            release.set()
            client.close()
            server.stop()

    def test_actively_delivering_stream_never_trips_idle(self):
        from spark_examples_tpu.genomics.grpc_transport import (
            GrpcGenomicsServer,
            GrpcVariantSource,
        )

        inner = synthetic_cohort(4, 20, seed=1)

        class SlowButFlowing:
            def list_callsets(self, vsid):
                return inner.list_callsets(vsid)

            def stream_variants(self, vsid, shard):
                import time

                for v in inner.stream_variants(vsid, shard):
                    time.sleep(0.05)  # slower than the idle budget? no:
                    yield v  # each message resets the idle clock

            def stream_reads(self, rgsid, shard):
                return inner.stream_reads(rgsid, shard)

        server = GrpcGenomicsServer(SlowButFlowing()).start()
        client = GrpcVariantSource(
            f"grpc://127.0.0.1:{server.port}", idle_timeout=0.5
        )
        shard = shards_for_references(REFS, 100_000)[0]
        try:
            got = list(client.stream_variants("", shard))
            assert len(got) == 20
            assert client.stats.io_exceptions == 0
        finally:
            client.close()
            server.stop()

    def test_injected_truncation_is_loud_not_silent(self):
        """gRPC has no end sentinel, so a truncate rule must surface as
        an error — a silent early end would drop records undetectably,
        which no REAL gRPC failure can do (truncation is a status)."""
        from spark_examples_tpu.genomics.grpc_transport import (
            GrpcGenomicsServer,
            GrpcVariantSource,
        )

        inner = synthetic_cohort(4, 10, seed=1)
        server = GrpcGenomicsServer(inner).start()
        client = GrpcVariantSource(f"grpc://127.0.0.1:{server.port}")
        shard = shards_for_references(REFS, 100_000)[0]
        plan = FaultPlan(
            rules=[
                FaultRule(
                    site="transport.grpc.stream",
                    kind="truncate",
                    times=1,
                    at_line=2,
                )
            ]
        )
        try:
            with faults.active_plan(plan):
                with pytest.raises(IOError, match="truncate"):
                    list(client.stream_variants("", shard))
            assert client.stats.io_exceptions == 1
            # Fault cleared: the idempotent re-request serves all 10.
            assert len(list(client.stream_variants("", shard))) == 10
        finally:
            client.close()
            server.stop()

    def test_stream_start_retry_respects_deadline_budget(self):
        """--rpc-retry-deadline bounds the stream path exactly like the
        unary path: a zero budget means no retries despite attempts
        remaining."""
        from spark_examples_tpu.genomics.grpc_transport import (
            GrpcVariantSource,
        )

        client = GrpcVariantSource(
            "grpc://127.0.0.1:1",
            timeout=2,
            retry_policy=RetryPolicy(
                max_attempts=5,
                base_delay=0.01,
                jitter=0.0,
                deadline=0.0,
            ),
        )
        try:
            with TelemetrySession() as session:
                with pytest.raises(IOError):
                    list(client.stream_variants("", shards_for_references(REFS, 100_000)[0]))
                counters = session.registry.snapshot()["counters"]
            retried = [
                v
                for k, v in counters.items()
                if k.startswith("genomics_rpc_retries_total")
            ]
            assert sum(retried) == 0  # budget dry → last error surfaced
        finally:
            client.close()

    def test_bind_failure_raises_instead_of_port_zero(self):
        import socket

        from spark_examples_tpu.genomics.grpc_transport import (
            GrpcGenomicsServer,
        )

        sock = socket.socket()
        try:
            sock.bind(("127.0.0.1", 0))
            port = sock.getsockname()[1]
            with pytest.raises(IOError, match="bind"):
                GrpcGenomicsServer(synthetic_cohort(2, 4), port=port)
        finally:
            sock.close()

    def test_unary_retries_are_observable(self):
        from spark_examples_tpu.genomics.grpc_transport import (
            GrpcVariantSource,
        )

        client = GrpcVariantSource(
            "grpc://127.0.0.1:1",
            timeout=2,
            retry_policy=RetryPolicy(
                max_attempts=3, base_delay=0.01, jitter=0.0
            ),
        )
        try:
            with TelemetrySession() as session:
                with pytest.raises(IOError):
                    client.list_callsets("")
                counters = session.registry.snapshot()["counters"]
            retried = [
                v
                for k, v in counters.items()
                if k.startswith("genomics_rpc_retries_total")
                and 'transport="grpc"' in k
            ]
            assert sum(retried) == 2  # 3 attempts = 2 retries
            assert client.stats.io_exceptions == 1  # counted once
        finally:
            client.close()


# -- integration: oauth classification ---------------------------------------


class _FlakyTokenEndpoint:
    """Token endpoint that fails the first ``fail_first`` requests with
    ``code`` and then mints a token; mode 'denial' always answers the
    RFC 6749 invalid_grant shape."""

    def __init__(self, fail_first=1, code=500, mode="flaky"):
        ep = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                ep.requests.append(self.path)
                if ep.mode == "denial":
                    body = json.dumps(
                        {
                            "error": "invalid_grant",
                            "error_description": "token revoked",
                        }
                    ).encode()
                    self.send_response(400)
                elif len(ep.requests) <= ep.fail_first:
                    body = b"upstream blew up"
                    self.send_response(ep.code)
                else:
                    body = json.dumps(
                        {"access_token": "minted", "token_type": "Bearer"}
                    ).encode()
                    self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.fail_first = fail_first
        self.code = code
        self.mode = mode
        self.requests = []
        self._server = HTTPServer(("127.0.0.1", 0), Handler)
        self.uri = f"http://127.0.0.1:{self._server.server_port}/token"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class TestOauthRetryClassification:
    def test_transient_5xx_retries_to_a_token(self):
        from spark_examples_tpu.genomics.oauth import exchange_refresh_token

        ep = _FlakyTokenEndpoint(fail_first=1, code=500)
        try:
            token = exchange_refresh_token(
                "cid",
                "csec",
                "rtok",
                token_uri=ep.uri,
                retry_policy=RetryPolicy(
                    max_attempts=3, base_delay=0.01, jitter=0.0
                ),
            )
            assert token == "minted"
            assert len(ep.requests) == 2
        finally:
            ep.stop()

    def test_denial_4xx_surfaces_immediately_without_retry(self):
        from spark_examples_tpu.genomics.auth import AuthError
        from spark_examples_tpu.genomics.oauth import exchange_refresh_token

        ep = _FlakyTokenEndpoint(mode="denial")
        try:
            with pytest.raises(AuthError, match="invalid_grant"):
                exchange_refresh_token(
                    "cid",
                    "csec",
                    "rtok",
                    token_uri=ep.uri,
                    retry_policy=RetryPolicy(
                        max_attempts=5, base_delay=0.01
                    ),
                )
            assert len(ep.requests) == 1  # a revoked token never un-revokes
        finally:
            ep.stop()

    def test_unreachable_endpoint_exhausts_and_wraps_as_autherror(self):
        from spark_examples_tpu.genomics.auth import AuthError
        from spark_examples_tpu.genomics.oauth import exchange_refresh_token

        with pytest.raises(AuthError, match="cannot reach"):
            exchange_refresh_token(
                "cid",
                "csec",
                "rtok",
                token_uri="http://127.0.0.1:1/token",
                retry_policy=RetryPolicy(
                    max_attempts=2, base_delay=0.01, jitter=0.0
                ),
            )


# -- integration: watchdog fail-stop ------------------------------------------


class TestWatchdogFailStop:
    def test_armed_phase_overrun_exits_77_with_flushed_telemetry(
        self, tmp_path
    ):
        """The exit-77 path end to end: a stalled 'collective' is shot
        by the watchdog, the process dies with the distinctive code, the
        diagnostic names the phase, and the telemetry flush leaves a
        valid trace carrying the watchdog instant."""
        trace = tmp_path / "wd.trace.json"
        script = f"""
import time
from spark_examples_tpu.obs.session import TelemetrySession
from spark_examples_tpu.utils.watchdog import CollectiveWatchdog

with TelemetrySession(trace_out={str(trace)!r}):
    wd = CollectiveWatchdog(0.3)
    with wd.armed("chaos test phase"):
        time.sleep(30)
"""
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 77
        assert "chaos test phase" in proc.stderr
        assert "FATAL" in proc.stderr
        assert validate.validate_trace(str(trace)) == []
        events = json.loads(trace.read_text())["traceEvents"]
        assert any(
            e["name"] == "collective_watchdog_fired" for e in events
        )

    def test_disarmed_watchdog_never_fires(self):
        from spark_examples_tpu.utils.watchdog import CollectiveWatchdog

        wd = CollectiveWatchdog(None)
        with wd.armed("anything"):
            pass  # no timer, no exit

    def test_exit77_runs_pre_exit_flush_hooks(self, tmp_path):
        """Regression (round 6): the exit-77 path flushed telemetry but
        no durable state. Now every registered flush hook — the job
        journal routes itself through one — runs before ``os._exit``,
        so resume-after-77 sees the same journal a clean shutdown
        leaves."""
        sentinel = tmp_path / "hook-ran"
        journal_dir = tmp_path / "journal"
        script = f"""
import time
from spark_examples_tpu.serving import JobJournal
from spark_examples_tpu.utils.watchdog import (
    CollectiveWatchdog,
    register_flush_hook,
)

journal = JobJournal({str(journal_dir)!r})
journal.append({{"e": "submit", "id": "wd-job", "seq": 1}})
register_flush_hook(
    "test-sentinel",
    lambda: open({str(sentinel)!r}, "w").write("flushed"),
)
wd = CollectiveWatchdog(0.3)
with wd.armed("serving flush phase"):
    time.sleep(30)
"""
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 77
        assert sentinel.read_text() == "flushed"
        from spark_examples_tpu.serving import JobJournal

        events = list(JobJournal.replay_events(str(journal_dir)))
        assert [e["id"] for e in events] == ["wd-job"]

    def test_flush_hook_registry_is_best_effort(self):
        from spark_examples_tpu.utils import watchdog

        ran = []
        watchdog.register_flush_hook(
            "t-bad", lambda: (_ for _ in ()).throw(RuntimeError("x"))
        )
        watchdog.register_flush_hook("t-good", lambda: ran.append(1))
        try:
            watchdog.run_flush_hooks()  # the bad hook must not block
            assert ran == [1]
        finally:
            watchdog.unregister_flush_hook("t-bad")
            watchdog.unregister_flush_hook("t-good")

    def test_flush_hooks_are_deadline_bounded(self):
        """A flush wedged in the kernel (fsync on hung storage — the
        very stall that fired the watchdog) must not turn fail-stop
        into a permanent hang: the hook pass runs on a daemon thread
        joined with a deadline."""
        import time as _time

        from spark_examples_tpu.utils import watchdog

        gate = threading.Event()
        watchdog.register_flush_hook("t-wedged", gate.wait)
        try:
            t0 = _time.monotonic()
            watchdog.run_flush_hooks(deadline_s=0.2)
            assert _time.monotonic() - t0 < 5.0
        finally:
            gate.set()  # let the daemon thread die
            watchdog.unregister_flush_hook("t-wedged")


# -- integration: fixture fault plane + mirror TOCTOU -------------------------


class TestFixtureFaultPlane:
    def test_fail_once_surface_preserved_on_the_plan(self):
        src = synthetic_cohort(4, 10, seed=1)
        shard = shards_for_references(REFS, 100_000)[0]
        src._fail_once.add(shard)
        with pytest.raises(IOError, match="injected stream failure"):
            list(src.stream_variants("", shard))
        assert src.stats.io_exceptions == 1
        assert len(list(src.stream_variants("", shard))) == 10
        assert src.faults.fired_total == 1

    def test_fail_shards_constructor_arg(self):
        from spark_examples_tpu.genomics.sources import FixtureSource

        shard = shards_for_references(REFS, 100_000)[0]
        src = FixtureSource(variants=[], fail_shards=[shard])
        with pytest.raises(IOError):
            list(src.stream_variants("", shard))
        assert list(src.stream_variants("", shard)) == []


class TestVsidLineGuard:
    def test_nested_variant_set_id_key_falls_back_to_parse(self):
        from spark_examples_tpu.genomics.sources import _line_vsid_matches

        # The only "variant_set_id" sits INSIDE calls — the top-level
        # record has none, so the zero-parse path must treat it as a
        # wildcard (match), exactly like the parsed path.
        line = (
            b'{"reference_name": "17", "start": 5, '
            b'"calls": [{"variant_set_id": "other"}]}'
        )
        assert _line_vsid_matches(line, "vs-1")
        # Top-level id still filters exactly.
        top = (
            b'{"reference_name": "17", "variant_set_id": "vs-2", '
            b'"start": 5, "calls": []}'
        )
        assert not _line_vsid_matches(top, "vs-1")
        assert _line_vsid_matches(top, "vs-2")

    def test_matches_parsed_path_on_jsonl_source(self, tmp_path):
        root = tmp_path / "c"
        os.makedirs(root)
        recs = [
            # Nested decoy only — top level has no variant_set_id.
            {
                "reference_name": "17",
                "start": 41200001,
                "end": 41200002,
                "calls": [],
                "info": {"variant_set_id": ["decoy"]},
            },
            {
                "reference_name": "17",
                "start": 41200005,
                "end": 41200006,
                "variant_set_id": "vs-1",
                "calls": [],
            },
        ]
        with open(root / "variants.jsonl", "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        with open(root / "callsets.json", "w") as f:
            f.write("[]")
        src = JsonlSource(str(root))
        from spark_examples_tpu.genomics.shards import Shard

        shard = Shard("17", 41200000, 41210000)
        raw = list(src.stream_variant_lines("vs-1", shard))
        parsed = list(src.stream_variants("vs-1", shard))
        # Both paths serve both records: the decoy's nested key is not
        # a top-level filter, and absent top-level id = wildcard.
        assert len(raw) == len(parsed) == 2


class TestLightMirrorUpgradeReverify:
    def test_mid_upgrade_cohort_swap_discards_and_raises(self, tmp_path):
        src = synthetic_cohort(8, 60, seed=9)
        root = str(tmp_path / "srv")
        src.dump(root)
        url_cache = str(tmp_path / "cache")
        backing = JsonlSource(root)
        server = GenomicsServiceServer(backing).start()
        shard = shards_for_references(REFS, 20_000)[0]
        try:
            light = HttpVariantSource(
                f"http://127.0.0.1:{server.port}",
                cache_dir=url_cache,
                cold_stream=False,
                mirror_mode="light",
            )
            indexes = {
                c.id: i
                for i, c in enumerate(
                    light.list_callsets(DEFAULT_VARIANT_SET_ID)
                )
            }
            list(
                light.stream_carrying(
                    DEFAULT_VARIANT_SET_ID, shard, indexes, None
                )
            )
        finally:
            server.stop()
        mirror_root = os.path.join(
            url_cache,
            [d for d in os.listdir(url_cache) if d.startswith("cohort-")][
                0
            ],
        )
        old_ident = backing.cohort_identity()

        class SwapsMidUpgrade:
            """Identity answers the OLD cohort until the upgrade files
            land, then the NEW one — the TOCTOU window."""

            def __init__(self):
                self.identity_calls = 0

            def cohort_identity(self):
                self.identity_calls += 1
                return (
                    old_ident if self.identity_calls == 1 else "swapped"
                )

            def __getattr__(self, name):
                return getattr(backing, name)

        server2 = GenomicsServiceServer(SwapsMidUpgrade()).start()
        try:
            full = HttpVariantSource(
                f"http://127.0.0.1:{server2.port}",
                cache_dir=url_cache,
                cold_stream=False,
                mirror_mode="full",
            )
            with pytest.raises(IOError, match="upgrading"):
                list(
                    full.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
                )
        finally:
            server2.stop()
        # The upgraded files were discarded: the mirror is back to its
        # light state (sidecar intact), not a mixed-cohort husk.
        assert not os.path.exists(
            os.path.join(mirror_root, "variants.jsonl")
        )
        assert os.path.exists(os.path.join(mirror_root, ".complete"))


# -- the chaos harness --------------------------------------------------------


def _chaos_conf(shard_retries=4, **kw):
    kw.setdefault("variant_set_ids", [DEFAULT_VARIANT_SET_ID])
    kw.setdefault("references", REFS)
    kw.setdefault("bases_per_partition", 20_000)
    kw.setdefault("block_variants", 16)
    kw.setdefault("ingest_workers", 2)
    return PcaConfig(shard_retries=shard_retries, **kw)


def _coords(result):
    return np.array([[pc1, pc2] for _, pc1, pc2 in result])


@pytest.fixture(scope="module")
def chaos_cohort(tmp_path_factory):
    """One dumped cohort + its fault-free pipeline result, shared by
    every chaos scenario (the baseline all runs must match exactly)."""
    root = str(tmp_path_factory.mktemp("cohort") / "c")
    synthetic_cohort(10, 80, seed=3).dump(root)
    baseline = VariantsPcaDriver(
        _chaos_conf(shard_retries=1), JsonlSource(root)
    ).run()
    return root, baseline


class TestChaosHarness:
    """Acceptance: the full CPU pipeline under seeded fault plans is
    numerically identical to the fault-free run, and the artifacts show
    the injected faults and breaker transitions."""

    def test_transport_fault_plan_is_result_identical(
        self, chaos_cohort, tmp_path
    ):
        root, baseline = chaos_cohort
        server = GenomicsServiceServer(JsonlSource(root)).start()
        plan = FaultPlan(
            seed=11,
            rules=[
                FaultRule(
                    site="transport.http.request", kind="error", times=2
                ),
                FaultRule(
                    site="transport.http.stream",
                    kind="truncate",
                    times=1,
                    at_line=1,
                ),
                FaultRule(
                    site="transport.http.stream",
                    kind="corrupt",
                    times=1,
                    at_line=0,
                ),
                FaultRule(
                    site="transport.http.stream",
                    kind="stall",
                    times=1,
                    stall_s=0.01,
                ),
            ],
        )
        trace = str(tmp_path / "chaos.trace.json")
        metrics = str(tmp_path / "chaos.prom")
        manifest = str(tmp_path / "chaos.manifest.json")
        try:
            with TelemetrySession(
                trace_out=trace, metrics_out=metrics, manifest_out=manifest
            ):
                with faults.active_plan(plan):
                    # wire_frames=False: this scenario exercises the
                    # JSON record tier's framing seams
                    # (transport.http.stream); the binary frame tier
                    # has its own chaos coverage in
                    # tests/test_wire_format.py::TestFrameFaults.
                    http = HttpVariantSource(
                        f"http://127.0.0.1:{server.port}",
                        retry_policy=RetryPolicy(
                            max_attempts=4, base_delay=0.01, jitter=0.0
                        ),
                        wire_frames=False,
                    )
                    result = VariantsPcaDriver(
                        _chaos_conf(shard_retries=4), http
                    ).run()
                # Same artifacts also record breaker behavior: a dead
                # endpoint trips its breaker open, then sheds.
                dead = HttpVariantSource(
                    "http://127.0.0.1:1",
                    timeout=2,
                    retry_policy=RetryPolicy(
                        max_attempts=2, base_delay=0.01, jitter=0.0
                    ),
                    breakers=BreakerSet(
                        "chaos:", failure_threshold=2, cooldown_s=60.0
                    ),
                )
                with pytest.raises(IOError):
                    dead.list_callsets("")
                with pytest.raises(CircuitOpenError):
                    dead.list_callsets("")
        finally:
            server.stop()
        # Numerically identical: same shard requests after retries, same
        # accumulation order, same eigensolver input → same bytes out.
        assert [r[0] for r in result] == [r[0] for r in baseline]
        np.testing.assert_array_equal(
            _coords(result), _coords(baseline)
        )
        # Every fault fired, and the run still converged.
        assert plan.fired_total == 5
        # Artifacts are schema-valid and carry the failure story.
        assert validate.validate_trace(trace) == []
        assert validate.validate_metrics(metrics) == []
        assert validate.validate_manifest(manifest) == []
        events = json.loads(open(trace).read())["traceEvents"]
        names = {e["name"] for e in events}
        assert "fault_injected" in names
        assert "retry_backoff" in names
        assert "breaker_transition" in names
        prom = open(metrics).read()
        assert "resilience_faults_injected_total" in prom
        assert "resilience_breaker_transitions_total" in prom
        assert "genomics_rpc_retries_total" in prom

    def test_worker_death_and_slow_lanes_result_identical(
        self, chaos_cohort
    ):
        root, baseline = chaos_cohort
        plan = FaultPlan(
            seed=23,
            rules=[
                FaultRule(site="ingest.shard", kind="error", times=2),
                FaultRule(
                    site="ingest.shard",
                    kind="stall",
                    times=2,
                    stall_s=0.01,
                ),
            ],
        )
        with faults.active_plan(plan):
            result = VariantsPcaDriver(
                _chaos_conf(shard_retries=4), JsonlSource(root)
            ).run()
        assert plan.fired_total == 4
        np.testing.assert_array_equal(_coords(result), _coords(baseline))

    def test_block_builder_death_retried_bit_identical(
        self, chaos_cohort
    ):
        """``ingest.build`` seam: a packed-block builder worker dying
        mid-block is retried per the shard-retry policy; the rebuilt
        block is byte-identical (the build is a pure function of its
        window), so coordinates match the fault-free run exactly and no
        block is ever silently dropped."""
        root, baseline = chaos_cohort
        plan = FaultPlan(
            seed=29,
            rules=[FaultRule(site="ingest.build", kind="error", times=2)],
        )
        with faults.active_plan(plan):
            result = VariantsPcaDriver(
                _chaos_conf(shard_retries=3), JsonlSource(root)
            ).run()
        assert plan.fired_total == 2
        assert {f.site for f in plan.injected} == {"ingest.build"}
        np.testing.assert_array_equal(_coords(result), _coords(baseline))

    def test_block_builder_death_without_retries_fails_loudly(
        self, chaos_cohort
    ):
        """With retries off the builder death must SURFACE (fail fast),
        never drop the block and emit a silently-wrong G."""
        root, _ = chaos_cohort
        plan = FaultPlan(
            seed=5,
            rules=[FaultRule(site="ingest.build", kind="error", times=1)],
        )
        with faults.active_plan(plan):
            with pytest.raises(IOError, match="ingest.build"):
                VariantsPcaDriver(
                    _chaos_conf(shard_retries=1), JsonlSource(root)
                ).run()
        assert plan.fired_total == 1

    def test_torn_checkpoint_writes_and_resume_identical(
        self, chaos_cohort, tmp_path
    ):
        root, baseline = chaos_cohort
        ckdir = str(tmp_path / "ck")
        plan = FaultPlan(
            seed=31,
            rules=[
                FaultRule(
                    site="checkpoint.snapshot_write",
                    kind="torn",
                    times=None,
                )
            ],
        )
        conf = _chaos_conf(
            shard_retries=1, checkpoint_dir=ckdir, checkpoint_every=2
        )
        with faults.active_plan(plan):
            result = VariantsPcaDriver(conf, JsonlSource(root)).run()
        # Every snapshot written this run was torn — the in-memory
        # accumulator is unaffected, results identical.
        assert plan.fired_total >= 1
        np.testing.assert_array_equal(_coords(result), _coords(baseline))
        # Resume over the torn snapshot: the tolerant loader discards it
        # with a warning and re-ingests — identical again, not a crash.
        resumed = VariantsPcaDriver(conf, JsonlSource(root)).run()
        np.testing.assert_array_equal(
            _coords(resumed), _coords(baseline)
        )

    def test_torn_lane_writes_and_elastic_resume_identical(
        self, chaos_cohort, tmp_path
    ):
        root, baseline = chaos_cohort
        ckdir = str(tmp_path / "elastic-ck")
        conf = _chaos_conf(
            shard_retries=1,
            checkpoint_dir=ckdir,
            checkpoint_every=2,
            elastic_checkpoint=True,
        )
        plan = FaultPlan(
            seed=47,
            rules=[
                FaultRule(
                    site="checkpoint.lane_write", kind="torn", times=1
                ),
                FaultRule(
                    site="checkpoint.lane_supersede",
                    kind="error",
                    times=1,
                ),
            ],
        )
        with faults.active_plan(plan):
            result = VariantsPcaDriver(conf, JsonlSource(root)).run()
        assert plan.fired_total == 2
        np.testing.assert_array_equal(_coords(result), _coords(baseline))
        # Resume: unreadable/stale lanes are discarded (their units
        # re-executed), the run converges to the same coordinates.
        resumed = VariantsPcaDriver(conf, JsonlSource(root)).run()
        np.testing.assert_array_equal(
            _coords(resumed), _coords(baseline)
        )

    def test_crash_after_torn_snapshot_then_resume(self, chaos_cohort, tmp_path):
        """Composed failure: a torn snapshot AND a mid-run worker death
        (no shard retries) — the run dies, resume discards the torn file
        and completes identically."""
        root, baseline = chaos_cohort
        ckdir = str(tmp_path / "ck2")
        conf = _chaos_conf(
            shard_retries=1, checkpoint_dir=ckdir, checkpoint_every=2
        )
        plan = FaultPlan(
            rules=[
                FaultRule(
                    site="checkpoint.snapshot_write", kind="torn", times=1
                ),
                FaultRule(
                    site="ingest.shard", kind="error", times=1, after=2
                ),
            ]
        )
        with faults.active_plan(plan):
            with pytest.raises(IOError):
                VariantsPcaDriver(conf, JsonlSource(root)).run()
        resumed = VariantsPcaDriver(conf, JsonlSource(root)).run()
        np.testing.assert_array_equal(
            _coords(resumed), _coords(baseline)
        )


@pytest.mark.slow
class TestChaosSoak:
    """Randomized soak: seeded random fault plans over the full served
    pipeline; every one must converge to the fault-free coordinates.
    ``CHAOS_SOAK_ITERS`` scales the fuzz (scripts/chaos_soak.sh)."""

    def test_randomized_fault_plans_converge(self, tmp_path):
        import random

        iters = int(os.environ.get("CHAOS_SOAK_ITERS", "3"))
        root = str(tmp_path / "c")
        synthetic_cohort(10, 80, seed=3).dump(root)
        baseline = VariantsPcaDriver(
            _chaos_conf(shard_retries=1), JsonlSource(root)
        ).run()
        for seed in range(iters):
            rng = random.Random(seed)
            rules = [
                FaultRule(
                    site="transport.http.request",
                    kind="error",
                    probability=0.2,
                    times=4,
                ),
                FaultRule(
                    site="transport.http.stream",
                    kind=rng.choice(["truncate", "corrupt", "error"]),
                    probability=0.25,
                    times=3,
                    at_line=rng.randint(0, 2),
                ),
                FaultRule(
                    site="ingest.shard",
                    kind="error",
                    probability=0.2,
                    times=3,
                ),
                FaultRule(
                    site="ingest.shard",
                    kind="stall",
                    probability=0.3,
                    times=3,
                    stall_s=0.01,
                ),
                FaultRule(
                    site="checkpoint.snapshot_write",
                    kind="torn",
                    probability=0.5,
                    times=None,
                ),
            ]
            plan = FaultPlan(seed=seed, rules=rules)
            ckdir = str(tmp_path / f"ck-{seed}")
            server = GenomicsServiceServer(JsonlSource(root)).start()
            try:
                with faults.active_plan(plan):
                    http = HttpVariantSource(
                        f"http://127.0.0.1:{server.port}",
                        retry_policy=RetryPolicy(
                            max_attempts=6, base_delay=0.01, jitter=0.1
                        ),
                    )
                    result = VariantsPcaDriver(
                        _chaos_conf(
                            shard_retries=6,
                            checkpoint_dir=ckdir,
                            checkpoint_every=2,
                        ),
                        http,
                    ).run()
            finally:
                server.stop()
            np.testing.assert_array_equal(
                _coords(result), _coords(baseline)
            )
            # And the resume over whatever the plan left behind:
            resumed = VariantsPcaDriver(
                _chaos_conf(
                    shard_retries=1,
                    checkpoint_dir=ckdir,
                    checkpoint_every=2,
                ),
                JsonlSource(root),
            ).run()
            np.testing.assert_array_equal(
                _coords(resumed), _coords(baseline)
            )


# -- the serving chaos scenarios ----------------------------------------------


class TestServingKillResume:
    """Deterministic service-tier chaos (the round-6 acceptance bar):
    a job killed mid-run resumes after restart bit-identically; a full
    queue sheds with Retry-After instead of queuing unboundedly; and
    per-tenant quotas hold under concurrent submission. The kill -9
    subprocess loop is the service soak
    (tests/test_serving.py::TestServiceChaosSoak, slow)."""

    @staticmethod
    def _tier(src, tmp_path=None, **kw):
        from spark_examples_tpu.serving import (
            AnalysisEngine,
            AnalysisJobTier,
        )

        kw.setdefault("workers", 0)
        if tmp_path is not None:
            kw.setdefault("journal_dir", str(tmp_path / "journal"))
        return AnalysisJobTier(
            AnalysisEngine(src), _chaos_conf(shard_retries=1), **kw
        )

    def test_kill_mid_job_then_restart_is_bit_identical(self, tmp_path):
        """The serving.job.kill seam leaves the journal exactly as a
        SIGKILL between the journaled start and completion would; a new
        tier over the same journal re-queues the job deterministically
        and re-runs it to the SAME coordinates, with valid artifacts
        carrying the whole story."""
        from spark_examples_tpu.serving import (
            AnalysisEngine,
            JobSpec,
            SimulatedCrash,
        )

        src = synthetic_cohort(10, 80, seed=3)
        baseline = AnalysisEngine(src).run(_chaos_conf(shard_retries=1))
        trace = str(tmp_path / "serv.trace.json")
        metrics = str(tmp_path / "serv.prom")
        plan = FaultPlan(
            seed=5,
            rules=[
                FaultRule(
                    site="serving.job.kill", kind="error", times=1
                )
            ],
        )
        with TelemetrySession(trace_out=trace, metrics_out=metrics):
            tier = self._tier(src, tmp_path)
            with faults.active_plan(plan):
                job, created = tier.submit(JobSpec(tenant="t"))
                assert created
                with pytest.raises(SimulatedCrash):
                    tier.step(timeout=1.0)
            # The "killed" tier is abandoned, as the process would be:
            # its in-memory job is still 'running', its journal has a
            # start event and no terminal one.
            assert job.state == "running"
            assert plan.fired_total == 1
            tier2 = self._tier(src, tmp_path)
            resumed = tier2.job(job.id)
            assert resumed is not None and resumed.state == "queued"
            assert tier2.step(timeout=1.0)
            assert resumed.state == "done"
            assert resumed.result == baseline  # exact float equality
            tier2.close()
        assert validate.validate_trace(trace) == []
        assert validate.validate_metrics(metrics) == []
        events = json.loads(open(trace).read())["traceEvents"]
        names = {e["name"] for e in events}
        assert {"fault_injected", "job.replay", "job.run"} <= names

    def test_full_queue_sheds_instead_of_queuing_unboundedly(self):
        from spark_examples_tpu.serving import JobSpec, QueueFullError

        src = synthetic_cohort(10, 80, seed=3)
        tier = self._tier(src, queue_depth=2)
        tier.submit(JobSpec(tenant="a"))
        tier.submit(JobSpec(tenant="b", num_pc=3))
        hints = []
        for k in (4, 5):
            with pytest.raises(QueueFullError) as ei:
                tier.submit(JobSpec(tenant="c", num_pc=k))
            hints.append(ei.value.retry_after)
        assert tier.queue_depth() == 2  # bounded, not unbounded
        assert 0 < hints[0] < hints[1]  # backoff-shaped Retry-After
        tier.close()

    def test_tenant_quota_holds_under_concurrent_submission(self):
        from spark_examples_tpu.serving import (
            JobSpec,
            QuotaExceededError,
        )

        src = synthetic_cohort(10, 80, seed=3)
        tier = self._tier(src, queue_depth=100, tenant_quota=2)
        n = 8
        barrier = threading.Barrier(n)
        outcomes = [None] * n

        def submit(i):
            barrier.wait()
            try:
                # Distinct analyses (different AF filters): dedup must
                # not mask the quota.
                tier.submit(
                    JobSpec(
                        tenant="greedy",
                        min_allele_frequency=0.001 * (i + 1),
                    )
                )
                outcomes[i] = "admitted"
            except QuotaExceededError:
                outcomes[i] = "quota"

        threads = [
            threading.Thread(target=submit, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert outcomes.count("admitted") == 2  # the quota, exactly
        assert outcomes.count("quota") == n - 2
        # Another tenant is unaffected by the greedy one.
        tier.submit(JobSpec(tenant="patient"))
        tier.close()
