"""Sparse-aware, mesh-sharded Gramian suite (ROADMAP item 2).

Pins the biobank-scale path end to end: the OOB-drop scatter kernel is
bit-identical to the dense integer-exact reference across mesh shapes
(1×1, 2×1, 2×2 host-device meshes), shuffled window orders, and density
edge cases; the per-window dense/sparse switch; the per-host
sample-range ingest contract; the streaming-sparse footprint bound that
replaced NOTES.md verdict #7's 16·N² host refusal; the centralized
k+1-values panel convention; and the ``--pca-mode sparse`` CLI route
with schema-valid telemetry. The N=65536 acceptance run is the ``slow``
test at the bottom.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from spark_examples_tpu.arrays.blocks import (
    csr_windows,
    restrict_window_to_sample_range,
)
from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.models.pca import VariantsPcaDriver
from spark_examples_tpu.ops.gramian import gramian
from spark_examples_tpu.ops.pcoa import randomized_panel_width
from spark_examples_tpu.ops.sparse import (
    padded_carrier_matrix,
    sparse_gramian_accumulate,
    sparse_gramian_blockwise,
    window_density,
    window_route,
)
from spark_examples_tpu.parallel.mesh import make_mesh
from spark_examples_tpu.parallel.sharded import (
    sample_bounds_of_indices,
    sparse_sharded_gramian_blockwise,
    topk_eig_randomized,
)
from spark_examples_tpu.utils.config import PcaConfig

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(__file__)), "scripts"),
)
import validate_trace as validate  # noqa: E402

# The issue's mesh matrix: 1x1, 2x1, 2x2 host-device meshes, plus a
# wider 4x2 when the device count allows. The conftest forces 8 virtual
# CPU devices by default but KEEPS a pre-set
# --xla_force_host_platform_device_count (the CI mesh leg pins 4), so
# the spec list adapts to what is actually available.
import jax  # noqa: E402  (after conftest has pinned the platform)

MESH_SPECS = tuple(
    spec
    for spec, need in (
        ("data:1", 1),
        ("data:2", 2),
        ("data:2,model:2", 4),
        ("data:4,model:2", 8),
    )
    if need <= jax.device_count()
)


def cohort_csr(n, v, density=0.08, seed=0):
    """(x, (indices, offsets)) — a dense reference and its CSR twin."""
    rng = np.random.default_rng(seed)
    x = (rng.random((n, v)) < density).astype(np.int8)
    cols, rows = np.nonzero(x.T)
    lens = np.bincount(cols, minlength=v)
    offsets = np.zeros(v + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    return x, (rows.astype(np.int64), offsets)


class TestCarrierMatrix:
    def test_shapes_sentinel_and_values(self):
        idx = np.array([5, 7, 2, 9, 9, 9], dtype=np.int64)
        lens = np.array([2, 1, 0, 3], dtype=np.int64)
        mat = padded_carrier_matrix(idx, lens, sentinel=10)
        assert mat.shape == (4, 8)  # min bucket 8
        assert mat.dtype == np.int32
        np.testing.assert_array_equal(mat[0, :2], [5, 7])
        np.testing.assert_array_equal(mat[1, :1], [2])
        assert (mat[2] == 10).all()  # empty variant: all sentinel
        np.testing.assert_array_equal(mat[3, :3], [9, 9, 9])
        # every pad cell is the sentinel
        assert (mat[0, 2:] == 10).all() and (mat[1, 1:] == 10).all()

    def test_row_padding_and_bucketing(self):
        idx = np.arange(9, dtype=np.int64)
        lens = np.array([9], dtype=np.int64)
        mat = padded_carrier_matrix(idx, lens, sentinel=99, n_rows=4)
        assert mat.shape == (4, 16)  # 9 carriers -> 16 bucket
        assert (mat[1:] == 99).all()  # padded variant rows inert

    def test_n_rows_too_small_rejected(self):
        with pytest.raises(ValueError, match="n_rows"):
            padded_carrier_matrix(
                np.zeros(0, np.int64),
                np.zeros(3, np.int64),
                sentinel=1,
                n_rows=2,
            )


class TestDensityRouting:
    def test_density_and_route_boundary(self):
        # 4 carriers over N=10, V=2 -> density exactly 0.2
        lens = np.array([3, 1])
        assert window_density(lens, 10) == pytest.approx(0.2)
        # Exactly AT the threshold routes dense (the MXU side of the
        # tie) — the boundary the auto selector is pinned to.
        assert window_route(lens, 10, 0.2) == "dense"
        # Just past it: mean density clears, and so does the max
        # per-variant carrier fraction (3/10 < 0.31) -> scatter.
        assert window_route(lens, 10, 0.31) == "scatter"
        assert window_route(np.zeros(4, np.int64), 10, 0.2) == "scatter"
        assert window_density(np.zeros(0, np.int64), 10) == 0.0

    def test_one_common_variant_forces_dense_route(self):
        """Scatter cost scales with k_max², not mean density: ONE
        common variant (k/N past the threshold) buried in an
        otherwise-rare window must route the window dense even though
        its MEAN density whispers 'sparse'."""
        n = 1000
        lens = np.concatenate([[250], np.ones(99, np.int64)])
        assert window_density(lens, n) < 0.02  # mean says sparse...
        assert window_route(lens, n, 0.02) == "dense"  # ...max says no
        # The same window with the common variant removed scatters.
        assert window_route(lens[1:], n, 0.02) == "scatter"

    def test_route_counters_record_the_mix(self):
        from spark_examples_tpu import obs

        reg = obs.get_registry()
        counter = reg.counter(
            "sparse_gramian_windows_total",
            "CSR windows accumulated by the sparse-aware Gramian engine",
        )
        before = {
            r: counter.labels(route=r).value for r in ("scatter", "dense")
        }
        x, pair = cohort_csr(24, 64, density=0.1, seed=4)
        # Threshold splits the stream: sparse windows scatter, the rest
        # densify — and the counters see exactly one window each way.
        g = sparse_gramian_blockwise(
            csr_windows(iter([pair]), 32),
            24,
            density_threshold=window_density(
                np.diff(pair[1][:33]), 24
            ),
            block_variants=32,
        )
        after = {
            r: counter.labels(route=r).value for r in ("scatter", "dense")
        }
        assert after["scatter"] + after["dense"] == (
            before["scatter"] + before["dense"] + 2
        )
        np.testing.assert_array_equal(np.asarray(g), np.asarray(gramian(x)))


class TestBitIdentity:
    def test_meshless_sparse_matches_dense(self):
        x, pair = cohort_csr(37, 300, density=0.08)
        g = sparse_gramian_blockwise(
            csr_windows(iter([pair]), 64), 37, block_variants=64
        )
        np.testing.assert_array_equal(np.asarray(g), np.asarray(gramian(x)))

    def test_meshless_mixed_routes_match_dense(self):
        # Threshold inside the density range -> some windows scatter,
        # some densify; the mix must still be bit-identical.
        x, pair = cohort_csr(37, 300, density=0.08, seed=2)
        g = sparse_gramian_blockwise(
            csr_windows(iter([pair]), 64),
            37,
            density_threshold=0.08,
            block_variants=64,
        )
        np.testing.assert_array_equal(np.asarray(g), np.asarray(gramian(x)))

    @pytest.mark.parametrize("spec", MESH_SPECS)
    def test_sharded_sparse_matches_dense_across_mesh_shapes(self, spec):
        x, pair = cohort_csr(37, 300, density=0.06, seed=1)
        mesh = make_mesh(spec)
        g = sparse_sharded_gramian_blockwise(
            csr_windows(iter([pair]), 64), 37, mesh, block_variants=64
        )
        np.testing.assert_array_equal(np.asarray(g), np.asarray(gramian(x)))

    def test_sharded_shuffled_window_order_bit_identical(self):
        x, pair = cohort_csr(41, 256, density=0.05, seed=7)
        mesh = make_mesh("data:2,model:2")
        windows = list(csr_windows(iter([pair]), 32))
        assert len(windows) >= 4
        rng = np.random.default_rng(3)
        shuffled = [windows[i] for i in rng.permutation(len(windows))]
        a = sparse_sharded_gramian_blockwise(
            iter(windows), 41, mesh, block_variants=32
        )
        b = sparse_sharded_gramian_blockwise(
            iter(shuffled), 41, mesh, block_variants=32
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(gramian(x)))

    def test_density_edge_cases(self):
        n = 19
        # all-zero window, single-nnz row, and a window exactly at the
        # switch threshold — every edge accumulates bit-identically.
        zero_w = (np.zeros(0, np.int64), np.zeros(8, np.int64))
        single = (np.array([4], np.int64), np.array([1], np.int64))
        # 8 variants x n samples: one carrier per variant => density
        # 8/(19*8) — pick the threshold exactly there.
        at_idx = np.arange(8, dtype=np.int64)
        at_lens = np.ones(8, np.int64)
        thr = window_density(at_lens, n)
        windows = [zero_w, single, (at_idx, at_lens)]
        want = np.zeros((n, n), np.float32)
        want[4, 4] += 1
        want[np.arange(8), np.arange(8)] += 1
        for mesh in (None, make_mesh("data:2,model:2")):
            if mesh is None:
                g = sparse_gramian_blockwise(
                    iter(windows), n, density_threshold=thr,
                    block_variants=8,
                )
            else:
                g = sparse_sharded_gramian_blockwise(
                    iter(windows), n, mesh, density_threshold=thr,
                    block_variants=8,
                )
            np.testing.assert_array_equal(np.asarray(g), want)

    def test_empty_stream_yields_zero_g(self):
        g = sparse_gramian_blockwise(iter(()), 5)
        np.testing.assert_array_equal(
            np.asarray(g), np.zeros((5, 5), np.float32)
        )

    def test_out_of_range_carrier_fails_loudly(self):
        bad = (np.array([7], np.int64), np.array([1], np.int64))
        with pytest.raises(ValueError, match="out of range"):
            sparse_gramian_blockwise(iter([bad]), 5)

    def test_scatter_kernel_accumulates_duplicate_pairs(self):
        # Two variants with the same carrier pair in ONE window: the
        # scatter must apply both +1s (XLA scatter-add dup semantics).
        g = jnp.zeros((6, 6), jnp.float32)
        g = sparse_gramian_accumulate(
            g,
            np.array([1, 3, 1, 3], np.int64),
            np.array([2, 2], np.int64),
        )
        assert np.asarray(g)[1, 3] == 2.0 and np.asarray(g)[3, 1] == 2.0


class TestShardedFootprint:
    def test_no_device_holds_nxn(self):
        n = 64
        x, pair = cohort_csr(n, 128, density=0.05, seed=5)
        mesh = make_mesh("data:2,model:2")
        g = sparse_sharded_gramian_blockwise(
            csr_windows(iter([pair]), 64), n, mesh, block_variants=64
        )
        shapes = {s.data.shape for s in g.addressable_shards}
        assert shapes == {(32, 32)}, (
            "each device must hold exactly one (N/rows, N/cols) tile"
        )
        np.testing.assert_array_equal(np.asarray(g), np.asarray(gramian(x)))


class TestSampleRangeContract:
    def test_restrict_window_drops_and_recounts(self):
        idx = np.array([0, 5, 9, 3, 7], np.int64)
        lens = np.array([3, 0, 2], np.int64)
        out_idx, out_lens = restrict_window_to_sample_range(
            idx, lens, 3, 8
        )
        np.testing.assert_array_equal(out_idx, [5, 3, 7])
        np.testing.assert_array_equal(out_lens, [1, 0, 2])

    def test_full_range_is_identity(self):
        idx = np.array([2, 4], np.int64)
        lens = np.array([2], np.int64)
        out_idx, out_lens = restrict_window_to_sample_range(
            idx, lens, 0, 100
        )
        np.testing.assert_array_equal(out_idx, idx)
        np.testing.assert_array_equal(out_lens, lens)

    def test_sample_bounds_of_indices_union(self):
        slices = [
            (slice(32, 64), slice(0, 16)),
            (slice(32, 64), slice(16, 32)),
        ]
        assert sample_bounds_of_indices(slices, 64) == (0, 64)
        assert sample_bounds_of_indices(
            [(slice(8, 16), slice(8, 16))], 64
        ) == (8, 16)
        # Degenerate/empty tile sets fall back to the full range.
        assert sample_bounds_of_indices([], 64) == (0, 64)

    def test_restricted_ingest_is_bit_identical_for_owned_tiles(self):
        """Dropping carriers outside a host's sample-range bounds can
        never change the tiles it owns — the ingest contract that lets
        each mesh host pull only its sample rows (ARCHITECTURE.md)."""
        n = 48
        x, pair = cohort_csr(n, 96, density=0.06, seed=8)
        windows = list(csr_windows(iter([pair]), 32))
        lo, hi = 16, 48  # a fictional host owning tile rows/cols 16..48
        restricted = [
            restrict_window_to_sample_range(i, l, lo, hi)
            for i, l in windows
        ]
        full = np.asarray(gramian(x))
        got = np.asarray(
            sparse_gramian_blockwise(iter(restricted), n, block_variants=32)
        )
        np.testing.assert_array_equal(
            got[lo:hi, lo:hi], full[lo:hi, lo:hi]
        )


class TestDriverSparseMode:
    def _driver(self, mode="sparse", mesh_spec=None, n=30, v=200, **kw):
        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            block_variants=64,
            pca_mode=mode,
            **kw,
        )
        mesh = make_mesh(mesh_spec) if mesh_spec else None
        source = synthetic_cohort(n, v, population_structure=2, seed=3)
        return VariantsPcaDriver(conf, source, mesh=mesh)

    def test_sparse_mode_matches_stream_coordinates(self):
        sparse = self._driver("sparse").run()
        stream = self._driver("stream").run()
        a = np.array([r[1:] for r in sparse])
        b = np.array([r[1:] for r in stream])
        assert np.abs(a - b).max() <= 1e-4
        assert [r[0] for r in sparse] == [r[0] for r in stream]

    def test_sparse_mode_on_mesh_matches_stream(self):
        sparse = self._driver("sparse", "data:2,model:2").run()
        stream = self._driver("stream").run()
        a = np.array([r[1:] for r in sparse])
        b = np.array([r[1:] for r in stream])
        assert np.abs(a - b).max() <= 1e-4

    def test_sparse_gramian_bit_identical_to_dense_tiers(self):
        d_sparse = self._driver("sparse")
        d_dense = self._driver("stream")
        g_sparse = np.asarray(d_sparse.ingest_gramian())
        g_dense = np.asarray(d_dense.ingest_gramian())
        np.testing.assert_array_equal(g_sparse, g_dense)

    def test_auto_selects_sparse_only_on_sample_sharded_mesh(self):
        auto_mesh = self._driver(
            "auto", "data:2,model:2", sample_shard_threshold=8
        )
        assert auto_mesh._sparse_selected()  # N=30 > 8, host-local mesh
        assert not self._driver(
            "auto", sample_shard_threshold=8
        )._sparse_selected()  # meshless auto keeps the dense tiers
        assert not self._driver(
            "auto", "data:2,model:2"
        )._sparse_selected()  # below the shard threshold
        assert self._driver("sparse")._sparse_selected()  # forced
        assert not self._driver("stream")._sparse_selected()

    def test_auto_sparse_run_matches_dense(self):
        auto = self._driver(
            "auto", "data:2,model:2", sample_shard_threshold=8
        )
        assert auto._sparse_selected()
        a = np.array([r[1:] for r in auto.run()])
        b = np.array([r[1:] for r in self._driver("stream").run()])
        assert np.abs(a - b).max() <= 1e-4

    def test_sparse_rejects_checkpointing_before_ingest(self):
        with pytest.raises(ValueError, match="sparse"):
            self._driver("sparse", checkpoint_dir="/tmp/nope")

    def test_negative_threshold_rejected(self):
        with pytest.raises(ValueError, match="sparse-density-threshold"):
            self._driver("sparse", sparse_density_threshold=-0.1)

    def test_negative_pipeline_depth_rejected(self):
        with pytest.raises(ValueError, match="pod-pipeline-depth"):
            self._driver("sparse", pod_pipeline_depth=-1)

    def test_negative_coalesce_rejected(self):
        with pytest.raises(ValueError, match="pod-coalesce-variants"):
            self._driver("sparse", pod_coalesce_variants=-8)

    def test_dense_panel_width_buckets(self):
        from spark_examples_tpu.ops.sparse import dense_panel_width

        # Power-of-two bucket, min 8, capped at the block width; a
        # wider-than-block window (direct API use) keeps exact rows.
        assert dense_panel_width(512, 8192) == 512
        assert dense_panel_width(513, 8192) == 1024
        assert dense_panel_width(0, 8192) == 8
        assert dense_panel_width(3, 32) == 8
        assert dense_panel_width(8192, 8192) == 8192
        assert dense_panel_width(9000, 8192) == 9000

    def test_rare_variant_af_out_of_range_rejected(self):
        # af > 2/3 would silently saturate carrier probability past 1
        # (an all-carrier "rare" cohort); af <= 0 an all-zero one.
        for bad in (0.8, 0.0, -0.1):
            with pytest.raises(ValueError, match="rare_variant_af"):
                synthetic_cohort(4, 4, rare_variant_af=bad)


class TestStreamFootprintBound:
    """Satellite: the 16·N² host refusal (NOTES.md verdict #7) is gone;
    the bound is the streaming-sparse per-host G footprint."""

    def _driver(self, mesh_spec=None):
        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID], block_variants=32
        )
        mesh = make_mesh(mesh_spec) if mesh_spec else None
        return VariantsPcaDriver(
            conf, synthetic_cohort(12, 90), mesh=mesh
        )

    def test_old_16n2_bound_is_gone(self):
        """A budget the historical 16·N² peak refused (anything under
        16·N²) now admits the run — the sparse engine never builds the
        int64 host G + f32 copy + jax buffer stack."""
        driver = self._driver()
        calls = list(driver.get_calls(driver.get_data()))
        out = driver.get_similarity_matrix_stream(
            iter(calls), max_host_bytes=16 * 12 * 12 - 1
        )
        assert out.shape == (12, 12)

    def test_refuses_past_per_host_footprint_with_new_message(self):
        driver = self._driver()
        calls = list(driver.get_calls(driver.get_data()))
        with pytest.raises(
            ValueError, match="per-host f32 Gramian tiles"
        ) as exc:
            driver.get_similarity_matrix_stream(
                iter(calls), max_host_bytes=4 * 12 * 12 - 1
            )
        assert "max_host_bytes" in str(exc.value)
        # AT the f32-G footprint it runs.
        out = driver.get_similarity_matrix_stream(
            iter(calls), max_host_bytes=4 * 12 * 12
        )
        assert out.shape == (12, 12)

    def test_stream_bit_identical_through_sparse_engine(self):
        driver = self._driver()
        calls = list(driver.get_calls(driver.get_data()))
        dense = np.asarray(driver.get_similarity_matrix(iter(calls)))
        stream = np.asarray(
            driver.get_similarity_matrix_stream(iter(calls))
        )
        np.testing.assert_array_equal(dense, stream)

    def test_mesh_footprint_accounts_tiles(self):
        meshed = self._driver("data:2,model:2")
        meshless = self._driver()
        # Single-controller: every tile is addressable, so the per-host
        # sum equals the padded f32 G — the accounting is per-HOST, and
        # only a process-spanning mesh shrinks it.
        assert meshed._sparse_host_g_bytes() == 4 * 12 * 12
        assert meshless._sparse_host_g_bytes() == 4 * 12 * 12


class TestPanelWidthConvention:
    """Satellite: the k+1-values calling convention lives in ONE helper
    so the sharded finish can't silently drop the gap check."""

    def test_floor_and_cap(self):
        assert randomized_panel_width(100, 2, 8) == 10
        assert randomized_panel_width(100, 2, 0) == 3  # k+1 floor
        assert randomized_panel_width(100, 2, -5) == 3
        assert randomized_panel_width(3, 2, 8) == 3  # n cap
        with pytest.raises(ValueError, match="k >= 1"):
            randomized_panel_width(10, 0, 8)

    def test_zero_oversample_still_checks_the_gap(self):
        """Before centralizing, oversample=0 silently disabled the
        spectral-gap degeneracy warning (no k+1-th Ritz value); now the
        panel floor guarantees it."""
        rng = np.random.default_rng(1)
        q, _ = np.linalg.qr(rng.random((48, 48)))
        w = np.concatenate([[10.0, 5.0, 4.999], np.linspace(1, 0.1, 45)])
        c = jnp.asarray((q * w) @ q.T, jnp.float32)
        with pytest.warns(Warning, match="near-degenerate"):
            vecs, vals = topk_eig_randomized(c, 2, oversample=0, iters=40)
        assert vecs.shape == (48, 2) and vals.shape == (2,)


class TestSparseCliTelemetry:
    def test_cli_sparse_run_emits_schema_valid_artifacts(self, tmp_path):
        from spark_examples_tpu.cli.main import main

        paths = {
            "trace": str(tmp_path / "run.trace.json"),
            "metrics": str(tmp_path / "run.metrics.prom"),
            "manifest": str(tmp_path / "run.manifest.json"),
        }
        old = os.environ.get("SPARK_EXAMPLES_TPU_COMPILE_CACHE")
        os.environ["SPARK_EXAMPLES_TPU_COMPILE_CACHE"] = "0"
        try:
            rc = main(
                [
                    "pca",
                    "--fixture-samples",
                    "16",
                    "--fixture-variants",
                    "96",
                    "--fixture-rare-af",
                    "0.05",
                    "--pca-mode",
                    "sparse",
                    "--mesh-shape",
                    "data:2,model:2",
                    "--trace-out",
                    paths["trace"],
                    "--metrics-out",
                    paths["metrics"],
                    "--manifest-out",
                    paths["manifest"],
                ]
            )
        finally:
            if old is None:
                os.environ.pop("SPARK_EXAMPLES_TPU_COMPILE_CACHE", None)
            else:
                os.environ["SPARK_EXAMPLES_TPU_COMPILE_CACHE"] = old
        assert rc == 0
        assert validate.validate_trace(paths["trace"]) == []
        assert validate.validate_metrics(paths["metrics"]) == []
        assert validate.validate_manifest(paths["manifest"]) == []
        trace = json.load(open(paths["trace"]))
        names = {ev.get("name") for ev in trace["traceEvents"]}
        assert "gramian.sparse.accumulate" in names
        assert "gramian.sparse.window" in names
        prom = open(paths["metrics"]).read()
        assert 'sparse_gramian_windows_total{route="' in prom
        assert "sparse_gramian_nnz_total" in prom


class TestSchemaDrift:
    """Satellite: both rejection directions for the pod-sparse obs
    surface — an unknown ``gramian.sparse.*`` span fails the trace
    gate, and a ``sparse_pod_sync_total`` sample without its outcome
    label fails the metrics gate (the closed sets GL003 cross-checks
    statically)."""

    def test_allgather_span_is_schema_known(self, tmp_path):
        trace = tmp_path / "t.json"
        trace.write_text(
            json.dumps(
                {
                    "traceEvents": [
                        {
                            "ph": "X",
                            "name": "gramian.sparse.allgather",
                            "pid": 1,
                            "ts": 0,
                            "dur": 1,
                        }
                    ]
                }
            )
        )
        assert validate.validate_trace(str(trace)) == []

    def test_unknown_sparse_span_rejected(self, tmp_path):
        trace = tmp_path / "t.json"
        trace.write_text(
            json.dumps(
                {
                    "traceEvents": [
                        {
                            "ph": "X",
                            "name": "gramian.sparse.carrier_sync",
                            "pid": 1,
                            "ts": 0,
                            "dur": 1,
                        }
                    ]
                }
            )
        )
        errs = validate.validate_trace(str(trace))
        assert errs and "gramian.sparse.carrier_sync" in errs[0]

    def test_pod_sync_counter_requires_outcome_label(self, tmp_path):
        good = tmp_path / "good.prom"
        good.write_text('sparse_pod_sync_total{outcome="synced"} 3\n')
        assert validate.validate_metrics(str(good)) == []
        bad = tmp_path / "bad.prom"
        bad.write_text("sparse_pod_sync_total 3\n")
        errs = validate.validate_metrics(str(bad))
        assert errs and "outcome" in errs[0]

    def test_slot_span_is_schema_known(self, tmp_path):
        trace = tmp_path / "t.json"
        trace.write_text(
            json.dumps(
                {
                    "traceEvents": [
                        {
                            "ph": "X",
                            "name": "gramian.sparse.slot",
                            "pid": 1,
                            "ts": 0,
                            "dur": 1,
                        }
                    ]
                }
            )
        )
        assert validate.validate_trace(str(trace)) == []

    def test_unknown_pipeline_span_rejected(self, tmp_path):
        trace = tmp_path / "t.json"
        trace.write_text(
            json.dumps(
                {
                    "traceEvents": [
                        {
                            "ph": "X",
                            "name": "gramian.sparse.pipeline_slot",
                            "pid": 1,
                            "ts": 0,
                            "dur": 1,
                        }
                    ]
                }
            )
        )
        errs = validate.validate_trace(str(trace))
        assert errs and "gramian.sparse.pipeline_slot" in errs[0]

    def test_coalesce_counter_requires_mode_label(self, tmp_path):
        good = tmp_path / "good.prom"
        good.write_text(
            'sparse_pod_coalesced_windows_total{mode="gang"} 12\n'
        )
        assert validate.validate_metrics(str(good)) == []
        bad = tmp_path / "bad.prom"
        bad.write_text("sparse_pod_coalesced_windows_total 12\n")
        errs = validate.validate_metrics(str(bad))
        assert errs and "mode" in errs[0]


# ---------------------------------------------------------------------------
# Process-spanning (pod) sparse protocol: subprocess-spawned
# jax.distributed CPU harness (2 and 4 processes). Same worker pattern
# as tests/test_multihost.py; every scenario runs under a hard timeout
# so a stranded-peer deadlock fails the test instead of hanging it.
# ---------------------------------------------------------------------------

import socket  # noqa: E402
import subprocess  # noqa: E402
import textwrap  # noqa: E402

pod_skip = pytest.mark.skipif(
    os.environ.get("SPARK_EXAMPLES_TPU_SKIP_MULTIHOST") == "1",
    reason="multihost tests disabled",
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_pod_workers(script_path, argv, n=2, timeout=300):
    """Spawn n coordinator-connected workers; assert every one exits 0
    within the hard timeout (a hung collective must FAIL, never hang
    the suite — dead peers are killed in the finally)."""
    port = _free_port()
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ),
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": str(n),
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        # The collective-congruence runtime backstop runs for the whole
        # multiprocess suite: every protocol step cross-checks its
        # derived (op, geometry) digest across peers, so a divergence
        # bug fails loudly here instead of deadlocking a real pod.
        "SPARK_EXAMPLES_TPU_COLLECTIVE_CHECK": "1",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script_path)] + [str(a) for a in argv],
            env={**env, "JAX_PROCESS_ID": str(i)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(n)
    ]
    try:
        logs = [p.communicate(timeout=timeout)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-3000:]
    return logs


_POD_SPARSE_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.arrays.blocks import csr_windows
    from spark_examples_tpu.parallel.sharded import (
        sparse_sharded_gramian_blockwise,
    )

    pid, world = jax.process_index(), jax.process_count()
    mesh = Mesh(np.array(jax.devices()).reshape(world, 2), ("data", "model"))
    rep = NamedSharding(mesh, P(None, None))
    replicate = jax.jit(lambda a: a, out_shardings=rep)

    def cohort(n, v, density, seed):
        rng = np.random.default_rng(seed)
        x = (rng.random((n, v)) < density).astype(np.int8)
        cols, rows = np.nonzero(x.T)
        lens = np.bincount(cols, minlength=v)
        offsets = np.zeros(v + 1, np.int64)
        np.cumsum(lens, out=offsets[1:])
        return x, (rows.astype(np.int64), offsets)

    out = {}
    n = 37
    x, pair = cohort(n, 300, 0.06, seed=1)
    windows = list(csr_windows(iter([pair]), 32))
    mine = windows[pid::world]  # uneven per-process streams (tail)

    # 1. Pod-sparse G over round-robin window slices, manifest order.
    g = sparse_sharded_gramian_blockwise(
        iter(mine), n, mesh, block_variants=32
    )
    assert not g.is_fully_addressable  # really cross-process sharded
    out["g"] = np.asarray(replicate(g)).tolist()
    out["tile_shapes"] = sorted(
        str(s.data.shape) for s in g.addressable_shards
    )

    # 2. Shuffled window order (each process shuffles its own slice) —
    # integer-exact accumulation is order-invariant.
    rng = np.random.default_rng(3 + pid)
    shuffled = [mine[i] for i in rng.permutation(len(mine))]
    g2 = sparse_sharded_gramian_blockwise(
        iter(shuffled), n, mesh, block_variants=32
    )
    out["g_shuffled"] = np.asarray(replicate(g2)).tolist()

    # 3. Density edges: all-zero window, single-nnz row, and a mixed
    # dense+scatter stream where SAME-STEP windows agree on the route
    # (steps 0-1 scatter everywhere, step 2 dense everywhere: density
    # 12/19 >= 0.5 on every process) — the pod route is a per-step
    # global decision, and the per-route window counter pins that the
    # dense pod payload branch REALLY ran (not just scatter twice).
    from spark_examples_tpu import obs
    cnt = obs.get_registry().counter(
        "sparse_gramian_windows_total",
        "CSR windows accumulated by the sparse-aware Gramian engine",
    )
    before = {r: cnt.labels(route=r).value for r in ("scatter", "dense")}
    edge = [
        (np.zeros(0, np.int64), np.zeros(8, np.int64)),       # all-zero
        (np.array([4 + pid], np.int64), np.array([1], np.int64)),
        (
            np.arange(12, dtype=np.int64),                     # dense step
            np.array([12], np.int64),
        ),
    ]
    g3 = sparse_sharded_gramian_blockwise(
        iter(edge), 19, mesh, density_threshold=0.5, block_variants=8
    )
    out["g_edges"] = np.asarray(
        jax.jit(lambda a: a, out_shardings=rep)(g3)
    ).tolist()
    out["edge_routes"] = {
        r: cnt.labels(route=r).value - before[r]
        for r in ("scatter", "dense")
    }

    # 4. Forced sparse on a HOST-LOCAL mesh in this multi-controller
    # run: each process tiles only ITS slice over its OWN devices with
    # zero collectives, so the result is a per-host partial — the
    # driver-side allreduce_gramian merge (pca._windows_to_gramian's
    # non-spanning multi-process branch) must reproduce the global G.
    from spark_examples_tpu.parallel.distributed import allreduce_gramian
    local_mesh = Mesh(
        np.array(jax.local_devices()).reshape(1, -1), ("data", "model")
    )
    g4 = sparse_sharded_gramian_blockwise(
        iter(mine), n, local_mesh, block_variants=32
    )
    assert g4.is_fully_addressable
    out["g_hostlocal_merged"] = np.asarray(allreduce_gramian(g4)).tolist()

    # 5. Pipeline-depth ablation: depth 0 (inline lockstep) and a deep
    # pipeline produce bit-identical G — the pipeline changes WHEN the
    # exchange runs, never what accumulates.
    g5 = sparse_sharded_gramian_blockwise(
        iter(mine), n, mesh, block_variants=32, pipeline_depth=0
    )
    out["g_depth0"] = np.asarray(replicate(g5)).tolist()
    g6 = sparse_sharded_gramian_blockwise(
        iter(mine), n, mesh, block_variants=32, pipeline_depth=4
    )
    out["g_depth4"] = np.asarray(replicate(g6)).tolist()

    # 6. Coalesced-gang bit-identity: many TINY windows (well under the
    # coalesce target) merge into multi-window gangs; shuffled local
    # orders and different coalesce settings all land on the same G,
    # and the gang/solo counter records the split.
    gang_counter = obs.get_registry().counter(
        "sparse_pod_coalesced_windows_total",
        "Local CSR windows entering pod-sparse protocol steps, by "
        "gang/solo coalescing outcome",
    )
    tiny = list(csr_windows(iter([pair]), 4))  # 4-variant windows
    mine_tiny = tiny[pid::world]
    before_gang = {
        m: gang_counter.labels(mode=m).value for m in ("gang", "solo")
    }
    for coalesce, key in ((0, "g_solo"), (64, "g_gang")):
        rng = np.random.default_rng(11 + pid + coalesce)
        shuffled_tiny = [
            mine_tiny[i] for i in rng.permutation(len(mine_tiny))
        ]
        gg = sparse_sharded_gramian_blockwise(
            iter(shuffled_tiny), n, mesh, block_variants=4,
            coalesce_variants=coalesce,
            density_threshold=1.01,  # all-scatter: gangs can form
        )
        out[key] = np.asarray(replicate(gg)).tolist()
    after_gang = {
        m: gang_counter.labels(mode=m).value for m in ("gang", "solo")
    }
    out["gang_delta"] = {
        m: after_gang[m] - before_gang[m] for m in ("gang", "solo")
    }
    out["tiny_windows"] = len(mine_tiny)

    # 7. Overlap proof on the emitted trace: with the pipelined stream,
    # some step w+1 exchange span must BEGIN before step w's scatter
    # span ENDS (the serialization MULTICHIP_r06 paid is gone).
    from spark_examples_tpu.obs import telemetry_session
    trace_path = sys.argv[1] + f".trace.{pid}.json"
    with telemetry_session(trace_out=trace_path):
        sparse_sharded_gramian_blockwise(
            iter(mine), n, mesh, block_variants=32
        )
    import spark_examples_tpu as _pkg
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(_pkg.__file__))),
        "scripts",
    ))
    import validate_trace as _vt
    evs = json.load(open(trace_path))["traceEvents"]
    out["overlap_proven"] = _vt.sparse_overlap_proven(evs)
    out["slot_spans"] = sum(
        1
        for e in evs
        if e.get("ph") == "X" and e["name"] == "gramian.sparse.slot"
    )

    if pid == 0:
        with open(sys.argv[1], "w") as f:
            json.dump(out, f)
    """
)


_POD_CHAOS_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.parallel.sharded import (
        sparse_sharded_gramian_blockwise,
    )
    from spark_examples_tpu import obs

    pid, world = jax.process_index(), jax.process_count()
    mesh = Mesh(np.array(jax.devices()).reshape(world, 2), ("data", "model"))
    results = {}
    DEPTH = 2
    # Failure positions 0..3 cover every in-flight slot of the depth-2
    # pipeline (position 0 = first slot, 3 = past the staged window).
    POSITIONS = [0, 1, 2, 3]

    def win(idx, lens):
        return np.asarray(idx, np.int64), np.asarray(lens, np.int64)

    def good(i):
        # Distinct tiny scatter windows (threshold 1.01 -> scatter).
        return win([i % 9], [1])

    # A. Producer exception on ONE process at slot position p must
    # raise on EVERY process together, at the same step — never a
    # stranded peer, whatever the pipeline had in flight.
    def failing(pid, p):
        for i in range(p):
            yield good(i)
        if pid == 0:
            raise IOError("injected mid-stream ingest failure")
        yield good(p)

    results["chaos"] = []
    for p in POSITIONS:
        try:
            sparse_sharded_gramian_blockwise(
                failing(pid, p), 9, mesh, density_threshold=1.01,
                pipeline_depth=DEPTH, coalesce_variants=0,
            )
            results["chaos"].append(False)
        except RuntimeError as e:
            ok = "carrier stream failed on process(es) [0]" in str(e)
            if pid == 0:
                ok = ok and isinstance(e.__cause__, IOError)
            else:
                ok = ok and e.__cause__ is None
            results["chaos"].append(ok)

    # B. Same-step route divergence at slot position p (one process's
    # window densifies, the peers' scatter) is a per-window GLOBAL
    # decision: ValueError on every process together.
    def divergent(pid, p):
        for i in range(p):
            yield good(i)
        if pid == 0:
            yield win(np.arange(6), [6])  # density 6/9 -> dense
        else:
            yield win([0], [1])           # density 1/9 -> scatter
    results["divergence"] = []
    for p in POSITIONS:
        try:
            sparse_sharded_gramian_blockwise(
                divergent(pid, p), 9, mesh, density_threshold=0.5,
                pipeline_depth=DEPTH, coalesce_variants=0,
            )
            results["divergence"].append(False)
        except ValueError as e:
            results["divergence"].append(
                "density route" in str(e)
                and "--sparse-density-threshold" in str(e)
            )

    # C. Payload construction failure AFTER the header sync (the
    # densify-OOM shape) at slot position p: _densify_window raises on
    # process 0 only — the payload-confirm exchange must turn it into
    # an all-process raise instead of stranding peers in the payload
    # phase.
    from spark_examples_tpu.arrays import blocks as _blocks

    real_densify = _blocks._densify_window

    def _oom(*a, **k):
        raise MemoryError("injected densify failure")

    def dense_tail(p):
        for i in range(p):
            yield win([i % 19], [1])      # 1/19 < 0.5 -> scatter
        yield win(np.arange(12), [12])    # 12/19 >= 0.5 -> dense
    results["payload"] = []
    for p in POSITIONS:
        if pid == 0:
            _blocks._densify_window = _oom
        try:
            sparse_sharded_gramian_blockwise(
                dense_tail(p), 19, mesh, density_threshold=0.5,
                pipeline_depth=DEPTH, coalesce_variants=0,
            )
            results["payload"].append(False)
        except RuntimeError as e:
            ok = (
                "carrier payload construction failed on process(es) [0]"
                in str(e)
            )
            if pid == 0:
                ok = ok and isinstance(e.__cause__, MemoryError)
            else:
                ok = ok and e.__cause__ is None
            results["payload"].append(ok)
        finally:
            _blocks._densify_window = real_densify

    # D. The sync counter recorded every outcome on every process:
    # one producer-error per A and per C scenario, one
    # route-divergence per B scenario, and exactly the good slots
    # BEFORE each failure as synced (sum over positions, x3 kinds).
    counter = obs.get_registry().counter(
        "sparse_pod_sync_total",
        "Pod-sparse per-window sync steps (header + carrier allgather) "
        "by outcome",
    )
    results["outcomes"] = {
        o: counter.labels(outcome=o).value
        for o in ("synced", "producer-error", "route-divergence")
    }
    results["expected"] = {
        "synced": 3 * sum(POSITIONS),
        "producer-error": 2 * len(POSITIONS),
        "route-divergence": len(POSITIONS),
    }
    with open(sys.argv[1] + f".{pid}", "w") as f:
        json.dump(results, f)
    """
)


_POD_COLLECTIVE_CHECK_WORKER = textwrap.dedent(
    """
    import json, os, re, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.parallel.sharded import (
        sparse_sharded_gramian_blockwise,
    )
    from spark_examples_tpu.utils import collectivecheck
    from spark_examples_tpu import obs

    pid, world = jax.process_index(), jax.process_count()
    mesh = Mesh(np.array(jax.devices()).reshape(world, 2), ("data", "model"))
    results = {}
    assert collectivecheck.collective_check_enabled()  # harness env

    counter = obs.get_registry().counter(
        "collective_check_steps_total",
        "Pod protocol steps cross-checked by the collective-congruence "
        "runtime backstop, by outcome",
    )

    def win(i):
        return np.asarray([i % 9], np.int64), np.asarray([1], np.int64)

    # A. Clean run with the backstop ON: bit-identical G, every live
    # step cross-checked and counted as agree.
    before = counter.labels(outcome="agree").value
    g = sparse_sharded_gramian_blockwise(
        iter([win(i) for i in range(4)]), 9, mesh,
        density_threshold=1.01, pipeline_depth=2, coalesce_variants=0,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = NamedSharding(mesh, P(None, None))
    results["g"] = np.asarray(
        jax.jit(lambda a: a, out_shardings=rep)(g)
    ).tolist()
    results["agree_clean"] = counter.labels(outcome="agree").value - before

    # B. Chaos: a one-sided extra/omitted collective, injected through
    # the fault seam on the podstream step hash — process 0's digest
    # diverges at FAULT_STEP. The backstop must raise on EVERY process
    # at the SAME step (never a stranded peer).
    FAULT_STEP = 1
    real_digest = collectivecheck.step_digest

    def faulty(stream, step, ops):
        d = real_digest(stream, step, ops)
        if pid == 0 and step == FAULT_STEP:
            # Simulate an extra collective in the derived sequence.
            d = real_digest(stream, step, list(ops) + [("psum", (9,))])
        return d

    collectivecheck.step_digest = faulty
    try:
        sparse_sharded_gramian_blockwise(
            iter([win(i) for i in range(6)]), 9, mesh,
            density_threshold=1.01, pipeline_depth=2,
            coalesce_variants=0,
        )
        results["raised"] = False
    except RuntimeError as e:
        msg = str(e)
        m = re.search(r"protocol step (\\d+)", msg)
        results["raised"] = (
            "collective-congruence check failed" in msg
            and "digests diverged" in msg
        )
        results["step"] = int(m.group(1)) if m else -1
    finally:
        collectivecheck.step_digest = real_digest
    results["divergence"] = counter.labels(outcome="divergence").value

    with open(sys.argv[1] + f".{pid}", "w") as f:
        json.dump(results, f)
    """
)


@pod_skip
class TestPodSparseProtocol:
    """The per-step carrier-allgather protocol on a REAL ≥2-process
    ``jax.distributed`` CPU mesh: G bit-identical across
    {single-controller sparse, pod-sparse, dense reference} × shuffled
    window orders × density edges, and the failure-sync chaos cases."""

    @pytest.mark.parametrize("nprocs", [2, 4])
    def test_pod_sparse_bit_identical_to_dense(self, tmp_path, nprocs):
        if nprocs > (os.cpu_count() or 1) * 4:
            pytest.skip("not enough cores to host the pod-sim")
        script = tmp_path / "worker.py"
        script.write_text(_POD_SPARSE_WORKER)
        out_file = tmp_path / "result.json"
        _run_pod_workers(script, [out_file], n=nprocs)
        result = json.loads(out_file.read_text())

        # Dense reference + single-controller sparse over the SAME
        # cohort the pod split round-robin (cohort_csr(seed=1) is the
        # worker's generator, bit for bit).
        x, pair = cohort_csr(37, 300, density=0.06, seed=1)
        want = np.asarray(gramian(x))
        single = np.asarray(
            sparse_gramian_blockwise(
                csr_windows(iter([pair]), 32), 37, block_variants=32
            )
        )
        got = np.asarray(result["g"])
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(got, single)
        np.testing.assert_array_equal(
            np.asarray(result["g_shuffled"]), want
        )

        # Density edges: the expectation is the union of every
        # process's edge windows (all-zero + per-process single-nnz +
        # one identical dense window each); the route counter pins
        # that the stream REALLY split 2 scatter + 1 dense steps.
        want_e = np.zeros((19, 19), np.float32)
        for p in range(nprocs):
            want_e[4 + p, 4 + p] += 1
        r = np.arange(12)
        want_e[np.ix_(r, r)] += nprocs
        np.testing.assert_array_equal(
            np.asarray(result["g_edges"]), want_e
        )
        assert result["edge_routes"] == {"scatter": 2, "dense": 1}

        # The host-local-mesh partial + DCN merge (the forced-sparse
        # multi-controller driver route) reproduces the global G.
        np.testing.assert_array_equal(
            np.asarray(result["g_hostlocal_merged"]), want
        )

        # Pipeline-depth ablation: inline lockstep (0) and a deep
        # pipeline (4) are bit-identical to the default-depth run.
        np.testing.assert_array_equal(np.asarray(result["g_depth0"]), want)
        np.testing.assert_array_equal(np.asarray(result["g_depth4"]), want)

        # Coalesced gangs: tiny windows, shuffled per-process orders,
        # with coalescing off and on — bit-identical G both ways, and
        # the gang/solo counter recorded every window on the right
        # side (no 1-window gangs at these sizes: 4-variant windows
        # against a 64-variant target).
        np.testing.assert_array_equal(np.asarray(result["g_solo"]), want)
        np.testing.assert_array_equal(np.asarray(result["g_gang"]), want)
        assert result["gang_delta"] == {
            "gang": result["tiny_windows"],
            "solo": result["tiny_windows"],
        }

        # The pipelined stream PROVABLY overlapped: a step w+1 exchange
        # span began before step w's scatter span ended, and slot spans
        # made it onto the timeline.
        assert result["overlap_proven"] is True
        assert result["slot_spans"] >= 2

    @pytest.mark.parametrize("nprocs", [2, 4])
    def test_pod_failure_sync_chaos(self, tmp_path, nprocs):
        """One-sided producer failures (mid-stream AND post-header
        payload construction) and same-step route divergence, injected
        at EVERY in-flight slot position of the depth-2 pipeline, raise
        on EVERY process together — each run completes (no hang) under
        the harness's hard timeout, and the per-outcome sync counters
        account for exactly the slots that completed before each
        failure."""
        if nprocs > (os.cpu_count() or 1) * 4:
            pytest.skip("not enough cores to host the pod-sim")
        script = tmp_path / "worker.py"
        script.write_text(_POD_CHAOS_WORKER)
        out_file = tmp_path / "result.json"
        _run_pod_workers(script, [out_file], n=nprocs, timeout=240)
        for pid in range(nprocs):
            r = json.loads((tmp_path / f"result.json.{pid}").read_text())
            assert all(r["chaos"]), r
            assert all(r["divergence"]), r
            assert all(r["payload"]), r
            assert r["outcomes"] == r["expected"], r

    @pytest.mark.parametrize("nprocs", [2, 4])
    def test_collective_check_divergence_chaos(self, tmp_path, nprocs):
        """The SPARK_EXAMPLES_TPU_COLLECTIVE_CHECK=1 backstop: a
        one-sided extra collective (injected through the fault seam on
        the podstream step hash) raises on EVERY process at the SAME
        step, while a clean run stays bit-identical with every live
        step counted as agree."""
        if nprocs > (os.cpu_count() or 1) * 4:
            pytest.skip("not enough cores to host the pod-sim")
        script = tmp_path / "worker.py"
        script.write_text(_POD_COLLECTIVE_CHECK_WORKER)
        out_file = tmp_path / "result.json"
        _run_pod_workers(script, [out_file], n=nprocs, timeout=240)

        # The clean phase's G equals the dense reference over the
        # union of every process's windows (each process scatters
        # win(0..3): +1 on the diagonal at 0..3 per process).
        want = np.zeros((9, 9), np.float32)
        for i in range(4):
            want[i % 9, i % 9] += nprocs
        steps = set()
        for pid in range(nprocs):
            r = json.loads((tmp_path / f"result.json.{pid}").read_text())
            np.testing.assert_array_equal(np.asarray(r["g"]), want)
            assert r["agree_clean"] == 4, r
            assert r["raised"] is True, r
            assert r["divergence"] >= 1, r
            steps.add(r["step"])
        # ... and the raise landed at the SAME step everywhere.
        assert steps == {1}, steps


@pytest.mark.slow
def test_biobank_scale_65k_end_to_end_on_mesh():
    """ROADMAP item 2 acceptance: a synthetic N=65536 rare-variant
    cohort end to end on a ≥4-device host mesh through
    ``cli pca --pca-mode sparse`` — G tiled (N/2, N/2) per device (no
    N×N on any single device), finish through the sharded randomized
    eig. CPU backend; takes minutes (17 GB of f32 G tiles)."""
    from spark_examples_tpu.cli.main import main

    import tempfile

    n = 65536
    with tempfile.TemporaryDirectory() as tmp:
        out = os.path.join(tmp, "out")
        rc = main(
            [
                "pca",
                "--fixture-samples",
                str(n),
                "--fixture-variants",
                "64",
                "--fixture-rare-af",
                "0.003",
                "--fixture-sparse-calls",
                "--pca-mode",
                "sparse",
                "--mesh-shape",
                "data:2,model:2",
                "--eig-tol",
                "1e-3",
                "--output-path",
                out,
            ]
        )
        assert rc == 0
        lines = open(out + "-pca.tsv").read().splitlines()
        assert len(lines) == n
