"""graftlint (tools/graftlint): the static-analysis gate's own tests.

Three layers:

1. **Golden fixtures** (tools/graftlint/fixtures/): per rule, a positive
   snippet must produce findings (and a non-zero CLI exit), a negative
   snippet must be clean, and a pragma-suppressed snippet must be clean
   while COUNTING the suppression — pragmas are visible debt, not
   silence.
2. **Real-tree gate**: ``python -m tools.graftlint spark_examples_tpu/``
   exits 0 on this tree — the same blocking invocation CI runs.
3. **Schema-sharing meta-test**: the span/metric name sets graftlint
   extracts from the real tree must match ``scripts/validate_trace.py``
   exactly, and the rule must provably read the schema FROM that script
   (same module object), so the static and runtime gates can never
   drift apart.
"""

import os
import shutil
import subprocess
import sys

import pytest

from tools.graftlint.engine import Project, find_root, load_config, run_lint
from tools.graftlint.rules import ALL_RULES
from tools.graftlint.rules import span_contract as span_contract_mod

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tools", "graftlint", "fixtures")

ALL_RULE_NAMES = [r.name for r in ALL_RULES]

# (fixture stem, rule name, source suffix) for the single-file rules;
# flag-registry uses directory fixtures and is parametrized separately.
SINGLE_FILE_RULES = [
    ("gl001", "jit-purity", ".py"),
    ("gl002", "dtype-discipline", ".py"),
    ("gl003", "span-contract", ".py"),
    ("gl005", "resilience-routing", ".py"),
    ("gl006", "native-gil", ".cpp"),
    ("gl007", "lock-discipline", ".py"),
    ("gl008", "deadlock-order", ".py"),
    ("gl009", "guarded-fields", ".py"),
    ("gl010", "collective-congruence", ".py"),
    ("gl011", "donation-aliasing", ".py"),
    ("gl012", "retrace-discipline", ".py"),
    ("gl013", "atomic-commit", ".py"),
    ("gl014", "fencing-discipline", ".py"),
]


def _mini_project(tmp_path, rule_name, fixture_files, extra_rule_cfg=()):
    """A throwaway project enabling exactly one rule, scoped to '.'."""
    lines = ["[tool.graftlint]", "exclude = []"]
    for name in ALL_RULE_NAMES:
        lines.append(f'[tool.graftlint.rules."{name}"]')
        lines.append(f"enabled = {'true' if name == rule_name else 'false'}")
        if name == rule_name:
            lines.append('paths = ["."]')
            lines.extend(extra_rule_cfg)
    (tmp_path / "pyproject.toml").write_text("\n".join(lines) + "\n")
    for f in fixture_files:
        shutil.copy(os.path.join(FIXTURES, f), tmp_path)
    return str(tmp_path)


class TestGoldenFixtures:
    @pytest.mark.parametrize("stem,rule,ext", SINGLE_FILE_RULES)
    def test_positive_fixture_reports(self, tmp_path, stem, rule, ext):
        root = _mini_project(tmp_path, rule, [f"{stem}_positive{ext}"])
        findings, suppressed = run_lint(root, [])
        assert findings, f"{rule} found nothing in its golden positive"
        assert all(f.rule == rule for f in findings)
        assert not suppressed

    @pytest.mark.parametrize("stem,rule,ext", SINGLE_FILE_RULES)
    def test_negative_fixture_clean(self, tmp_path, stem, rule, ext):
        root = _mini_project(tmp_path, rule, [f"{stem}_negative{ext}"])
        findings, suppressed = run_lint(root, [])
        assert findings == []
        assert not suppressed

    @pytest.mark.parametrize("stem,rule,ext", SINGLE_FILE_RULES)
    def test_pragma_suppresses_and_counts(self, tmp_path, stem, rule, ext):
        root = _mini_project(tmp_path, rule, [f"{stem}_suppressed{ext}"])
        findings, suppressed = run_lint(root, [])
        assert findings == []
        assert suppressed.get(rule, 0) >= 1, (
            "suppression must be COUNTED, not silently dropped"
        )

    @pytest.mark.parametrize("kind,expect", [("positive", True), ("negative", False)])
    def test_flag_registry_fixture(self, tmp_path, kind, expect):
        src = os.path.join(FIXTURES, f"gl004_{kind}")
        for f in os.listdir(src):
            shutil.copy(os.path.join(src, f), tmp_path)
        _mini_project(
            tmp_path,
            "flag-registry",
            [],
            extra_rule_cfg=[
                'config_module = "config.py"',
                'cli_module = "main.py"',
                "script_paths = []",
                'doc_paths = ["README.md"]',
            ],
        )
        findings, _ = run_lint(str(tmp_path), [])
        assert bool(findings) == expect, [f.human() for f in findings]
        if expect:
            messages = "\n".join(f.message for f in findings)
            # All three sync directions must be represented:
            assert "dead flag" in messages
            assert "no CLI flag" in messages
            assert "stale documentation" in messages

    @pytest.mark.parametrize("stem,rule,ext", SINGLE_FILE_RULES)
    def test_cli_exits_nonzero_on_positive(self, tmp_path, stem, rule, ext):
        """The acceptance-criteria form: the CLI itself gates."""
        root = _mini_project(tmp_path, rule, [f"{stem}_positive{ext}"])
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "--root", root],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "GL0" in proc.stdout

    def test_cli_exits_nonzero_on_flag_registry_positive(self, tmp_path):
        src = os.path.join(FIXTURES, "gl004_positive")
        for f in os.listdir(src):
            shutil.copy(os.path.join(src, f), tmp_path)
        _mini_project(
            tmp_path,
            "flag-registry",
            [],
            extra_rule_cfg=[
                'config_module = "config.py"',
                'cli_module = "main.py"',
                "script_paths = []",
                'doc_paths = ["README.md"]',
            ],
        )
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "tools.graftlint",
                "--root",
                str(tmp_path),
            ],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "GL004" in proc.stdout


class TestRealTreeGate:
    def test_tree_is_clean(self):
        """`python -m tools.graftlint spark_examples_tpu/` exits 0 —
        the exact blocking invocation CI runs."""
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "tools.graftlint",
                "spark_examples_tpu/",
            ],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_jsonl_output_is_machine_readable(self):
        import json

        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "tools.graftlint",
                "--format",
                "jsonl",
                "spark_examples_tpu/",
            ],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        objs = [json.loads(ln) for ln in lines]
        assert "summary" in objs[-1]
        # The deliberate session-root suppression is visible data:
        assert objs[-1]["summary"]["suppressed"].get("span-contract", 0) >= 1

    def test_list_rules_names_all_twelve(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "--list-rules"],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 0
        for code in (
            "GL001",
            "GL002",
            "GL003",
            "GL004",
            "GL005",
            "GL006",
            "GL007",
            "GL008",
            "GL009",
            "GL010",
            "GL011",
            "GL012",
        ):
            assert code in proc.stdout

    def test_self_lint_is_clean(self):
        """The analyzer holds itself to its own concurrency bar: the
        CI self-lint leg (`python -m tools.graftlint tools/graftlint`)
        exits 0."""
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "tools.graftlint",
                "tools/graftlint",
            ],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


class TestSchemaSharing:
    """The span/metric contract rule and scripts/validate_trace.py must
    read from ONE name-set source — asserted, not assumed."""

    @pytest.fixture()
    def project(self):
        return Project(REPO_ROOT, load_config(REPO_ROOT))

    def test_rule_loads_schema_from_validate_trace(self):
        schema = span_contract_mod.load_schema(REPO_ROOT)
        assert schema is not None
        # The rule's schema object IS the script: same file, same sets.
        assert schema.__file__ == os.path.join(
            REPO_ROOT, "scripts", "validate_trace.py"
        )
        assert hasattr(schema, "_INGEST_SPANS")

    def test_extracted_ingest_spans_match_schema_exactly(self, project):
        schema = span_contract_mod.load_schema(REPO_ROOT)
        extracted = {
            name
            for name in span_contract_mod.extract_span_names(project)
            if name.startswith("ingest.")
        }
        assert extracted == set(schema._INGEST_SPANS), (
            "emitted ingest.* span literals and the validate_trace "
            "schema diverged — change both sides in one PR"
        )

    def test_extracted_job_spans_match_schema_exactly(self, project):
        schema = span_contract_mod.load_schema(REPO_ROOT)
        extracted = {
            name
            for name in span_contract_mod.extract_span_names(project)
            if name.startswith("job.")
        }
        assert extracted == set(schema._JOB_SPANS), (
            "emitted job.* span literals and the validate_trace "
            "schema diverged — change both sides in one PR"
        )

    def test_extracted_sparse_spans_match_schema_exactly(self, project):
        schema = span_contract_mod.load_schema(REPO_ROOT)
        extracted = {
            name
            for name in span_contract_mod.extract_span_names(project)
            if name.startswith("gramian.sparse.")
        }
        assert extracted == set(schema._SPARSE_SPANS), (
            "emitted gramian.sparse.* span literals and the "
            "validate_trace schema diverged — change both sides in one "
            "PR"
        )

    def test_extracted_pairhmm_spans_match_schema_exactly(self, project):
        schema = span_contract_mod.load_schema(REPO_ROOT)
        extracted = {
            name
            for name in span_contract_mod.extract_span_names(project)
            if name.startswith("pairhmm.")
        }
        assert extracted == set(schema._PAIRHMM_SPANS), (
            "emitted pairhmm.* span literals and the validate_trace "
            "schema diverged — change both sides in one PR"
        )

    def test_contract_metrics_registered_with_required_labels(self, project):
        schema = span_contract_mod.load_schema(REPO_ROOT)
        regs = span_contract_mod.extract_metric_registrations(project)
        for name in (*schema._WIRE_COUNTERS, schema._WIRE_HISTOGRAM):
            assert name in regs, f"wire metric {name} not registered"
            for _, _, _, labels in regs[name]:
                assert "transport" in labels
        for name in (*schema._INGEST_COUNTERS, schema._INGEST_HISTOGRAM):
            assert name in regs, f"ingest metric {name} not registered"
            for _, _, _, labels in regs[name]:
                assert "mode" in labels
        for name, label in schema._LABELED_COUNTERS.items():
            assert name in regs, f"labeled counter {name} not registered"
            for _, _, _, labels in regs[name]:
                assert label in labels, (
                    f"{name} registration missing .labels({label}=...)"
                )

    def test_schema_drift_is_detected(self, tmp_path):
        """End-to-end drift proof: a tree emitting an ingest span the
        schema doesn't know fails the rule in BOTH directions."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "feed.py").write_text(
            "from spark_examples_tpu import obs\n\n\n"
            "def stage():\n"
            "    with obs.span('ingest.typo'):\n"
            "        pass\n"
        )
        scripts = tmp_path / "scripts"
        scripts.mkdir()
        (scripts / "validate_trace.py").write_text(
            "_INGEST_SPANS = {'ingest.slice'}\n"
        )
        lines = ["[tool.graftlint]", "exclude = []"]
        for name in ALL_RULE_NAMES:
            lines.append(f'[tool.graftlint.rules."{name}"]')
            enabled = name == "span-contract"
            lines.append(f"enabled = {'true' if enabled else 'false'}")
            if enabled:
                lines.append('paths = ["pkg"]')
        (tmp_path / "pyproject.toml").write_text("\n".join(lines) + "\n")
        findings, _ = run_lint(str(tmp_path), [])
        messages = "\n".join(f.message for f in findings)
        assert "ingest.typo" in messages  # emitted-but-unknown direction
        assert "ingest.slice" in messages  # schema-but-unemitted direction

    def test_job_span_drift_is_detected(self, tmp_path):
        """The serving tier's job.* family gets the same two-way drift
        gate as the ingest sub-phases."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "tier.py").write_text(
            "from spark_examples_tpu import obs\n\n\n"
            "def run():\n"
            "    with obs.span('job.typo'):\n"
            "        pass\n"
        )
        scripts = tmp_path / "scripts"
        scripts.mkdir()
        (scripts / "validate_trace.py").write_text(
            "_JOB_SPANS = {'job.run'}\n"
        )
        lines = ["[tool.graftlint]", "exclude = []"]
        for name in ALL_RULE_NAMES:
            lines.append(f'[tool.graftlint.rules."{name}"]')
            enabled = name == "span-contract"
            lines.append(f"enabled = {'true' if enabled else 'false'}")
            if enabled:
                lines.append('paths = ["pkg"]')
        (tmp_path / "pyproject.toml").write_text("\n".join(lines) + "\n")
        findings, _ = run_lint(str(tmp_path), [])
        messages = "\n".join(f.message for f in findings)
        assert "job.typo" in messages  # emitted-but-unknown direction
        assert "job.run" in messages  # schema-but-unemitted direction

    def test_sparse_span_drift_is_detected(self, tmp_path):
        """The sparse Gramian's gramian.sparse.* family gets the same
        two-way drift gate as the ingest/job span sets."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "sparse.py").write_text(
            "from spark_examples_tpu import obs\n\n\n"
            "def accumulate():\n"
            "    with obs.span('gramian.sparse.typo'):\n"
            "        pass\n"
        )
        scripts = tmp_path / "scripts"
        scripts.mkdir()
        (scripts / "validate_trace.py").write_text(
            "_SPARSE_SPANS = {'gramian.sparse.window'}\n"
        )
        lines = ["[tool.graftlint]", "exclude = []"]
        for name in ALL_RULE_NAMES:
            lines.append(f'[tool.graftlint.rules."{name}"]')
            enabled = name == "span-contract"
            lines.append(f"enabled = {'true' if enabled else 'false'}")
            if enabled:
                lines.append('paths = ["pkg"]')
        (tmp_path / "pyproject.toml").write_text("\n".join(lines) + "\n")
        findings, _ = run_lint(str(tmp_path), [])
        messages = "\n".join(f.message for f in findings)
        assert "gramian.sparse.typo" in messages
        assert "gramian.sparse.window" in messages


class TestEngineBehavior:
    def test_find_root_walks_up(self, tmp_path):
        (tmp_path / "pyproject.toml").write_text("[tool.graftlint]\n")
        nested = tmp_path / "a" / "b"
        nested.mkdir(parents=True)
        assert find_root(str(nested)) == str(tmp_path)

    def test_path_narrowing_keeps_project_wide_rules(self, tmp_path):
        """CLI path scoping must not hide cross-file contract breaks."""
        src = os.path.join(FIXTURES, "gl004_positive")
        for f in os.listdir(src):
            shutil.copy(os.path.join(src, f), tmp_path)
        (tmp_path / "other").mkdir()
        _mini_project(
            tmp_path,
            "flag-registry",
            [],
            extra_rule_cfg=[
                'config_module = "config.py"',
                'cli_module = "main.py"',
                "script_paths = []",
                'doc_paths = ["README.md"]',
            ],
        )
        # Narrow to an unrelated subdir: flag-registry still reports.
        findings, _ = run_lint(str(tmp_path), ["other"])
        assert findings

    def test_syntax_error_fails_the_gate(self, tmp_path):
        """An unparseable file is skipped by every rule — so it must
        surface as its own (unsuppressible) finding, not a green exit."""
        root = _mini_project(tmp_path, "jit-purity", [])
        (tmp_path / "broken.py").write_text("def broken(:\n")
        findings, _ = run_lint(root, [])
        assert len(findings) == 1
        assert findings[0].code == "GL000"
        assert findings[0].path == "broken.py"

    def test_dot_prefixed_paths_survive_walk_and_exclude(self, tmp_path):
        """Regression: lstrip('./') stripped a charset, corrupting
        dot-prefixed names — hiding violations and deadening the
        shipped '.sanitize' exclude."""
        root = _mini_project(tmp_path, "native-gil", [])
        (tmp_path / ".hidden.cpp").write_text("PyObject* p;\n")
        findings, _ = run_lint(root, [])
        assert [f.path for f in findings] == [".hidden.cpp"]
        # And an exclude entry for the dot-dir actually excludes it:
        sub = tmp_path / ".sanitize"
        sub.mkdir()
        (sub / "gen.cpp").write_text("PyGILState_Ensure();\n")
        cfg = (tmp_path / "pyproject.toml").read_text()
        (tmp_path / "pyproject.toml").write_text(
            cfg.replace("exclude = []", 'exclude = [".sanitize"]')
        )
        findings, _ = run_lint(str(tmp_path), [])
        assert [f.path for f in findings] == [".hidden.cpp"]

    def test_cli_relative_paths_resolve_against_root(self, tmp_path):
        """Regression: positional paths resolved against cwd, so
        --root from elsewhere scoped every rule to nothing (false
        green)."""
        root = _mini_project(
            tmp_path, "native-gil", ["gl006_positive.cpp"]
        )
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "tools.graftlint",
                "--root",
                root,
                "gl006_positive.cpp",
            ],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,  # a cwd that is NOT the project root
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "GL006" in proc.stdout

    def test_jit_named_function_call_form_is_checked(self, tmp_path):
        """`jax.jit(named_fn)(x)` traces named_fn's body exactly like a
        decorator — the parallel/sharded.py idiom."""
        root = _mini_project(tmp_path, "jit-purity", [])
        (tmp_path / "mod.py").write_text(
            "import jax\n"
            "import numpy as np\n\n\n"
            "def _local(x):\n"
            "    return np.asarray(x)\n\n\n"
            "def run(x):\n"
            "    return jax.jit(_local)(x)\n"
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 1
        assert findings[0].rule == "jit-purity"

    def test_cpp_escaped_newline_keeps_line_numbers(self, tmp_path):
        """Regression: blanking a backslash-newline escape merged two
        source lines, shifting later findings (and pragma lookups)."""
        root = _mini_project(tmp_path, "native-gil", [])
        (tmp_path / "a.cpp").write_text(
            'const char* s = "a\\\n b";\n'  # escaped newline in literal
            "int x;\n"
            "PyObject* p;\n"  # line 4
        )
        findings, _ = run_lint(root, [])
        assert [(f.path, f.line) for f in findings] == [("a.cpp", 4)]
