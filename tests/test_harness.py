"""Smoke test that the virtual 8-device CPU mesh is actually wired up."""

import jax


def test_virtual_device_count():
    assert jax.default_backend() == "cpu"
    assert jax.device_count() == 8


def test_device_prefetch_order_and_error():
    import numpy as np
    import pytest

    from spark_examples_tpu.arrays.feed import device_prefetch

    blocks = [np.full((4, 4), i, np.int8) for i in range(7)]
    out = [int(np.asarray(b)[0, 0]) for b in device_prefetch(iter(blocks))]
    assert out == list(range(7))

    def failing():
        yield np.zeros((2, 2), np.int8)
        raise IOError("ingest died")

    it = device_prefetch(failing())
    next(it)
    with pytest.raises(IOError, match="ingest died"):
        list(it)


def test_device_prefetch_abandoned_consumer_releases_producer():
    import threading
    import time

    import numpy as np

    from spark_examples_tpu.arrays.feed import device_prefetch

    started = threading.Event()
    n_produced = []

    def blocks():
        for i in range(100):
            started.set()
            n_produced.append(i)
            yield np.zeros((64, 64), np.int8)

    it = device_prefetch(blocks(), depth=2)
    next(it)
    started.wait(5)
    it.close()  # consumer abandons mid-stream
    deadline = time.time() + 5
    while time.time() < deadline and threading.active_count() > 20:
        time.sleep(0.05)
    time.sleep(0.3)
    produced_after_close = len(n_produced)
    time.sleep(0.5)
    # Producer must have stopped: no further blocks drawn from the source.
    assert len(n_produced) <= produced_after_close + 1
