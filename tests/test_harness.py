"""Smoke test that the virtual 8-device CPU mesh is actually wired up."""

import os

import jax


def test_virtual_device_count():
    assert jax.default_backend() == "cpu"
    assert jax.device_count() == 8


def test_device_prefetch_order_and_error():
    import numpy as np
    import pytest

    from spark_examples_tpu.arrays.feed import device_prefetch

    blocks = [np.full((4, 4), i, np.int8) for i in range(7)]
    out = [int(np.asarray(b)[0, 0]) for b in device_prefetch(iter(blocks))]
    assert out == list(range(7))

    def failing():
        yield np.zeros((2, 2), np.int8)
        raise IOError("ingest died")

    it = device_prefetch(failing())
    next(it)
    with pytest.raises(IOError, match="ingest died"):
        list(it)


def test_device_prefetch_abandoned_consumer_releases_producer():
    import threading
    import time

    import numpy as np

    from spark_examples_tpu.arrays.feed import device_prefetch

    started = threading.Event()
    n_produced = []

    def blocks():
        for i in range(100):
            started.set()
            n_produced.append(i)
            yield np.zeros((64, 64), np.int8)

    it = device_prefetch(blocks(), depth=2)
    next(it)
    started.wait(5)
    it.close()  # consumer abandons mid-stream
    deadline = time.time() + 5
    while time.time() < deadline and threading.active_count() > 20:
        time.sleep(0.05)
    time.sleep(0.3)
    produced_after_close = len(n_produced)
    time.sleep(0.5)
    # Producer must have stopped: no further blocks drawn from the source.
    assert len(n_produced) <= produced_after_close + 1


def test_device_prefetch_producer_exception_at_depth_gt_2():
    """A producer failure must surface in the consumer at ANY staging
    depth — with depth > 2 several good blocks are already queued ahead
    of the error, and all of them must still be delivered first."""
    import numpy as np
    import pytest

    from spark_examples_tpu.arrays.feed import device_prefetch

    def failing():
        for i in range(5):
            yield np.full((3, 3), i, np.int8)
        raise IOError("builder worker died")

    it = device_prefetch(failing(), depth=4)
    got = []
    with pytest.raises(IOError, match="builder worker died"):
        for b in it:
            got.append(int(np.asarray(b)[0, 0]))
    assert got == [0, 1, 2, 3, 4]  # nothing staged was dropped


def test_device_prefetch_consumer_cancel_at_depth_gt_2():
    """Abandoning the consumer mid-stream with a deep queue must stop
    the producer promptly: with depth > 2 a blocked q.put holds MORE
    staged device blocks alive, so a leak here is depth× worse."""
    import threading
    import time

    import numpy as np

    from spark_examples_tpu.arrays.feed import device_prefetch

    started = threading.Event()
    n_produced = []

    def blocks():
        for i in range(1000):
            started.set()
            n_produced.append(i)
            yield np.zeros((32, 32), np.int8)

    it = device_prefetch(blocks(), depth=5)
    next(it)
    started.wait(5)
    it.close()  # consumer abandons with ~depth blocks staged
    deadline = time.time() + 5
    stable_at = None
    while time.time() < deadline:
        n = len(n_produced)
        time.sleep(0.3)
        if len(n_produced) == n:
            stable_at = n
            break
    # Producer stopped well short of the stream (bounded by the window
    # in flight when close() landed), not at exhaustion.
    assert stable_at is not None and stable_at < 1000


def test_device_prefetch_cancel_while_producer_blocked_in_fetch():
    """Consumer cancellation against an UNBOUNDED-LATENCY producer (the
    cold-stream shape: a shard fetch that may take arbitrarily long on
    a slow wire). close() must return promptly even though the producer
    thread is parked INSIDE its fetch, and once the fetch finally
    returns the producer must notice the cancel and stop — no further
    frames drawn, no thread leak."""
    import threading
    import time

    import numpy as np

    from spark_examples_tpu.arrays.feed import device_prefetch

    in_fetch = threading.Event()
    release = threading.Event()
    produced = []

    def frames():
        produced.append(0)
        yield np.zeros((8, 8), np.int8)
        in_fetch.set()
        # The unbounded-latency fetch (bounded here only so a failing
        # implementation can't hang the suite).
        release.wait(30)
        produced.append(1)
        yield np.zeros((8, 8), np.int8)
        produced.append(2)
        yield np.zeros((8, 8), np.int8)

    it = device_prefetch(frames(), depth=2)
    next(it)
    assert in_fetch.wait(5)
    t0 = time.monotonic()
    it.close()  # cancel while frame 2 is still "on the wire"
    assert time.monotonic() - t0 < 1.0  # close never waits on the fetch
    release.set()  # the slow fetch lands AFTER the cancel
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(produced) < 2:
        time.sleep(0.05)
    time.sleep(0.3)
    # The in-flight fetch may complete (it was already running) but the
    # producer must stop there: frame 3 is never drawn.
    assert len(produced) <= 2


def test_device_prefetch_producer_exception_with_frames_in_flight():
    """A producer that dies while staged frames are still in flight
    (queued, unconsumed — the consumer hasn't even started draining):
    every already-staged frame must still be delivered, in order, and
    the failure must surface AFTER them — never a silent drop, never a
    lost exception."""
    import time

    import numpy as np
    import pytest

    from spark_examples_tpu.arrays.feed import device_prefetch

    def failing():
        for i in range(3):
            yield np.full((4, 4), i, np.int8)
        raise IOError("wire fetch died mid-stream")

    it = device_prefetch(failing(), depth=3)
    # Let the producer fill the queue AND die before the consumer takes
    # a single frame — the frames are "in flight" when the error lands.
    time.sleep(0.5)
    got = []
    with pytest.raises(IOError, match="wire fetch died mid-stream"):
        for b in it:
            got.append(int(np.asarray(b)[0, 0]))
    assert got == [0, 1, 2]


def test_int8_int32_gramian_exact():
    """int8 x int8 -> int32 einsum (the MXU int-matmul path) is exact and
    matches the f32 path."""
    import numpy as np
    import jax.numpy as jnp

    from spark_examples_tpu.ops import gramian

    rng = np.random.default_rng(0)
    x = (rng.random((64, 512)) < 0.4).astype(np.int8)
    g_int = gramian(x, compute_dtype=jnp.int8, accum_dtype=jnp.int32)
    g_f32 = gramian(x)
    assert g_int.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(g_int), np.asarray(g_f32))


def test_gramian_packed_transfer_path_bit_identical():
    """The bit-packed transfer path (8x fewer host->device bytes) must be
    bit-identical to the dense path, including non-multiple-of-8 block
    widths whose packbits pad bits unpack to inert zero columns."""
    import numpy as np
    import jax.numpy as jnp

    from spark_examples_tpu.ops.gramian import (
        gramian_blockwise,
        pack_indicator_block,
        unpack_indicator_block,
    )

    rng = np.random.default_rng(3)
    for n, v in ((17, 96), (33, 100)):
        blocks = [
            (rng.random((n, v)) < 0.2).astype(np.int8) for _ in range(3)
        ]
        dense = np.asarray(gramian_blockwise(blocks, n))
        packed = np.asarray(gramian_blockwise(blocks, n, packed=True))
        np.testing.assert_array_equal(dense, packed)
        xp = pack_indicator_block(blocks[0])
        np.testing.assert_array_equal(
            np.asarray(unpack_indicator_block(jnp.asarray(xp), v)),
            blocks[0],
        )


def test_pack_indicator_block_rejects_non_indicator_values():
    """Packing collapses any nonzero to 1; a dosage-valued (0/1/2) block
    must be rejected loudly instead of silently producing a wrong Gramian
    (round-3 advisor finding on the hard-coded packed default)."""
    import numpy as np
    import pytest

    from spark_examples_tpu.ops.gramian import pack_indicator_block

    ok = np.zeros((4, 16), dtype=np.int8)
    ok[1, 3] = 1
    pack_indicator_block(ok)  # 0/1 passes
    bad = ok.copy()
    bad[2, 5] = 2
    with pytest.raises(ValueError, match="0/1 indicator"):
        pack_indicator_block(bad)
    neg = ok.copy()
    neg[0, 0] = -1
    with pytest.raises(ValueError, match="0/1 indicator"):
        pack_indicator_block(neg)
    # Fractional dosages sit inside [0, 1] but still collapse to 1 under
    # astype(bool) — the guard must be an exact-0/1 check, not a range
    # check (round-4 re-review finding).
    frac = np.zeros((4, 16), dtype=np.float32)
    frac[1, 2] = 0.5
    with pytest.raises(ValueError, match="0/1 indicator"):
        pack_indicator_block(frac)


def test_gramian_env_escape_hatch_per_call(monkeypatch):
    """SPARK_EXAMPLES_TPU_GRAMIAN is resolved OUTSIDE jit on every call:
    flipping it after a first (cached) trace must still take effect, and
    an invalid value must raise even after prior successful calls — the
    round-3 review found the original trace-time read silently froze the
    first call's choice into the jit cache."""
    import numpy as np
    import jax.numpy as jnp

    from spark_examples_tpu.ops.gramian import (
        gramian,
        resolve_gramian_compute_dtype,
    )

    x = (np.random.default_rng(0).random((16, 32)) < 0.4).astype(np.int8)
    g_auto = np.asarray(gramian(x))  # traces+caches the int8 auto path
    assert resolve_gramian_compute_dtype(x.dtype, jnp.float32) == jnp.int8

    monkeypatch.setenv("SPARK_EXAMPLES_TPU_GRAMIAN", "f32")
    assert (
        resolve_gramian_compute_dtype(x.dtype, jnp.float32) == jnp.float32
    )
    np.testing.assert_array_equal(np.asarray(gramian(x)), g_auto)

    monkeypatch.setenv("SPARK_EXAMPLES_TPU_GRAMIAN", "bogus")
    try:
        gramian(x)
    except ValueError as e:
        assert "SPARK_EXAMPLES_TPU_GRAMIAN" in str(e)
    else:
        raise AssertionError("invalid env value must raise per call")


def test_debug_numerics_and_range_guard():
    import numpy as np
    import jax.numpy as jnp
    import pytest

    from spark_examples_tpu.utils.debug import (
        assert_exact_f32_range,
        debug_numerics,
    )

    assert_exact_f32_range(jnp.ones((3, 3)))
    with pytest.raises(AssertionError, match="2\\^24"):
        assert_exact_f32_range(jnp.full((2, 2), float(1 << 24)))
    with debug_numerics():
        with pytest.raises(FloatingPointError):
            _ = jnp.log(jnp.zeros(2)) * 0  # -inf triggers debug_infs


def test_graft_entry_contract():
    """The driver contract: entry() compiles; dryrun_multichip works for
    even, odd, and prime device counts."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (128, 2)
    for n in (1, 3, 8):
        ge.dryrun_multichip(n)


def test_compile_cache_dir_is_host_keyed(tmp_path):
    """The persistent XLA cache dir must embed the host CPU feature set so
    a cache populated on a different host can never feed this one illegal
    instructions (round-2 bench tail SIGILL-risk warning)."""
    from spark_examples_tpu.utils.compile_cache import (
        compilation_cache_dir,
        host_feature_key,
    )

    key = host_feature_key()
    assert len(key) == 12
    assert key == host_feature_key()  # stable within a host
    path = compilation_cache_dir(str(tmp_path))
    assert os.path.isdir(path)
    assert os.path.basename(path) == f"host-{key}"
