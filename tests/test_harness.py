"""Smoke test that the virtual 8-device CPU mesh is actually wired up."""

import jax


def test_virtual_device_count():
    assert jax.default_backend() == "cpu"
    assert jax.device_count() == 8
