"""The SPMD dispatch-analysis graftlint layer (GL010-GL012) and its
runtime backstop.

The golden fixtures in tests/test_graftlint.py prove each rule's
headline behavior; this file drills the ENGINE pieces whose
mis-modeling would make the rules silently wrong on exactly the
protocol code they gate — the taint/agreement classification behind
GL010, the wrapper-transitivity and loop-rebind handling behind GL011,
the derivation analysis behind GL012 — plus the docs/CONCURRENCY.md
collective-order drift gate and the collectivecheck digest unit
behavior."""

import json
import os
import re
import textwrap

from tools.graftlint.engine import Project, load_config, run_lint
from tools.graftlint.rules.collective_congruence import collective_order

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mini(tmp_path, rule_name, files):
    """One-rule project over inline sources (the test_graftlint_flow
    harness, reused for the SPMD rules)."""
    from tools.graftlint.rules import ALL_RULES

    lines = ["[tool.graftlint]", "exclude = []"]
    for r in ALL_RULES:
        lines.append(f'[tool.graftlint.rules."{r.name}"]')
        lines.append(
            f"enabled = {'true' if r.name == rule_name else 'false'}"
        )
        if r.name == rule_name:
            lines.append('paths = ["."]')
    (tmp_path / "pyproject.toml").write_text("\n".join(lines) + "\n")
    for name, src in files.items():
        (tmp_path / name).write_text(textwrap.dedent(src))
    return str(tmp_path)


class TestCollectiveCongruence:
    def test_agreed_predicate_from_gather_is_clean(self, tmp_path):
        """The all-raise-together protocol shape: a raise governed by
        gathered data ahead of later collectives is sanctioned."""
        root = _mini(
            tmp_path,
            "collective-congruence",
            {
                "m.py": """
                import numpy as np

                def step(exchange, step, windows):
                    gang = next(windows, None)
                    code = -1 if gang is None else 0
                    exchange.post_header(step, np.array([code]))
                    peers = exchange.gather_headers(step, 1)
                    live = peers[peers[:, 0] >= 0]
                    if live.size == 0:
                        return None
                    exchange.post_confirm(step, True)
                    return exchange.gather_confirms(step)
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert findings == []

    def test_tainted_terminal_branch_governs_later_collectives(
        self, tmp_path
    ):
        """One process returning on its local stream state while peers
        proceed into the gather is THE one-sided deadlock."""
        root = _mini(
            tmp_path,
            "collective-congruence",
            {
                "m.py": """
                import numpy as np

                def step(exchange, step, windows):
                    gang = next(windows, None)
                    if gang is None:
                        return None
                    exchange.post_header(step, np.asarray(gang))
                    return exchange.gather_headers(step, 1)
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 2  # post_header AND gather_headers
        assert all("host-local state" in f.message for f in findings)

    def test_stream_loop_governs_collectives(self, tmp_path):
        root = _mini(
            tmp_path,
            "collective-congruence",
            {
                "m.py": """
                from jax.experimental import multihost_utils

                def per_block(blocks):
                    for xb in blocks:
                        multihost_utils.process_allgather(xb)
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 1

    def test_enumerate_does_not_launder_a_stream(self, tmp_path):
        """Wrapping a per-process stream in enumerate()/sorted() must
        not make its iteration look agreed — the length still
        diverges across processes."""
        root = _mini(
            tmp_path,
            "collective-congruence",
            {
                "m.py": """
                from jax.experimental import multihost_utils

                def per_block(blocks):
                    for i, xb in enumerate(blocks):
                        multihost_utils.process_allgather(xb)
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 1

    def test_enumerate_over_gathered_data_is_clean(self, tmp_path):
        """enumerate over agreement-derived data (the `for i, row in
        enumerate(peers)` protocol idiom) stays sanctioned."""
        root = _mini(
            tmp_path,
            "collective-congruence",
            {
                "m.py": """
                import numpy as np
                from jax.experimental import multihost_utils

                def step(exchange, step, payload):
                    peers = exchange.gather_headers(step, 1)
                    live = peers[peers[:, 0] >= 0]
                    for i, row in enumerate(live):
                        payload = multihost_utils.process_allgather(
                            payload
                        )
                    return payload
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert findings == []

    def test_bounded_range_loop_is_clean(self, tmp_path):
        root = _mini(
            tmp_path,
            "collective-congruence",
            {
                "m.py": """
                from jax.experimental import multihost_utils

                def rounds(g, total_rounds):
                    for _ in range(total_rounds):
                        g = multihost_utils.process_allgather(g)
                    return g
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert findings == []

    def test_exception_variable_taints_derived_names(self, tmp_path):
        """`except E as e: flag = e` then branching into a collective
        on `flag` is the raise-on-one-process shape."""
        root = _mini(
            tmp_path,
            "collective-congruence",
            {
                "m.py": """
                import jax

                def risky(x, source):
                    failed = None
                    try:
                        payload = source.build(x)
                    except ValueError as e:
                        failed = e
                    if failed is None:
                        x = jax.lax.psum(x, "data")
                    return x
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 1
        assert "psum" in findings[0].message

    def test_collective_in_lax_cond_named_branch(self, tmp_path):
        """Named local functions referenced by lax.cond are inspected,
        not just lambdas."""
        root = _mini(
            tmp_path,
            "collective-congruence",
            {
                "m.py": """
                import jax

                def tile(g, flag):
                    def _with_sum(v):
                        return jax.lax.psum(v, "data")

                    def _skip(v):
                        return v

                    return jax.lax.cond(flag, _with_sum, _skip, g)
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 1
        assert "lax.cond" in findings[0].message


class TestDonationAliasing:
    def test_wrapper_transitivity_gates_wrapper_call_sites(self, tmp_path):
        """gramian_accumulate-style wrappers: the plain function
        forwarding into a donated position donates its own parameter,
        so ITS call sites are checked."""
        root = _mini(
            tmp_path,
            "donation-aliasing",
            {
                "m.py": """
                from functools import partial
                import jax

                @partial(jax.jit, donate_argnums=(0,))
                def _accum_jit(g, xb):
                    return g + xb

                def accumulate(g, xb):
                    return _accum_jit(g, xb)

                def caller(g, xb):
                    g2 = accumulate(g, xb)
                    return g + g2  # g donated through the wrapper
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 1
        assert "read after" in findings[0].message

    def test_loop_rebind_is_safe_non_rebind_is_not(self, tmp_path):
        root = _mini(
            tmp_path,
            "donation-aliasing",
            {
                "m.py": """
                from functools import partial
                import jax

                @partial(jax.jit, donate_argnums=(0,))
                def _accum_jit(g, xb):
                    return g + xb

                def good(g, blocks):
                    for xb in blocks:
                        g = _accum_jit(g, xb)
                    return g

                def bad(g, blocks, sink):
                    for xb in blocks:
                        out = _accum_jit(g, xb)
                        sink.append(g)  # next iteration reads dead g
                    return out
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 1
        assert findings[0].line  # attributed to the bad call site

    def test_attribute_donation_names_other_accessors(self, tmp_path):
        root = _mini(
            tmp_path,
            "donation-aliasing",
            {
                "m.py": """
                from functools import partial
                import jax
                import jax.numpy as jnp

                @partial(jax.jit, donate_argnums=(0,))
                def _accum_jit(g, xb):
                    return g + xb

                class Tier:
                    def __init__(self):
                        self._g = jnp.zeros((4, 4))

                    def step(self, xb):
                        return _accum_jit(self._g, xb)

                    def snapshot(self):
                        return self._g
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 1
        assert "stored attribute" in findings[0].message
        assert "Tier.snapshot" in findings[0].message


class TestRetraceDiscipline:
    def test_jit_assignment_form_with_keyword_static(self, tmp_path):
        """`scatter = jax.jit(f, donate..., static_argnames=...)`
        assignment forms gate keyword-passed geometry statics."""
        root = _mini(
            tmp_path,
            "retrace-discipline",
            {
                "m.py": """
                import jax

                def _impl(x, n_rows):
                    return x[:n_rows]

                scatter = jax.jit(_impl, static_argnames=("n_rows",))

                def run(x, windows):
                    out = []
                    for idx, lens in windows:
                        out.append(scatter(x, n_rows=int(lens.size)))
                    return out
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 1
        assert "n_rows" in findings[0].message

    def test_shape_of_same_call_operand_is_blessed(self, tmp_path):
        """n_bits = 8 * xp.shape[1] where xp rides the same call: the
        operand's shape is already part of the executable key."""
        root = _mini(
            tmp_path,
            "retrace-discipline",
            {
                "m.py": """
                from functools import partial
                import jax

                @partial(jax.jit, static_argnames=("n_bits",))
                def _unpack_jit(g, xp, n_bits):
                    return g, xp, n_bits

                def accumulate(g, xp):
                    return _unpack_jit(g, xp, 8 * xp.shape[1])
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert findings == []

    def test_helper_call_blesses_raw_interior(self, tmp_path):
        """The bucket helper IS the blessing: raw stream geometry
        inside its arguments is exactly the sanctioned shape."""
        root = _mini(
            tmp_path,
            "retrace-discipline",
            {
                "m.py": """
                from functools import partial
                import jax

                @partial(jax.jit, static_argnames=("width",))
                def _panel_jit(x, width):
                    return x[:, :width]

                def run(x, windows, block_variants):
                    out = []
                    for idx, lens in windows:
                        out.append(
                            _panel_jit(
                                x,
                                dense_panel_width(
                                    int(lens.size), block_variants
                                ),
                            )
                        )
                    return out
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert findings == []


class TestCollectiveOrderDrift:
    """docs/CONCURRENCY.md embeds the GL010-derived per-function
    collective sequences as JSON; the doc and the derivation must never
    disagree (the GL008 lock-graph discipline applied to the SPMD
    dispatch surface)."""

    def _doc_order(self):
        path = os.path.join(REPO_ROOT, "docs", "CONCURRENCY.md")
        with open(path, encoding="utf-8") as f:
            text = f.read()
        section = text.split("## The collective order", 1)
        assert len(section) == 2, (
            "docs/CONCURRENCY.md lost its collective-order section"
        )
        m = re.search(r"```json\n(.*?)```", section[1], re.S)
        assert m, "collective-order section lost its JSON block"
        return json.loads(m.group(1))

    def test_documented_order_matches_derivation(self):
        derived = collective_order(
            Project(REPO_ROOT, load_config(REPO_ROOT))
        )
        assert self._doc_order() == derived, (
            "docs/CONCURRENCY.md and the GL010 derivation diverged — "
            "re-run `python -m tools.graftlint --collective-order` and "
            "update the doc in the same PR"
        )

    def test_pod_protocol_sequence_is_present(self):
        """The pod-sparse per-window protocol must appear with its full
        header→check→confirm order — losing it from the derivation
        would mean GL010 stopped seeing the protocol at all."""
        derived = collective_order(
            Project(REPO_ROOT, load_config(REPO_ROOT))
        )
        key = (
            "spark_examples_tpu/parallel/sharded.py::"
            "_synced_carrier_stream._produce_step"
        )
        assert derived[key] == [
            "post_header",
            "gather_headers",
            "post_check",
            "gather_checks",
            "post_confirm",
            "gather_confirms",
        ]


class TestCollectiveCheckBackstop:
    def test_digest_is_order_sensitive_and_nonnegative(self):
        from spark_examples_tpu.utils import collectivecheck as cc

        a = cc.step_digest(1, 0, [("scatter", (256, 8)), ("psum", (4,))])
        b = cc.step_digest(1, 0, [("psum", (4,)), ("scatter", (256, 8))])
        assert a != b
        assert a >= 0 and b >= 0
        # Deterministic across calls (peers must derive the same value).
        assert a == cc.step_digest(
            1, 0, [("scatter", (256, 8)), ("psum", (4,))]
        )
        # Step identity is part of the digest.
        assert a != cc.step_digest(
            1, 1, [("scatter", (256, 8)), ("psum", (4,))]
        )

    def test_verify_raises_on_divergence_with_step(self):
        import pytest

        from spark_examples_tpu.utils import collectivecheck as cc

        cc.verify_step_digests(3, [7, 7, 7], 7)  # agree: no raise
        with pytest.raises(RuntimeError) as ei:
            cc.verify_step_digests(5, [7, 8, 7], 7)
        assert "protocol step 5" in str(ei.value)
        assert "digests diverged" in str(ei.value)

    def test_enabled_reads_env_per_call(self, monkeypatch):
        from spark_examples_tpu.utils import collectivecheck as cc

        monkeypatch.delenv(cc.COLLECTIVE_CHECK_ENV, raising=False)
        assert not cc.collective_check_enabled()
        monkeypatch.setenv(cc.COLLECTIVE_CHECK_ENV, "1")
        assert cc.collective_check_enabled()
        monkeypatch.setenv(cc.COLLECTIVE_CHECK_ENV, "0")
        assert not cc.collective_check_enabled()
