"""Fused carrying-index ingest fast path ≡ the staged slow path.

The fast path (sources.stream_carrying / _carrying_records) exists because
per-call dataclass construction dominated host ingest at chr20 scale; its
contract is OBSERVABLE EQUALITY with stream_variants → af_filter →
carrying_sample_indices on every source type, stats included.
"""

import numpy as np
import pytest

from spark_examples_tpu.genomics.callsets import CallsetIndex
from spark_examples_tpu.genomics.datasets import (
    af_filter,
    carrying_sample_indices,
)
from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.genomics.shards import shards_for_references
from spark_examples_tpu.genomics.sources import FixtureSource, JsonlSource

REFS = "17:41196311:41277499"


def _slow(source, vsid, shards, indexes, min_af):
    out = []
    for shard in shards:
        stream = af_filter(source.stream_variants(vsid, shard), min_af)
        for v in stream:
            calls = carrying_sample_indices(v, indexes)
            if calls:
                out.append(calls)
    return out


def _fast(source, vsid, shards, indexes, min_af):
    out = []
    for shard in shards:
        out.extend(source.stream_carrying(vsid, shard, indexes, min_af))
    return out


def _cohort(**kw):
    return synthetic_cohort(
        12,
        80,
        seed=21,
        dropped_contig_every=9,
        reference_blocks_every=13,
        **kw,
    )


@pytest.mark.parametrize("min_af", [None, 0.2])
def test_fixture_source_parity(min_af):
    shards = shards_for_references(REFS, 20_000)
    slow_src, fast_src = _cohort(), _cohort()
    index = CallsetIndex.from_source(slow_src, [DEFAULT_VARIANT_SET_ID])
    slow = _slow(
        slow_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, min_af
    )
    fast = _fast(
        fast_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, min_af
    )
    assert fast == slow
    # Stats parity: same variants_read (counted post contig-drop,
    # pre AF-filter) and same request/partition accounting.
    assert fast_src.stats.variants_read == slow_src.stats.variants_read
    assert fast_src.stats.partitions == slow_src.stats.partitions


@pytest.mark.parametrize("min_af", [None, 0.2])
def test_jsonl_source_parity(tmp_path, min_af):
    _cohort().dump(str(tmp_path / "c"))
    shards = shards_for_references(REFS, 20_000)
    slow_src = JsonlSource(str(tmp_path / "c"))
    fast_src = JsonlSource(str(tmp_path / "c"))
    index = CallsetIndex.from_source(slow_src, [DEFAULT_VARIANT_SET_ID])
    assert _fast(
        fast_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, min_af
    ) == _slow(
        slow_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, min_af
    )
    assert fast_src.stats.variants_read == slow_src.stats.variants_read


@pytest.mark.parametrize("min_af", [None, 0.2])
def test_csr_direct_parity(tmp_path, min_af):
    """stream_carrying_csr + blocks_from_csr ≡ stream_carrying +
    blocks_from_calls — blocks bit-for-bit, stats identical. The CSR
    tier skips the array→list→array round-trip that was ~85% of warm
    host wall-clock at all-autosomes scale."""
    from spark_examples_tpu.arrays.blocks import (
        blocks_from_calls,
        blocks_from_csr,
    )

    _cohort().dump(str(tmp_path / "c"))
    shards = shards_for_references(REFS, 20_000)
    list_src = JsonlSource(str(tmp_path / "c"))
    csr_src = JsonlSource(str(tmp_path / "c"))
    index = CallsetIndex.from_source(list_src, [DEFAULT_VARIANT_SET_ID])

    lists = (
        calls
        for sh in shards
        for calls in list_src.stream_carrying(
            DEFAULT_VARIANT_SET_ID, sh, index.indexes, min_af
        )
    )
    want = list(blocks_from_calls(lists, index.size, 32))
    pairs = (
        csr_src.stream_carrying_csr(
            DEFAULT_VARIANT_SET_ID, sh, index.indexes, min_af
        )
        for sh in shards
    )
    got = list(blocks_from_csr(pairs, index.size, 32))
    assert len(got) == len(want)
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    assert csr_src.stats.variants_read == list_src.stats.variants_read
    assert csr_src.stats.partitions == list_src.stats.partitions


def test_csr_direct_unknown_callset_raises(tmp_path):
    """The CSR tier must fail on unknown callset ids exactly like the
    row tier (KeyError naming the id — VariantsPca.scala:59 analog)."""
    _cohort().dump(str(tmp_path / "c"))
    src = JsonlSource(str(tmp_path / "c"))
    index = CallsetIndex.from_source(src, [DEFAULT_VARIANT_SET_ID])
    shards = shards_for_references(REFS, 20_000)
    bad = {k: v for k, v in list(index.indexes.items())[:-1]}  # drop one
    with pytest.raises(KeyError):
        for sh in shards:
            src.stream_carrying_csr(DEFAULT_VARIANT_SET_ID, sh, bad)


@pytest.mark.parametrize("min_af", [None, 0.2])
def test_nonnumeric_af_behavior_identical_across_tiers(tmp_path, min_af):
    """A VCF "."-style AF must get the SAME treatment from the staged
    path, the fused record stream, and the CSR sidecar: missing → dropped
    under the filter, untouched without it (round-2 ADVICE: the sidecar
    dropped where the staged float() raised)."""
    import json

    cohort = _cohort()
    cohort.dump(str(tmp_path / "c"))
    cid = cohort.list_callsets(DEFAULT_VARIANT_SET_ID)[0].id
    bad = {
        "reference_name": "17",
        "start": 41_200_000,
        "end": 41_200_001,
        "reference_bases": "A",
        "variant_set_id": DEFAULT_VARIANT_SET_ID,
        "info": {"AF": ["."]},
        "calls": [{"callset_id": cid, "genotype": [1]}],
    }
    with open(tmp_path / "c" / "variants.jsonl", "a") as fh:
        fh.write(json.dumps(bad) + "\n")

    shards = shards_for_references(REFS, 20_000)
    slow_src = JsonlSource(str(tmp_path / "c"))
    fast_src = JsonlSource(str(tmp_path / "c"))
    index = CallsetIndex.from_source(slow_src, [DEFAULT_VARIANT_SET_ID])
    slow = _slow(
        slow_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, min_af
    )
    fast = _fast(
        fast_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, min_af
    )
    assert fast == slow
    # The record itself is served with the filter off, dropped with it on.
    clean = _slow(
        _cohort(), DEFAULT_VARIANT_SET_ID, shards, index.indexes, min_af
    )
    assert len(slow) == len(clean) + (0 if min_af else 1)


def test_http_source_parity():
    from spark_examples_tpu.genomics.service import (
        GenomicsServiceServer,
        HttpVariantSource,
    )

    server = GenomicsServiceServer(_cohort()).start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        shards = shards_for_references(REFS, 20_000)
        slow_src = HttpVariantSource(url)
        fast_src = HttpVariantSource(url)
        index = CallsetIndex.from_source(
            slow_src, [DEFAULT_VARIANT_SET_ID]
        )
        assert _fast(
            fast_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, 0.2
        ) == _slow(
            slow_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, 0.2
        )
    finally:
        server.stop()


def test_variant_object_fallback_parity():
    # A fixture holding built Variant objects takes the order-preserving
    # fallback; results must still match the staged path.
    raw = _cohort()
    from spark_examples_tpu.genomics.sources import variant_from_record

    objs = [
        v
        for rec in raw._variants
        if (v := variant_from_record(rec)) is not None
    ]
    obj_src = FixtureSource(variants=objs, callsets=raw._callsets)
    ref_src = _cohort()
    shards = shards_for_references(REFS, 20_000)
    index = CallsetIndex.from_source(ref_src, [DEFAULT_VARIANT_SET_ID])
    assert _fast(
        obj_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, 0.2
    ) == _slow(
        ref_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, 0.2
    )


def test_unknown_callset_raises_keyerror():
    src = _cohort()
    shards = shards_for_references(REFS, 100_000)
    with pytest.raises(KeyError):
        for _ in src.stream_carrying(
            DEFAULT_VARIANT_SET_ID, shards[0], {"not-a-callset": 0}
        ):
            pass


def test_fault_injection_fires_in_fast_path():
    src = _cohort()
    shard = shards_for_references(REFS, 100_000)[0]
    src._fail_once.add(shard)
    with pytest.raises(IOError):
        list(src.stream_carrying(DEFAULT_VARIANT_SET_ID, shard, {}))
    assert src.stats.io_exceptions == 1


def test_driver_fused_equals_staged():
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    class StagedOnly:
        """Proxy hiding stream_carrying so the driver takes the slow path."""

        def __init__(self, inner):
            self._inner = inner
            self.stats = inner.stats

        def list_callsets(self, vsid):
            return self._inner.list_callsets(vsid)

        def stream_variants(self, vsid, shard):
            return self._inner.stream_variants(vsid, shard)

    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        min_allele_frequency=0.1,
    )
    fused_driver = VariantsPcaDriver(conf, _cohort())
    assert fused_driver._fused_ingest_possible()
    fused = fused_driver.run()
    staged_driver = VariantsPcaDriver(conf, StagedOnly(_cohort()))
    assert not staged_driver._fused_ingest_possible()
    staged = staged_driver.run()
    assert [r[0] for r in fused] == [r[0] for r in staged]
    np.testing.assert_allclose(
        np.array([r[1:] for r in fused]),
        np.array([r[1:] for r in staged]),
        atol=1e-6,
    )


class TestCsrSidecar:
    def test_sidecar_persists_and_serves_without_reparse(self, tmp_path):
        import os

        root = str(tmp_path / "c")
        _cohort().dump(root)
        shards = shards_for_references(REFS, 20_000)
        first = JsonlSource(root)
        index = CallsetIndex.from_source(first, [DEFAULT_VARIANT_SET_ID])
        want = _fast(first, DEFAULT_VARIANT_SET_ID, shards, index.indexes, None)
        sidecar = os.path.join(root, ".variants.csr.npz")
        assert os.path.exists(sidecar)

        # Corrupt the JSONL but keep its stat signature: a fresh source
        # must serve identical results purely from the sidecar — proof it
        # never re-parses.
        path = os.path.join(root, "variants.jsonl")
        st = os.stat(path)
        size = st.st_size
        with open(path, "r+b") as f:
            f.write(b"\x00" * min(64, size))
        os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns))
        assert os.stat(path).st_size == size
        fresh = JsonlSource(root)
        got = _fast(fresh, DEFAULT_VARIANT_SET_ID, shards, index.indexes, None)
        assert got == want

    def test_sidecar_invalidated_by_file_change(self, tmp_path):
        import json as _json
        import os

        root = str(tmp_path / "c")
        _cohort().dump(root)
        shards = shards_for_references(REFS, 100_000)
        first = JsonlSource(root)
        index = CallsetIndex.from_source(first, [DEFAULT_VARIANT_SET_ID])
        before = _fast(
            first, DEFAULT_VARIANT_SET_ID, shards, index.indexes, None
        )
        # Append one more carrying variant; mtime/size change → rebuild.
        rec = {
            "reference_name": "17",
            "start": 41200001,
            "end": 41200002,
            "reference_bases": "A",
            "alternate_bases": ["G"],
            "variant_set_id": DEFAULT_VARIANT_SET_ID,
            "calls": [
                {
                    "callset_id": f"{DEFAULT_VARIANT_SET_ID}-0",
                    "genotype": [0, 1],
                }
            ],
        }
        with open(os.path.join(root, "variants.jsonl"), "a") as f:
            f.write(_json.dumps(rec) + "\n")
        fresh = JsonlSource(root)
        after = _fast(
            fresh, DEFAULT_VARIANT_SET_ID, shards, index.indexes, None
        )
        assert len(after) == len(before) + 1


class TestVariantSetRule:
    """The ONE variant-set rule: falsy stored id = wildcard, non-empty
    must equal — identical across staged, fused, sidecar, and HTTP."""

    def _vsidless(self):
        src = _cohort()
        for rec in src._variants:
            rec.pop("variant_set_id", None)
        return src

    def test_http_round_trip_keeps_vsidless_records(self):
        # Serialization turns a missing key into an explicit "" — the
        # fused client path must keep those exactly like the staged one.
        from spark_examples_tpu.genomics.service import (
            GenomicsServiceServer,
            HttpVariantSource,
        )

        server = GenomicsServiceServer(self._vsidless()).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            shards = shards_for_references(REFS, 20_000)
            ref = CallsetIndex.from_source(
                _cohort(), [DEFAULT_VARIANT_SET_ID]
            )
            staged = _slow(
                HttpVariantSource(url),
                DEFAULT_VARIANT_SET_ID,
                shards,
                ref.indexes,
                None,
            )
            fused = _fast(
                HttpVariantSource(url),
                DEFAULT_VARIANT_SET_ID,
                shards,
                ref.indexes,
                None,
            )
            assert staged and fused == staged
        finally:
            server.stop()

    def test_jsonl_explicit_empty_vsid_is_wildcard(self, tmp_path):
        import json as _json
        import os

        root = str(tmp_path / "c")
        self._vsidless().dump(root)
        # dump writes records without the key; rewrite with explicit "".
        path = os.path.join(root, "variants.jsonl")
        recs = [
            {**_json.loads(line), "variant_set_id": ""}
            for line in open(path)
        ]
        with open(path, "w") as f:
            for rec in recs:
                f.write(_json.dumps(rec) + "\n")
        shards = shards_for_references(REFS, 20_000)
        ref = CallsetIndex.from_source(_cohort(), [DEFAULT_VARIANT_SET_ID])
        staged = _slow(
            JsonlSource(root), DEFAULT_VARIANT_SET_ID, shards, ref.indexes, None
        )
        fused = _fast(
            JsonlSource(root), DEFAULT_VARIANT_SET_ID, shards, ref.indexes, None
        )
        assert staged and fused == staged


class TestUnknownCallsetLazy:
    def test_out_of_scope_unknown_callset_does_not_crash_build(
        self, tmp_path
    ):
        """An unknown callset in a record OUTSIDE the query must not
        break fused ingest (the staged path never touches it); querying
        the bad record itself still raises with the true id."""
        import json as _json
        import os

        root = str(tmp_path / "c")
        _cohort().dump(root)
        bad = {
            "reference_name": "18",
            "start": 500,
            "end": 501,
            "reference_bases": "A",
            "alternate_bases": ["G"],
            "variant_set_id": DEFAULT_VARIANT_SET_ID,
            "calls": [{"callset_id": "ghost-callset", "genotype": [0, 1]}],
        }
        with open(os.path.join(root, "variants.jsonl"), "a") as f:
            f.write(_json.dumps(bad) + "\n")
        js = JsonlSource(root)
        index = CallsetIndex.from_source(js, [DEFAULT_VARIANT_SET_ID])
        shards = shards_for_references(REFS, 20_000)
        # chr17 query: works, ghost record never touched.
        assert _fast(js, DEFAULT_VARIANT_SET_ID, shards, index.indexes, None)
        # chr18 query hits the ghost record: KeyError with the true id.
        bad_shard = shards_for_references("18:0:1000", 1_000)[0]
        with pytest.raises(KeyError, match="ghost-callset"):
            list(
                js.stream_carrying(
                    DEFAULT_VARIANT_SET_ID, bad_shard, index.indexes
                )
            )


class TestFusedMultiDataset:
    """Keyed fused join/merge ≡ the staged multi-dataset path."""

    def _two_sets(self, src_factory=None):
        a = synthetic_cohort(8, 60, variant_set_id="setA", seed=1)
        b = synthetic_cohort(8, 60, variant_set_id="setB", seed=1)
        merged = FixtureSource(
            variants=a._variants + b._variants,
            callsets=a._callsets + b._callsets,
        )
        return merged

    def _three_sets(self):
        srcs = [
            synthetic_cohort(6, 40, variant_set_id=f"set{i}", seed=1)
            for i in range(3)
        ]
        return FixtureSource(
            variants=[r for s in srcs for r in s._variants],
            callsets=[c for s in srcs for c in s._callsets],
        )

    @pytest.mark.parametrize("min_af", [None, 0.2])
    def test_driver_join_fused_equals_staged(self, min_af):
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        conf = PcaConfig(
            variant_set_ids=["setA", "setB"],
            bases_per_partition=20_000,
            block_variants=32,
            min_allele_frequency=min_af,
        )
        fused_driver = VariantsPcaDriver(conf, self._two_sets())
        assert fused_driver._fused_multi_possible()
        fused = fused_driver.run()
        staged_driver = VariantsPcaDriver(conf, self._two_sets())
        staged_calls = staged_driver.get_calls(
            [
                staged_driver.filter_dataset(d)
                for d in staged_driver.get_data()
            ]
        )
        g = staged_driver.get_similarity_matrix(staged_calls)
        staged = staged_driver.compute_pca(g)
        assert [r[0] for r in fused] == [r[0] for r in staged]
        np.testing.assert_allclose(
            np.array([r[1:] for r in fused]),
            np.array([r[1:] for r in staged]),
            atol=1e-6,
        )

    def test_three_set_merge_calls_identical(self):
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        conf = PcaConfig(
            variant_set_ids=["set0", "set1", "set2"],
            bases_per_partition=20_000,
            block_variants=32,
        )
        fused_driver = VariantsPcaDriver(conf, self._three_sets())
        fused = sorted(map(tuple, fused_driver.get_calls_fused_multi()))
        staged_driver = VariantsPcaDriver(conf, self._three_sets())
        staged = sorted(
            map(
                tuple,
                staged_driver.get_calls(
                    [
                        staged_driver.filter_dataset(d)
                        for d in staged_driver.get_data()
                    ]
                ),
            )
        )
        assert fused and fused == staged

    def test_keyed_join_over_http(self, tmp_path):
        from spark_examples_tpu.genomics.service import (
            GenomicsServiceServer,
            HttpVariantSource,
        )
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        server = GenomicsServiceServer(self._two_sets()).start()
        try:
            conf = PcaConfig(
                variant_set_ids=["setA", "setB"],
                bases_per_partition=20_000,
                block_variants=32,
            )
            remote = VariantsPcaDriver(
                conf, HttpVariantSource(f"http://127.0.0.1:{server.port}")
            )
            assert remote._fused_multi_possible()
            got = remote.run()
            local = VariantsPcaDriver(conf, self._two_sets()).run()
            np.testing.assert_allclose(
                np.array([r[1:] for r in got]),
                np.array([r[1:] for r in local]),
                atol=1e-6,
            )
        finally:
            server.stop()

    def test_keyed_join_over_jsonl(self, tmp_path):
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        root = str(tmp_path / "c")
        self._two_sets().dump(root)
        conf = PcaConfig(
            variant_set_ids=["setA", "setB"],
            bases_per_partition=20_000,
            block_variants=32,
        )
        disk = VariantsPcaDriver(conf, JsonlSource(root)).run()
        mem = VariantsPcaDriver(conf, self._two_sets()).run()
        np.testing.assert_allclose(
            np.array([r[1:] for r in disk]),
            np.array([r[1:] for r in mem]),
            atol=1e-6,
        )

    def test_keyed_duplicate_identity_cross_product(self):
        from spark_examples_tpu.genomics.datasets import join_keyed

        def triple(contig, payload, calls):
            return (contig, payload, calls)

        a = [triple("17", b"p1", [0]), triple("17", b"p1", [1])]
        b = [triple("17", b"p1", [2]), triple("17", b"p2", [3])]
        out = sorted(join_keyed(iter(a), iter(b)))
        assert out == [[0, 2], [1, 2]]

    def test_keyed_empty_left_calls_still_join(self):
        # A record with NO carriers in set A still matches and
        # contributes B's carriers (reference joins records, not calls).
        from spark_examples_tpu.genomics.datasets import (
            calls_stream_keyed,
        )

        a = [("17", b"p1", [])]
        b = [("17", b"p1", [4, 5])]
        assert list(calls_stream_keyed([iter(a), iter(b)])) == [[4, 5]]


class TestSidecarRecovery:
    def test_corrupt_sidecar_rebuilds(self, tmp_path):
        import os

        root = str(tmp_path / "c")
        _cohort().dump(root)
        shards = shards_for_references(REFS, 20_000)
        index = CallsetIndex.from_source(
            JsonlSource(root), [DEFAULT_VARIANT_SET_ID]
        )
        want = _fast(
            JsonlSource(root), DEFAULT_VARIANT_SET_ID, shards, index.indexes, None
        )
        sidecar = os.path.join(root, ".variants.csr.npz")
        # Truncate to garbage: np.load raises BadZipFile, which must
        # trigger a rebuild, not a crash.
        with open(sidecar, "wb") as f:
            f.write(b"PK\x03\x04 not a real zip")
        got = _fast(
            JsonlSource(root), DEFAULT_VARIANT_SET_ID, shards, index.indexes, None
        )
        assert got == want

    def test_version_mismatch_rebuilds(self, tmp_path):
        import os

        import numpy as _np

        root = str(tmp_path / "c")
        _cohort().dump(root)
        shards = shards_for_references(REFS, 20_000)
        index = CallsetIndex.from_source(
            JsonlSource(root), [DEFAULT_VARIANT_SET_ID]
        )
        want = _fast(
            JsonlSource(root), DEFAULT_VARIANT_SET_ID, shards, index.indexes, None
        )
        sidecar = os.path.join(root, ".variants.csr.npz")
        # A structurally-valid npz from an older format version: the
        # digest embeds the version, so it must be rejected and rebuilt.
        with open(sidecar, "wb") as f:
            _np.savez(f, digest=_np.str_("v1|stale"))
        got = _fast(
            JsonlSource(root), DEFAULT_VARIANT_SET_ID, shards, index.indexes, None
        )
        assert got == want


class TestRelayHelper:
    def test_no_axon_site_is_noop(self, monkeypatch):
        from spark_examples_tpu.utils import relay

        monkeypatch.setattr(relay, "AXON_SITE", "/nonexistent-axon")
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        assert not relay.axon_possible()
        assert not relay.cpu_failover_if_dead()

    def test_explicit_cpu_is_noop(self, monkeypatch, tmp_path):
        from spark_examples_tpu.utils import relay

        # Axon IS possible here — the explicit-cpu guard must short-
        # circuit before any relay probe.
        monkeypatch.setattr(relay, "AXON_SITE", str(tmp_path))
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        monkeypatch.setattr(
            relay,
            "relay_alive",
            lambda timeout=5.0: (_ for _ in ()).throw(
                AssertionError("must not probe when platform is cpu")
            ),
        )
        assert not relay.cpu_failover_if_dead()

    def test_dead_relay_engages(self, monkeypatch, tmp_path):
        from spark_examples_tpu.utils import relay

        monkeypatch.setattr(relay, "AXON_SITE", str(tmp_path))  # exists
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        monkeypatch.setattr(relay, "relay_alive", lambda timeout=5.0: False)
        assert relay.cpu_failover_if_dead()
        monkeypatch.setattr(relay, "relay_alive", lambda timeout=5.0: True)
        assert not relay.cpu_failover_if_dead()


class TestShardParallelIngest:
    """--ingest-workers: wall-clock parallelism with bit-identical
    results (round-2 verdict #2 — the shard-parallel cold ingest
    composition; perf is host-dependent, ORDER is not)."""

    def test_ordered_parallel_map_preserves_order(self):
        import time

        from spark_examples_tpu.utils.concurrency import (
            ordered_parallel_map,
        )

        def slow_square(x):
            time.sleep(0.002 * (7 - x % 8))  # later items finish earlier
            return x * x

        items = list(range(40))
        assert list(ordered_parallel_map(slow_square, items, 8)) == [
            x * x for x in items
        ]

    def test_ordered_parallel_map_error_position(self):
        from spark_examples_tpu.utils.concurrency import (
            ordered_parallel_map,
        )

        def boom(x):
            if x == 5:
                raise IOError("shard 5 failed")
            return x

        out = []
        with pytest.raises(IOError, match="shard 5"):
            for r in ordered_parallel_map(boom, range(10), 4):
                out.append(r)
        assert out == [0, 1, 2, 3, 4]  # everything before the failure

    @pytest.mark.parametrize("workers", [1, 3])
    def test_driver_results_bit_identical_across_worker_counts(
        self, tmp_path, workers
    ):
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        _cohort().dump(str(tmp_path / "c"))

        def g_with(n_workers):
            conf = PcaConfig(
                variant_set_ids=[DEFAULT_VARIANT_SET_ID],
                bases_per_partition=20_000,
                block_variants=32,
                ingest_workers=n_workers,
            )
            driver = VariantsPcaDriver(
                conf, JsonlSource(str(tmp_path / "c"))
            )
            return np.asarray(
                driver.get_similarity_matrix(driver.get_calls_fused())
            )

        np.testing.assert_array_equal(g_with(workers), g_with(1))

    def test_multi_dataset_keyed_parallel_bit_identical(self, tmp_path):
        """The keyed path interleaves DIFFERENT variant sets from
        concurrent workers against one shared sidecar — the exact shape
        of the _allowed-mask race the review fixed; results must match
        serial exactly."""
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        a = synthetic_cohort(8, 60, variant_set_id="setA", seed=1)
        b = synthetic_cohort(8, 60, variant_set_id="setB", seed=1)
        FixtureSource(
            variants=a._variants + b._variants,
            callsets=a._callsets + b._callsets,
        ).dump(str(tmp_path / "c"))

        def g_with(n_workers):
            conf = PcaConfig(
                variant_set_ids=["setA", "setB"],
                bases_per_partition=20_000,
                block_variants=32,
                ingest_workers=n_workers,
            )
            driver = VariantsPcaDriver(
                conf, JsonlSource(str(tmp_path / "c"))
            )
            assert driver._fused_multi_possible()
            return np.asarray(
                driver.get_similarity_matrix(
                    driver.get_calls_fused_multi()
                )
            )

        np.testing.assert_array_equal(g_with(4), g_with(1))

    def test_http_source_parallel_shards(self):
        """Concurrent in-flight shard requests against the threaded
        server — the reference's one-stream-per-task shape."""
        from spark_examples_tpu.genomics.service import (
            GenomicsServiceServer,
            HttpVariantSource,
        )
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        server = GenomicsServiceServer(_cohort()).start()
        try:
            url = f"http://127.0.0.1:{server.port}"

            def result_with(n_workers):
                conf = PcaConfig(
                    variant_set_ids=[DEFAULT_VARIANT_SET_ID],
                    bases_per_partition=20_000,
                    block_variants=32,
                    ingest_workers=n_workers,
                )
                driver = VariantsPcaDriver(conf, HttpVariantSource(url))
                return np.asarray(
                    driver.get_similarity_matrix(driver.get_calls_fused())
                )

            np.testing.assert_array_equal(result_with(4), result_with(1))
        finally:
            server.stop()
