"""Fused carrying-index ingest fast path ≡ the staged slow path.

The fast path (sources.stream_carrying / _carrying_records) exists because
per-call dataclass construction dominated host ingest at chr20 scale; its
contract is OBSERVABLE EQUALITY with stream_variants → af_filter →
carrying_sample_indices on every source type, stats included.
"""

import numpy as np
import pytest

from spark_examples_tpu.genomics.callsets import CallsetIndex
from spark_examples_tpu.genomics.datasets import (
    af_filter,
    carrying_sample_indices,
)
from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.genomics.shards import shards_for_references
from spark_examples_tpu.genomics.sources import FixtureSource, JsonlSource
from spark_examples_tpu.utils.stats import IoStats

REFS = "17:41196311:41277499"


def _slow(source, vsid, shards, indexes, min_af):
    out = []
    for shard in shards:
        stream = af_filter(source.stream_variants(vsid, shard), min_af)
        for v in stream:
            calls = carrying_sample_indices(v, indexes)
            if calls:
                out.append(calls)
    return out


def _fast(source, vsid, shards, indexes, min_af):
    out = []
    for shard in shards:
        out.extend(source.stream_carrying(vsid, shard, indexes, min_af))
    return out


def _cohort(**kw):
    return synthetic_cohort(
        12,
        80,
        seed=21,
        dropped_contig_every=9,
        reference_blocks_every=13,
        **kw,
    )


@pytest.mark.parametrize("min_af", [None, 0.2])
def test_fixture_source_parity(min_af):
    shards = shards_for_references(REFS, 20_000)
    slow_src, fast_src = _cohort(), _cohort()
    index = CallsetIndex.from_source(slow_src, [DEFAULT_VARIANT_SET_ID])
    slow = _slow(
        slow_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, min_af
    )
    fast = _fast(
        fast_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, min_af
    )
    assert fast == slow
    # Stats parity: same variants_read (counted post contig-drop,
    # pre AF-filter) and same request/partition accounting.
    assert fast_src.stats.variants_read == slow_src.stats.variants_read
    assert fast_src.stats.partitions == slow_src.stats.partitions


@pytest.mark.parametrize("min_af", [None, 0.2])
def test_jsonl_source_parity(tmp_path, min_af):
    _cohort().dump(str(tmp_path / "c"))
    shards = shards_for_references(REFS, 20_000)
    slow_src = JsonlSource(str(tmp_path / "c"))
    fast_src = JsonlSource(str(tmp_path / "c"))
    index = CallsetIndex.from_source(slow_src, [DEFAULT_VARIANT_SET_ID])
    assert _fast(
        fast_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, min_af
    ) == _slow(
        slow_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, min_af
    )
    assert fast_src.stats.variants_read == slow_src.stats.variants_read


def test_http_source_parity():
    from spark_examples_tpu.genomics.service import (
        GenomicsServiceServer,
        HttpVariantSource,
    )

    server = GenomicsServiceServer(_cohort()).start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        shards = shards_for_references(REFS, 20_000)
        slow_src = HttpVariantSource(url)
        fast_src = HttpVariantSource(url)
        index = CallsetIndex.from_source(
            slow_src, [DEFAULT_VARIANT_SET_ID]
        )
        assert _fast(
            fast_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, 0.2
        ) == _slow(
            slow_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, 0.2
        )
    finally:
        server.stop()


def test_variant_object_fallback_parity():
    # A fixture holding built Variant objects takes the order-preserving
    # fallback; results must still match the staged path.
    raw = _cohort()
    from spark_examples_tpu.genomics.sources import variant_from_record

    objs = [
        v
        for rec in raw._variants
        if (v := variant_from_record(rec)) is not None
    ]
    obj_src = FixtureSource(variants=objs, callsets=raw._callsets)
    ref_src = _cohort()
    shards = shards_for_references(REFS, 20_000)
    index = CallsetIndex.from_source(ref_src, [DEFAULT_VARIANT_SET_ID])
    assert _fast(
        obj_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, 0.2
    ) == _slow(
        ref_src, DEFAULT_VARIANT_SET_ID, shards, index.indexes, 0.2
    )


def test_unknown_callset_raises_keyerror():
    src = _cohort()
    shards = shards_for_references(REFS, 100_000)
    with pytest.raises(KeyError):
        for _ in src.stream_carrying(
            DEFAULT_VARIANT_SET_ID, shards[0], {"not-a-callset": 0}
        ):
            pass


def test_fault_injection_fires_in_fast_path():
    src = _cohort()
    shard = shards_for_references(REFS, 100_000)[0]
    src._fail_once.add(shard)
    with pytest.raises(IOError):
        list(src.stream_carrying(DEFAULT_VARIANT_SET_ID, shard, {}))
    assert src.stats.io_exceptions == 1


def test_driver_fused_equals_staged():
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    class StagedOnly:
        """Proxy hiding stream_carrying so the driver takes the slow path."""

        def __init__(self, inner):
            self._inner = inner
            self.stats = inner.stats

        def list_callsets(self, vsid):
            return self._inner.list_callsets(vsid)

        def stream_variants(self, vsid, shard):
            return self._inner.stream_variants(vsid, shard)

    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        min_allele_frequency=0.1,
    )
    fused_driver = VariantsPcaDriver(conf, _cohort())
    assert fused_driver._fused_ingest_possible()
    fused = fused_driver.run()
    staged_driver = VariantsPcaDriver(conf, StagedOnly(_cohort()))
    assert not staged_driver._fused_ingest_possible()
    staged = staged_driver.run()
    assert [r[0] for r in fused] == [r[0] for r in staged]
    np.testing.assert_allclose(
        np.array([r[1:] for r in fused]),
        np.array([r[1:] for r in staged]),
        atol=1e-6,
    )
