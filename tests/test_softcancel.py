"""Soft-cancel run deadlines (utils/softcancel.py + scripts/tpu_run.sh).

The round-5 incident: a ``timeout``-style SIGKILL landed mid-TPU-
dispatch and wedged the relay for the rest of the round. These tests
pin the cooperative replacement: the driver exits cleanly (code 75) at
a BLOCK BOUNDARY when the wrapper's deadline passes, and the wrapper
escalates to signals only after the grace period.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.utils import softcancel

REFS = "17:41196311:41277499"


class TestSoftCancelCheck:
    def test_noop_without_env(self):
        softcancel.check("anywhere", environ={})

    def test_future_deadline_is_noop(self):
        env = {softcancel.SOFT_DEADLINE_ENV: str(time.time() + 3600)}
        softcancel.check("anywhere", environ=env)
        assert softcancel.remaining(environ=env) > 3500

    def test_past_deadline_raises_clean_exit_75(self, capsys):
        env = {softcancel.SOFT_DEADLINE_ENV: str(time.time() - 5)}
        with pytest.raises(SystemExit) as exc:
            softcancel.check("block boundary", environ=env)
        assert exc.value.code == softcancel.SOFT_CANCEL_EXIT == 75
        assert "block boundary" in capsys.readouterr().err

    def test_unparseable_deadline_is_loud(self):
        env = {softcancel.SOFT_DEADLINE_ENV: "tomorrow"}
        with pytest.raises(ValueError, match="unix timestamp"):
            softcancel.check("anywhere", environ=env)


class TestDriverBlockBoundary:
    def _driver(self, source):
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        conf = PcaConfig(
            references=REFS,
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            bases_per_partition=20_000,
        )
        return VariantsPcaDriver(conf, source)

    @pytest.fixture()
    def cohort(self, tmp_path):
        from spark_examples_tpu.genomics.sources import JsonlSource

        root = str(tmp_path / "cohort")
        synthetic_cohort(12, 60, seed=3).dump(root)
        return JsonlSource(root)

    def test_ingest_cancels_at_block_boundary(self, monkeypatch, cohort):
        drv = self._driver(cohort)
        monkeypatch.setenv(
            softcancel.SOFT_DEADLINE_ENV, str(time.time() - 1)
        )
        with pytest.raises(SystemExit) as exc:
            drv.get_similarity_matrix_csr(drv.get_csr_fused())
        assert exc.value.code == 75

    def test_run_completes_without_deadline(self, monkeypatch, cohort):
        monkeypatch.delenv(softcancel.SOFT_DEADLINE_ENV, raising=False)
        drv = self._driver(cohort)
        g = np.asarray(
            drv.get_similarity_matrix_csr(drv.get_csr_fused())
        )
        assert g.shape == (12, 12)


class TestRunWrapper:
    WRAPPER = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "tpu_run.sh",
    )

    def test_exports_absolute_deadline_and_passes_exit_code(self):
        proc = subprocess.run(
            [
                "bash",
                self.WRAPPER,
                "-d",
                "60",
                "--",
                sys.executable,
                "-c",
                "import os, time, sys;"
                "d = float(os.environ['SPARK_EXAMPLES_TPU_SOFT_DEADLINE']);"
                "sys.exit(0 if 50 < d - time.time() <= 60 else 3)",
            ],
            capture_output=True,
            timeout=30,
        )
        assert proc.returncode == 0, proc.stderr.decode()

    def test_soft_cancel_exit_code_passes_through(self):
        proc = subprocess.run(
            ["bash", self.WRAPPER, "-d", "60", "--", "bash", "-c", "exit 75"],
            capture_output=True,
            timeout=30,
        )
        assert proc.returncode == 75

    def test_escalates_to_sigterm_after_grace(self):
        t0 = time.monotonic()
        proc = subprocess.run(
            ["bash", self.WRAPPER, "-d", "0", "-g", "1", "--", "sleep", "30"],
            capture_output=True,
            timeout=30,
        )
        assert proc.returncode == 124
        assert time.monotonic() - t0 < 15
        assert b"SIGTERM" in proc.stderr
        # the pre-escalation liveness snapshot makes a wedge attributable
        assert b"liveness snapshot" in proc.stderr
