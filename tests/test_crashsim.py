"""crashsim (tools/crashsim): the crash-consistency harness's own tests.

Four layers:

1. **Recorder units** — interposition captures exactly the
   durability-relevant ops, relative to the root, and restores the
   patched functions on exit.
2. **Model units** — the crashed-state semantics the scenarios rely
   on: volatile content propagates THROUGH renames (the ALICE failure
   class), fsync pins the durable floor, the floor variant of a
   never-synced file is absence.
3. **Planted-bug detection** — a workload that renames WITHOUT fsync
   must produce violations. A harness that cannot catch the bug it
   exists for proves nothing; this is crashsim's own golden positive.
4. **The real scenarios** — every shipped scenario recovers from every
   enumerated crashed state (the acceptance bar), the enumeration
   covers the four required commit points, and the CLI gates.
"""

import builtins
import json
import os
import subprocess
import sys

import pytest

from tools.crashsim.harness import run_scenario, write_report
from tools.crashsim.model import (
    CrashInfo,
    enumerate_crash_states,
    materialize,
)
from tools.crashsim.recorder import FsOp, OpRecorder
from tools.crashsim.scenarios import SCENARIOS, Scenario

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestRecorder:
    def test_captures_commit_sequence(self, tmp_path):
        root = str(tmp_path)
        with OpRecorder(root) as rec:
            tmp = os.path.join(root, "doc.tmp")
            with open(tmp, "wb") as f:
                f.write(b"payload")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(root, "doc"))
        kinds = [(op.kind, op.path) for op in rec.ops]
        assert kinds == [
            ("write", "doc.tmp"),
            ("fsync", "doc.tmp"),
            ("rename", "doc.tmp"),
        ]
        assert rec.ops[0].content == b"payload"
        assert rec.ops[2].dst == "doc"

    def test_ignores_paths_outside_root(self, tmp_path):
        inside = tmp_path / "in"
        outside = tmp_path / "out"
        inside.mkdir()
        outside.mkdir()
        with OpRecorder(str(inside)) as rec:
            with open(outside / "other", "w") as f:
                f.write("x")
            os.mkdir(outside / "d")
        assert rec.ops == []

    def test_read_opens_pass_through_unwrapped(self, tmp_path):
        (tmp_path / "existing").write_bytes(b"abc")
        with OpRecorder(str(tmp_path)) as rec:
            with open(tmp_path / "existing", "rb") as f:
                assert f.read() == b"abc"
        assert rec.ops == []

    def test_restores_patched_functions(self, tmp_path):
        orig_open, orig_fsync = builtins.open, os.fsync
        orig_replace, orig_mkdir = os.replace, os.mkdir
        with OpRecorder(str(tmp_path)):
            assert builtins.open is not orig_open
        assert builtins.open is orig_open
        assert os.fsync is orig_fsync
        assert os.replace is orig_replace
        assert os.mkdir is orig_mkdir

    def test_not_reentrant(self, tmp_path):
        with OpRecorder(str(tmp_path)) as rec:
            with pytest.raises(RuntimeError):
                rec.__enter__()

    def test_makedirs_resolves_through_patched_mkdir(self, tmp_path):
        with OpRecorder(str(tmp_path)) as rec:
            os.makedirs(os.path.join(str(tmp_path), "a", "b"))
        assert [(op.kind, op.path) for op in rec.ops] == [
            ("mkdir", "a"),
            ("mkdir", os.path.join("a", "b")),
        ]


class TestModel:
    def test_volatile_content_propagates_through_rename(self):
        """The ALICE pessimism the whole harness is built on: a rename
        of a never-fsynced file can expose a torn image under the
        DESTINATION name."""
        ops = [
            FsOp("write", "doc.tmp", content=b"0123456789"),
            FsOp("rename", "doc.tmp", dst="doc"),
        ]
        states = list(enumerate_crash_states(ops))
        torn_under_final = [
            s
            for s in states
            if s.variant == "torn" and dict(s.files).get("doc")
        ]
        assert torn_under_final, "torn state must surface under 'doc'"
        torn = dict(torn_under_final[0].files)["doc"]
        assert torn and torn != b"0123456789"
        assert b"0123456789".startswith(torn)

    def test_fsync_pins_the_floor(self):
        ops = [
            FsOp("write", "doc.tmp", content=b"0123456789"),
            FsOp("fsync", "doc.tmp"),
            FsOp("rename", "doc.tmp", dst="doc"),
        ]
        for state in enumerate_crash_states(ops):
            if state.n_ops == 3:
                # Post-fsync, post-rename: nothing is volatile — only
                # the full image exists and it is complete.
                assert state.variant == "full"
                assert dict(state.files)["doc"] == b"0123456789"

    def test_floor_of_never_synced_file_is_absence(self):
        ops = [FsOp("write", "doc.tmp", content=b"abc")]
        by_variant = {
            s.variant: s
            for s in enumerate_crash_states(ops)
            if s.n_ops == 1
        }
        assert "doc.tmp" not in dict(by_variant["floor"].files)
        assert dict(by_variant["full"].files)["doc.tmp"] == b"abc"

    def test_directory_rename_moves_subtree(self):
        ops = [
            FsOp("mkdir", "staging"),
            FsOp("write", "staging/a", content=b"a"),
            FsOp("fsync", "staging/a"),
            FsOp("rename", "staging", dst="final"),
        ]
        final = list(enumerate_crash_states(ops))[-1]
        assert dict(final.files) == {"final/a": b"a"}
        assert final.dirs == ("final",)

    def test_materialize_back_dates_artifacts(self, tmp_path):
        import time

        ops = [
            FsOp("mkdir", "lockdir.lck"),
            FsOp("write", "doc", content=b"x"),
        ]
        state = next(
            s
            for s in enumerate_crash_states(ops)
            if s.n_ops == 2 and s.variant == "full"
        )
        dest = str(tmp_path / "crash")
        materialize(state, dest)
        for rel in ("lockdir.lck", "doc"):
            age = time.time() - os.path.getmtime(os.path.join(dest, rel))
            assert age > 3000, (
                "crashed artifacts must read as PAST so mtime-based "
                "stale-breakers fire instead of waiting out a ghost"
            )

    def test_crash_info_helpers(self):
        info = CrashInfo(
            ops=[
                FsOp("write", "a/doc.tmp", content=b"x"),
                FsOp("fsync", "a/doc.tmp"),
                FsOp("rename", "a/doc.tmp", dst="a/doc"),
            ]
        )
        assert info.renames_to("a/doc") == 1
        assert info.fsyncs_of("doc.tmp") == 1
        assert info.writes_of(".tmp") == [b"x"]


class TestPlantedBug:
    """The harness's golden positive: rename-without-fsync MUST be
    caught, and the same workload with the fsync restored must pass —
    the detector works and does not cry wolf."""

    @staticmethod
    def _scenario(fsync_before_rename):
        def workload(root):
            tmp = os.path.join(root, "doc.tmp")
            with open(tmp, "wb") as f:
                f.write(b"0123456789abcdef")
                f.flush()
                if fsync_before_rename:
                    os.fsync(f.fileno())
            os.replace(tmp, os.path.join(root, "doc"))

        def check(root, info):
            path = os.path.join(root, "doc")
            if not os.path.exists(path):
                return None
            with open(path, "rb") as f:
                content = f.read()
            if content != b"0123456789abcdef":
                return "partial file visible under the committed name"
            return None

        return Scenario("planted", "planted bug", workload, check)

    def test_missing_fsync_is_detected(self, tmp_path):
        res = run_scenario(
            self._scenario(fsync_before_rename=False), str(tmp_path)
        )
        assert not res.ok
        assert any(
            v.variant in ("torn", "floor") for v in res.violations
        )

    def test_fsynced_variant_is_clean(self, tmp_path):
        res = run_scenario(
            self._scenario(fsync_before_rename=True), str(tmp_path)
        )
        assert res.ok, [v.message for v in res.violations]

    def test_throwing_recovery_is_a_violation(self, tmp_path):
        sc = Scenario(
            "raiser",
            "recovery that throws",
            lambda root: open(
                os.path.join(root, "f"), "wb"
            ).close(),
            lambda root, info: (_ for _ in ()).throw(
                ValueError("recovery exploded")
            ),
        )
        res = run_scenario(sc, str(tmp_path))
        assert not res.ok
        assert "recovery raised" in res.violations[0].message


class TestScenarios:
    @pytest.mark.parametrize(
        "scenario", SCENARIOS, ids=[s.name for s in SCENARIOS]
    )
    def test_every_crashed_state_recovers(self, tmp_path, scenario):
        """The acceptance bar, per scenario: every enumerated crashed
        state runs the real recovery code and every invariant holds."""
        res = run_scenario(scenario, str(tmp_path))
        assert res.n_ops > 0, "workload recorded nothing"
        assert res.n_states > res.n_ops, "variants missing"
        assert res.ok, [
            f"crash@{v.n_ops}/{v.variant}: {v.message}"
            for v in res.violations
        ]

    def test_required_commit_points_are_covered(self):
        """ISSUE acceptance: the enumeration reaches (at least) the
        store lease CAS, the journal append, the mirror staging
        commit, and the delta persist."""
        names = {s.name for s in SCENARIOS}
        assert {
            "store-lease-cas",
            "journal-append",
            "mirror-staging",
            "delta-persist",
        } <= names

    def test_scenario_workloads_hit_their_commit_renames(self, tmp_path):
        """Each scenario's op log must actually contain an atomic
        rename — a workload that never commits enumerates trivially
        and verifies nothing."""
        by_name = {s.name: s for s in SCENARIOS}
        sc = by_name["store-put"]
        work = tmp_path / "w"
        work.mkdir()
        with OpRecorder(str(work)) as rec:
            sc.workload(str(work))
        renames = [op for op in rec.ops if op.kind == "rename"]
        fsyncs = [op for op in rec.ops if op.kind == "fsync"]
        assert renames and fsyncs
        # fsync-before-rename order, per commit:
        first_rename = rec.ops.index(renames[0])
        assert any(
            rec.ops.index(f) < first_rename for f in fsyncs
        )


class TestCli:
    def test_list(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.crashsim", "--list"],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 0
        for sc in SCENARIOS:
            assert sc.name in proc.stdout

    def test_unknown_scenario_is_usage_error(self):
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "tools.crashsim",
                "--scenario",
                "no-such-thing",
            ],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 2

    def test_single_scenario_run_writes_jsonl(self, tmp_path):
        out = str(tmp_path / "report.jsonl")
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "tools.crashsim",
                "--scenario",
                "flightrec-dump",
                "--out",
                out,
            ],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        lines = [
            json.loads(line)
            for line in open(out, encoding="utf-8")
            if line.strip()
        ]
        assert lines and lines[0]["kind"] == "scenario"
        assert lines[0]["ok"] is True

    def test_report_shape_for_violations(self, tmp_path):
        import io

        res = run_scenario(
            TestPlantedBug._scenario(False), str(tmp_path)
        )
        buf = io.StringIO()
        write_report([res], buf)
        lines = [json.loads(x) for x in buf.getvalue().splitlines()]
        assert lines[0]["kind"] == "scenario"
        assert lines[0]["ok"] is False
        assert any(x["kind"] == "violation" for x in lines[1:])
