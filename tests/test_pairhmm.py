"""PairHMM read-level kernel subsystem: kernel parity, driver, serving.

Four layers, mirroring the subsystem's structure:

- **kernel** (ops/pairhmm.py): the batched anti-diagonal f32 forward
  pass holds tolerance parity with the scalar float64 numpy golden
  across length buckets, masked pads, and shuffled pair orders — the
  acceptance contract of ISSUE 15;
- **fixtures** (genomics/fixtures.synthetic_read_pairs): the
  hand-computable pairs really are hand-computable (the closed-form
  all-match path sum pins the match-kind likelihood to ~1%);
- **driver** (models/pairhmm.py): consensus voting, bucketing, and the
  completion-order feed produce rows bit-identical under any worker
  count / batch size, with schema-valid telemetry;
- **serving** (the `pairhmm` job kind): spec validation, result
  caching, and the deterministic kill -9 → restart → identical-result
  chaos pin the PCA kind has always had.
"""

import json

import numpy as np
import pytest

from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    FIXTURE_READSET_ID,
    synthetic_cohort,
    synthetic_read_pairs,
    synthetic_reads,
)
from spark_examples_tpu.ops.pairhmm import (
    DEFAULT_GAP_EXT_PHRED,
    DEFAULT_GAP_OPEN_PHRED,
    PAIRHMM_FORWARD_ATOL,
    PAIRHMM_FORWARD_RTOL,
    PAIRHMM_NEG_INF,
    pairhmm_bucket,
    pairhmm_forward_batch,
    pairhmm_forward_ref,
)
from spark_examples_tpu.utils.config import PcaConfig

READS_REFS = "11:6888648:6890648"


def _batch_arrays(pairs, r_bucket=None, h_bucket=None, b_pad=None):
    """Stack (read, quals, hap) triples into padded kernel operands."""
    r_b = r_bucket or pairhmm_bucket(max(p["read"].size for p in pairs))
    h_b = h_bucket or pairhmm_bucket(max(p["hap"].size for p in pairs))
    b = b_pad or len(pairs)
    rc = np.zeros((b, r_b), np.int8)
    rq = np.zeros((b, r_b), np.int32)
    hc = np.full((b, h_b), 4, np.int8)
    rl = np.zeros(b, np.int32)
    hl = np.zeros(b, np.int32)
    for k, p in enumerate(pairs):
        rc[k, : p["read"].size] = p["read"]
        rq[k, : p["quals"].size] = p["quals"]
        hc[k, : p["hap"].size] = p["hap"]
        rl[k] = p["read"].size
        hl[k] = p["hap"].size
    return rc, rq, rl, hc, hl


def _random_pairs(rng, shapes, substring=True):
    pairs = []
    for rl, hl in shapes:
        hap = rng.integers(0, 4, hl).astype(np.int8)
        if substring and hl >= rl:
            off = int(rng.integers(0, hl - rl + 1))
            read = hap[off : off + rl].copy()
            errs = rng.random(rl) < 0.05
            read[errs] = rng.integers(0, 4, int(errs.sum()))
        else:
            read = rng.integers(0, 4, rl).astype(np.int8)
        pairs.append(
            {
                "read": read.astype(np.int8),
                "quals": rng.integers(5, 55, rl).astype(np.int32),
                "hap": hap,
            }
        )
    return pairs


def _run_batch(pairs, **kw):
    rc, rq, rl, hc, hl = _batch_arrays(pairs, **kw)
    return np.asarray(
        pairhmm_forward_batch(
            rc,
            rq,
            rl,
            hc,
            hl,
            np.float32(DEFAULT_GAP_OPEN_PHRED),
            np.float32(DEFAULT_GAP_EXT_PHRED),
        )
    )


def _assert_parity(out, pairs):
    refs = np.array(
        [
            pairhmm_forward_ref(p["read"], p["quals"], p["hap"])
            for p in pairs
        ]
    )
    np.testing.assert_allclose(
        out[: len(pairs)],
        refs,
        rtol=PAIRHMM_FORWARD_RTOL,
        atol=PAIRHMM_FORWARD_ATOL,
    )


class TestKernelGoldenParity:
    def test_matches_scalar_golden_across_length_buckets(self):
        """The acceptance matrix: reads and haplotypes spanning several
        pow2 buckets, every pair within the documented f32 tolerance of
        the float64 golden."""
        rng = np.random.default_rng(0)
        shapes = [
            (1, 1),
            (1, 8),
            (3, 5),
            (7, 16),
            (20, 33),
            (37, 64),
            (100, 116),
            (100, 200),
            (250, 300),
        ]
        pairs = _random_pairs(rng, shapes)
        _assert_parity(_run_batch(pairs), pairs)

    def test_masked_pads_do_not_leak_into_results(self):
        """A pair's value must be identical whether it rides a tile
        bucketed exactly to its length or one padded 4x wider/taller
        with junk in the pad lanes — bit-for-bit, since masking (not
        the pad contents) defines the matrix."""
        rng = np.random.default_rng(1)
        pairs = _random_pairs(rng, [(9, 12), (17, 40), (64, 80)])
        tight = _run_batch(pairs)
        r_b = pairhmm_bucket(64) * 4
        h_b = pairhmm_bucket(80) * 4
        rc, rq, rl, hc, hl = _batch_arrays(
            pairs, r_bucket=r_b, h_bucket=h_b, b_pad=8
        )
        # Poison every pad lane: masked geometry must ignore it.
        for k, p in enumerate(pairs):
            rc[k, p["read"].size :] = 2
            rq[k, p["read"].size :] = 60
            hc[k, p["hap"].size :] = 1
        wide = np.asarray(
            pairhmm_forward_batch(
                rc,
                rq,
                rl,
                hc,
                hl,
                np.float32(DEFAULT_GAP_OPEN_PHRED),
                np.float32(DEFAULT_GAP_EXT_PHRED),
            )
        )
        np.testing.assert_array_equal(tight, wide[: len(pairs)])
        _assert_parity(wide, pairs)
        # Padded batch slots report the sentinel, never a number that
        # could be mistaken for a score.
        assert (wide[len(pairs) :] <= PAIRHMM_NEG_INF / 2).all()

    def test_shuffled_pair_order_is_bit_identical(self):
        """Per-pair results are elementwise along the batch axis: any
        permutation of the tile permutes the outputs exactly."""
        rng = np.random.default_rng(2)
        pairs = _random_pairs(rng, [(25, 40)] * 12)
        base = _run_batch(pairs)
        perm = rng.permutation(len(pairs))
        shuffled = _run_batch([pairs[i] for i in perm])
        np.testing.assert_array_equal(base[perm], shuffled)

    def test_n_bases_never_match(self):
        """Code 4 (N) on either side scores as a mismatch — including a
        consensus hole (all-N haplotype)."""
        quals = np.full(4, 30, np.int32)
        read = np.array([0, 1, 2, 3], np.int8)
        hap_n = np.full(8, 4, np.int8)
        out = _run_batch(
            [{"read": read, "quals": quals, "hap": hap_n}]
        )
        ref = pairhmm_forward_ref(read, quals, hap_n)
        np.testing.assert_allclose(
            out[0], ref, rtol=PAIRHMM_FORWARD_RTOL, atol=PAIRHMM_FORWARD_ATOL
        )
        # And strictly below the same read against a matching hap.
        hap_m = np.array([0, 1, 2, 3, 0, 0, 0, 0], np.int8)
        out_m = _run_batch(
            [{"read": read, "quals": quals, "hap": hap_m}]
        )
        assert out[0] < out_m[0]

    def test_likelihood_orders_edit_structures(self):
        """More damage, less likelihood: exact match > one mismatch,
        and every structured pair stays golden-parity."""
        pairs = synthetic_read_pairs(8, read_len=8, hap_len=14, seed=3)
        out = _run_batch(pairs)
        _assert_parity(out, pairs)
        by_kind = {}
        for p, v in zip(pairs, out):
            by_kind.setdefault(p["kind"], []).append(float(v))
        assert max(by_kind["mismatch"]) < max(by_kind["match"])

    def test_bucket_helper(self):
        assert pairhmm_bucket(0) == 8
        assert pairhmm_bucket(8) == 8
        assert pairhmm_bucket(9) == 16
        assert pairhmm_bucket(100) == 128
        assert pairhmm_bucket(3, floor=1) == 4
        assert pairhmm_bucket(1, floor=1) == 1


class TestSyntheticReadPairs:
    def test_match_kind_matches_hand_formula(self):
        """The whole point of the fixture: a reviewer can compute the
        match-kind likelihood on paper. The all-match path sum
        (h-r+1)·(1/h)·(1-2ε_go)^(r-1)·(1-ε)^r is a lower bound within
        ~1% of the full forward value at these shapes."""
        pairs = [
            p
            for p in synthetic_read_pairs(
                12, read_len=6, hap_len=10, quality=20, seed=0
            )
            if p["kind"] == "match"
        ]
        assert pairs
        eps = 10.0 ** (-20 / 10.0)
        eps_go = 10.0 ** (-DEFAULT_GAP_OPEN_PHRED / 10.0)
        eps_ge = 10.0 ** (-DEFAULT_GAP_EXT_PHRED / 10.0)
        for p in pairs:
            r, h = p["read"].size, p["hap"].size
            # Count the offsets where the read really is an exact
            # substring (the drawn hap may repeat the motif).
            n_off = sum(
                1
                for off in range(h - r + 1)
                if (p["hap"][off : off + r] == p["read"]).all()
            )
            hand = (
                np.log(n_off)
                - np.log(h)
                + np.log1p(-eps_ge)  # D(free start) -> M gap close
                + (r - 1) * np.log1p(-2 * eps_go)
                + r * np.log1p(-eps)
            )
            full = pairhmm_forward_ref(p["read"], p["quals"], p["hap"])
            assert hand <= full + 1e-12
            assert abs(full - hand) < 0.01 * abs(hand) + 0.02

    def test_deterministic_and_structured(self):
        a = synthetic_read_pairs(8, seed=5)
        b = synthetic_read_pairs(8, seed=5)
        for pa, pb in zip(a, b):
            assert pa["name"] == pb["name"]
            np.testing.assert_array_equal(pa["read"], pb["read"])
            np.testing.assert_array_equal(pa["hap"], pb["hap"])
        kinds = {p["kind"] for p in a}
        assert kinds == {"match", "mismatch", "insert", "delete"}
        for p in a:
            assert p["read"].size == 6 and p["hap"].size == 10

    def test_rejects_impossible_geometry(self):
        with pytest.raises(ValueError, match="must exceed"):
            synthetic_read_pairs(2, read_len=8, hap_len=8)


def _driver_conf(**kw):
    base = dict(
        references=READS_REFS,
        bases_per_partition=500,
        read_group_set_id=FIXTURE_READSET_ID,
    )
    base.update(kw)
    return PcaConfig(**base)


class TestPairHmmDriver:
    def test_scores_every_read_bit_identical_across_feeds(self):
        """Worker count and batch size change only wall-clock: the
        emitted rows (names, f32 log-likelihoods, buckets) are
        EXACTLY equal — the completion-order feed's contract."""
        from spark_examples_tpu.models.pairhmm import PairHmmDriver

        src = synthetic_reads(90, references=READS_REFS, seed=4)
        base = PairHmmDriver(_driver_conf(), src).run_rows()
        assert len(base) == 90
        assert base == sorted(base, key=lambda r: r[0])
        for workers, batch in ((1, 128), (3, 128), (3, 9), (2, 1)):
            rows = PairHmmDriver(
                _driver_conf(ingest_workers=workers, pairhmm_batch=batch),
                src,
            ).run_rows()
            assert rows == base

    def test_consensus_recovers_latent_haplotype_scores(self):
        """With enough coverage the consensus equals the latent
        haplotype, so an error-free read scores near the hand formula
        for a perfect substring — the fixture/driver loop closes."""
        from spark_examples_tpu.models.pairhmm import (
            PairHmmDriver,
            consensus_haplotype,
        )

        src = synthetic_reads(300, references=READS_REFS, seed=0)
        rows = PairHmmDriver(_driver_conf(), src).run_rows()
        scored = [r for r in rows if r[1] > PAIRHMM_NEG_INF / 2]
        assert len(scored) == 300
        # ~1% base error at Q~35: the bulk of reads should sit near
        # the few-errors regime, far above a random-sequence score.
        med = float(np.median([r[1] for r in scored]))
        assert -40.0 < med < 0.0
        # consensus_haplotype with zero coverage holds N (code 4).
        hole = consensus_haplotype([], 0, 16)
        assert (hole == 4).all()

    def test_empty_readset_warns_not_raises(self, capsys):
        from spark_examples_tpu.models.pairhmm import PairHmmDriver

        src = synthetic_reads(0, references=READS_REFS)
        rows = PairHmmDriver(_driver_conf(), src).run(out_path=None)
        assert rows == []
        assert "no read x haplotype pairs" in capsys.readouterr().err

    def test_flag_validation_is_loud(self):
        from spark_examples_tpu.models.pairhmm import PairHmmDriver

        src = synthetic_reads(1, references=READS_REFS)
        for kw, msg in (
            ({"pairhmm_batch": 0}, "pairhmm_batch"),
            ({"pairhmm_context": -1}, "pairhmm_context"),
            ({"pairhmm_gap_open_phred": 0.0}, "gap_open"),
            # At or below 10*log10(2) ~= 3.01 the M->M transition
            # probability is non-positive and every likelihood would
            # be NaN — rejected at the boundary, never a NaN sea.
            ({"pairhmm_gap_open_phred": 3.0}, "NaN"),
            ({"pairhmm_gap_ext_phred": -3.0}, "gap_ext"),
        ):
            with pytest.raises(ValueError, match=msg):
                PairHmmDriver(_driver_conf(**kw), src)

    def test_cli_run_emits_schema_valid_telemetry(self, tmp_path):
        """A real `cli pairhmm` run (the CI leg's shape): artifacts
        validate, the pairhmm spans and the bucket-labeled pair counter
        are present, and the score dump is written."""
        import scripts.validate_trace as validate
        from spark_examples_tpu.cli.main import main

        trace = str(tmp_path / "p.trace.json")
        metrics = str(tmp_path / "p.metrics.prom")
        rc = main(
            [
                "pairhmm",
                "--fixture-reads",
                "40",
                "--bases-per-partition",
                "1000",
                "--output-path",
                str(tmp_path),
                "--trace-out",
                trace,
                "--metrics-out",
                metrics,
            ]
        )
        assert rc == 0
        assert validate.validate_trace(trace) == []
        assert validate.validate_metrics(metrics) == []
        events = json.loads(open(trace).read())["traceEvents"]
        names = {e["name"] for e in events}
        assert {"pairhmm.bucket", "pairhmm.forward"} <= names
        prom = open(metrics).read()
        assert 'pairhmm_pairs_total{bucket="' in prom
        out = (tmp_path / "pairhmm_scores" / "part-00000").read_text()
        assert len(out.strip().splitlines()) == 40

    def test_schema_rejects_unknown_pairhmm_span(self, tmp_path):
        """Drift gate, rejection direction: a renamed pairhmm span
        fails validate_trace (GL003 holds the other direction)."""
        import scripts.validate_trace as validate

        path = tmp_path / "bad.trace.json"
        path.write_text(
            json.dumps(
                {
                    "traceEvents": [
                        {
                            "ph": "X",
                            "name": "pairhmm.scoar",
                            "pid": 1,
                            "ts": 0,
                            "dur": 1,
                        }
                    ]
                }
            )
        )
        errs = validate.validate_trace(str(path))
        assert errs and "unknown pairhmm span" in errs[0]


def _serving_fixture():
    src = synthetic_cohort(12, 120, references=READS_REFS, seed=2)
    src.add_reads(
        synthetic_reads(50, references=READS_REFS, seed=6).reads_records()
    )
    base = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        references=READS_REFS,
        bases_per_partition=1000,
    )
    return src, base


class TestPairhmmJobKind:
    def test_spec_validation(self):
        from spark_examples_tpu.serving import JobSpec

        spec = JobSpec.from_record(
            {"kind": "pairhmm", "read_group_set_id": FIXTURE_READSET_ID}
        )
        assert spec.kind == "pairhmm"
        rec = spec.to_record()
        assert rec["kind"] == "pairhmm"
        assert "variant_set_ids" not in rec
        assert JobSpec.from_record(rec) == spec  # journal round-trip
        with pytest.raises(ValueError, match="unknown job kind"):
            JobSpec.from_record({"kind": "bwa"})
        with pytest.raises(ValueError, match="do not apply"):
            JobSpec.from_record({"kind": "pairhmm", "num_pc": 3})
        with pytest.raises(ValueError, match="only to pairhmm"):
            JobSpec.from_record({"read_group_set_id": "x"})
        # Default kind keeps the historical record shape and keys.
        assert "kind" not in JobSpec().to_record()

    def test_pairhmm_job_runs_caches_and_isolates_from_pca(self):
        from spark_examples_tpu.serving import (
            AnalysisEngine,
            AnalysisJobTier,
            JobSpec,
        )

        src, base = _serving_fixture()
        tier = AnalysisJobTier(AnalysisEngine(src), base, workers=0)
        spec = JobSpec.from_record(
            {"kind": "pairhmm", "read_group_set_id": FIXTURE_READSET_ID}
        )
        job, created = tier.submit(spec)
        assert created
        while tier.step(timeout=0.0):
            pass
        assert job.state == "done", job.error
        assert len(job.result) == 50
        name, loglik, bucket = job.result[0]
        assert isinstance(loglik, float) and bucket.startswith("r")
        # Identical resubmission: result cache, no new work.
        again, created2 = tier.submit(spec)
        assert not created2 and again.cached
        assert again.result == job.result
        # A PCA job on the same tier still runs (and its key space
        # never collides with the pairhmm kind's).
        pca_job, _ = tier.submit(JobSpec.from_record({}))
        while tier.step(timeout=0.0):
            pass
        assert pca_job.state == "done", pca_job.error
        assert pca_job.key != job.key
        tier.close()

    def test_pairhmm_jobs_never_gang(self):
        """Gang coalescing is a Gramian-stack optimization; a pairhmm
        lead (or member) must run solo even with gangs armed."""
        from spark_examples_tpu.serving import (
            AnalysisEngine,
            AnalysisJobTier,
            JobSpec,
        )

        src, base = _serving_fixture()
        tier = AnalysisJobTier(
            AnalysisEngine(src), base, workers=0, gang_max_samples=256
        )
        phmm = JobSpec.from_record(
            {"kind": "pairhmm", "read_group_set_id": FIXTURE_READSET_ID}
        )
        jobs = [tier.submit(phmm)[0]]
        jobs.append(
            tier.submit(
                JobSpec.from_record(
                    {"kind": "pairhmm", "references": READS_REFS}
                )
            )[0]
        )
        while tier.step(timeout=0.0):
            pass
        assert all(j.state == "done" for j in jobs), [
            j.error for j in jobs
        ]
        tier.close()

    def test_kill_nine_restart_identical_result(self, tmp_path):
        """ISSUE 15 acceptance: a pairhmm job killed between the
        journaled start and execution re-queues on restart and re-runs
        to the EXACT same rows — the same chaos pin the PCA kind
        carries (exact float equality, deterministic f32 kernel)."""
        from spark_examples_tpu.resilience import faults
        from spark_examples_tpu.resilience.faults import (
            FaultPlan,
            FaultRule,
        )
        from spark_examples_tpu.serving import (
            AnalysisEngine,
            AnalysisJobTier,
            JobSpec,
            SimulatedCrash,
        )

        src, base = _serving_fixture()
        spec = JobSpec.from_record(
            {"kind": "pairhmm", "read_group_set_id": FIXTURE_READSET_ID}
        )
        # Baseline rows from a journal-less tier on the same source.
        solo = AnalysisJobTier(AnalysisEngine(src), base, workers=0)
        ref_job, _ = solo.submit(spec)
        while solo.step(timeout=0.0):
            pass
        assert ref_job.state == "done", ref_job.error
        solo.close()

        journal = str(tmp_path / "journal")
        tier = AnalysisJobTier(
            AnalysisEngine(src), base, workers=0, journal_dir=journal
        )
        plan = FaultPlan(
            seed=1,
            rules=[
                FaultRule(site="serving.job.kill", kind="error", times=1)
            ],
        )
        with faults.active_plan(plan):
            job, created = tier.submit(spec)
            assert created
            with pytest.raises(SimulatedCrash):
                tier.step(timeout=1.0)
        assert job.state == "running"  # abandoned, as a SIGKILL leaves it
        tier2 = AnalysisJobTier(
            AnalysisEngine(src), base, workers=0, journal_dir=journal
        )
        resumed = tier2.job(job.id)
        assert resumed is not None and resumed.state == "queued"
        assert tier2.step(timeout=1.0)
        assert resumed.state == "done", resumed.error
        assert resumed.result == ref_job.result  # exact equality
        tier2.close()
        # And a third tier replays the DONE job straight into the
        # cache — kill -9 after completion loses nothing either.
        tier3 = AnalysisJobTier(
            AnalysisEngine(src), base, workers=0, journal_dir=journal
        )
        cached, created3 = tier3.submit(spec)
        assert not created3 and cached.result == ref_job.result
        tier3.close()
