"""Source/STRICT-boundary/stats/datasets tests over the hermetic fixture."""

import numpy as np
import pytest

from spark_examples_tpu.genomics import FixtureSource, Shard
from spark_examples_tpu.genomics.callsets import CallsetIndex
from spark_examples_tpu.genomics.datasets import (
    af_filter,
    calls_stream,
    carrying_sample_indices,
    join_datasets,
    merge_datasets,
)
from spark_examples_tpu.genomics.fixtures import synthetic_cohort
from spark_examples_tpu.genomics.shards import shards_for_references
from spark_examples_tpu.genomics.sources import Callset, JsonlSource
from spark_examples_tpu.genomics.types import Call, Variant


def _variant(contig, start, vsid="vs1", calls=(), **kw):
    return Variant.build(
        contig,
        start,
        start + 1,
        "A",
        alternate_bases=["G"],
        variant_set_id=vsid,
        calls=calls,
        **kw,
    )


def _call(cid, gt):
    return Call(cid, cid, tuple(gt))


class TestStrictShardBoundary:
    def test_variant_in_exactly_one_shard(self):
        # A variant whose range straddles a shard boundary is yielded only
        # by the shard containing its START (STRICT semantics,
        # VariantsRDD.scala:210-211).
        src = FixtureSource(
            variants=[
                {
                    "reference_name": "17",
                    "start": 999_999,
                    "end": 1_000_050,
                    "reference_bases": "A" * 51,
                    "calls": [],
                }
            ]
        )
        shards = shards_for_references("17:0:2000000", 1_000_000)
        hits = [
            v
            for s in shards
            for v in src.stream_variants("", s)
        ]
        assert len(hits) == 1
        assert hits[0].start == 999_999

    def test_chr_prefix_matching(self):
        src = FixtureSource(
            variants=[
                {"reference_name": "chr17", "start": 5, "end": 6, "calls": []}
            ]
        )
        (v,) = src.stream_variants("", Shard("17", 0, 10))
        assert v.contig == "17"

    def test_dropped_contig_not_streamed(self):
        src = FixtureSource(
            variants=[
                {"reference_name": "chrX_alt", "start": 5, "end": 6},
                {"reference_name": "17", "start": 5, "end": 6},
            ]
        )
        out = list(src.stream_variants("", Shard("17", 0, 10)))
        assert len(out) == 1

    def test_stats_accumulate(self):
        src = synthetic_cohort(10, 50)
        shards = shards_for_references("17:41196311:41277499", 30_000)
        n = sum(len(list(src.stream_variants("", s))) for s in shards)
        assert n == 50
        assert src.stats.variants_read == 50
        assert src.stats.partitions == len(shards)
        assert src.stats.reference_bases == sum(s.range for s in shards)

    def test_fault_injection_then_retry(self):
        shard = Shard("17", 41196311, 41277499)
        src = synthetic_cohort(4, 10)
        src._fail_once.add(shard)
        with pytest.raises(IOError):
            list(src.stream_variants("", shard))
        # Deterministic manifest → idempotent re-ingest succeeds.
        assert len(list(src.stream_variants("", shard))) == 10
        assert src.stats.io_exceptions == 1


class TestCallsetIndex:
    def test_dense_index_across_sets(self):
        src = FixtureSource(
            callsets=[
                Callset("a", "S1", "vs1"),
                Callset("b", "S2", "vs1"),
                Callset("c", "S3", "vs2"),
            ]
        )
        idx = CallsetIndex.from_source(src, ["vs1", "vs2"])
        assert idx.size == 3
        assert idx.indexes == {"a": 0, "b": 1, "c": 2}
        assert idx.name_of_index() == ["S1", "S2", "S3"]


class TestDatasets:
    def test_af_filter(self):
        vs = [
            _variant("17", 1, info={"AF": ("0.05",)}),
            _variant("17", 2, info={"AF": ("0.5",)}),
            _variant("17", 3),  # no AF → dropped
        ]
        kept = list(af_filter(vs, 0.1))
        assert [v.start for v in kept] == [2]
        assert len(list(af_filter(vs, None))) == 3

    def test_carrying_sample_indices(self):
        v = _variant(
            "17",
            1,
            calls=[_call("a", (0, 1)), _call("b", (0, 0)), _call("c", (1, 1))],
        )
        assert carrying_sample_indices(v, {"a": 0, "b": 1, "c": 2}) == [0, 2]

    def test_join_two_datasets(self):
        idx = {"a": 0, "b": 1}
        set1 = [
            _variant("17", 1, calls=[_call("a", (0, 1))]),
            _variant("17", 9, calls=[_call("a", (1, 1))]),
        ]
        set2 = [_variant("17", 1, calls=[_call("b", (0, 1))])]
        out = list(join_datasets(set1, set2, idx))
        # Only position 1 is shared; calls concatenated.
        assert out == [[0, 1]]

    def test_merge_requires_presence_in_all(self):
        idx = {"a": 0, "b": 1, "c": 2}
        s1 = [_variant("17", 1, calls=[_call("a", (0, 1))])]
        s2 = [_variant("17", 1, calls=[_call("b", (0, 1))])]
        s3 = [
            _variant("17", 1, calls=[_call("c", (0, 1))]),
            _variant("17", 2, calls=[_call("c", (0, 1))]),
        ]
        out = list(merge_datasets([s1, s2, s3], idx))
        assert sorted(out[0]) == [0, 1, 2]
        assert len(out) == 1  # position 2 present in only one set

    def test_join_duplicate_identities_cross_product(self):
        # Duplicate identities within a dataset join like the reference's
        # RDD join: one output row per (left record, right record) pair.
        idx = {"a": 0, "b": 1, "c": 2, "d": 3}
        set1 = [
            _variant("17", 1, calls=[_call("a", (0, 1))]),
            _variant("17", 1, calls=[_call("b", (1, 1))]),
        ]
        set2 = [
            _variant("17", 1, calls=[_call("c", (0, 1))]),
            _variant("17", 1, calls=[_call("d", (1, 1))]),
        ]
        out = sorted(join_datasets(set1, set2, idx))
        assert out == [[0, 2], [0, 3], [1, 2], [1, 3]]

    def test_join_multi_contig_aligned_runs(self):
        idx = {"a": 0, "b": 1}
        s1 = [
            _variant(c, p, calls=[_call("a", (0, 1))])
            for c, p in [("1", 5), ("2", 7), ("17", 9)]
        ]
        s2 = [
            _variant(c, p, calls=[_call("b", (1, 1))])
            for c, p in [("1", 5), ("2", 8), ("17", 9)]
        ]
        # Contigs 1 and 17 share positions; contig 2 differs.
        assert list(
            join_datasets(s1, s2, idx, contig_runs_unique=True)
        ) == [[0, 1], [0, 1]]

    def test_join_divergent_run_order_still_correct(self):
        # Contig runs arriving in different orders fall back to the
        # unbounded path — results must be identical, nothing dropped.
        idx = {"a": 0, "b": 1}
        s1 = [
            _variant("1", 5, calls=[_call("a", (0, 1))]),
            _variant("2", 7, calls=[_call("a", (1, 1))]),
        ]
        s2 = [
            _variant("2", 7, calls=[_call("b", (0, 1))]),
            _variant("1", 5, calls=[_call("b", (1, 1))]),
        ]
        assert sorted(
            join_datasets(s1, s2, idx, contig_runs_unique=True)
        ) == [[0, 1], [0, 1]]

    def test_aligned_chunks_bounded_per_contig(self):
        from spark_examples_tpu.genomics.datasets import _aligned_chunks

        def mk(contigs, pos):
            return [_variant(c, pos) for c in contigs]

        # Aligned: one chunk per contig — join state is bounded by the
        # largest contig, not the cohort.
        chunks = [
            [list(part) for part in chunk]
            for chunk in _aligned_chunks([mk("123", 1), mk("123", 2)])
        ]
        assert len(chunks) == 3

        # A contig missing from one stream: lossless remainder fallback.
        chunks = [
            [list(part) for part in chunk]
            for chunk in _aligned_chunks([mk("123", 1), mk("13", 3)])
        ]
        assert len(chunks) == 2
        assert [v.contig for v in chunks[1][0]] == ["2", "3"]
        assert [v.contig for v in chunks[1][1]] == ["3"]

    def test_merge_multi_contig(self):
        idx = {"a": 0, "b": 1, "c": 2}

        def mk(cid):
            return [
                _variant(c, 1, calls=[_call(cid, (0, 1))]) for c in "12"
            ]

        out = list(
            merge_datasets(
                [mk("a"), mk("b"), mk("c")], idx, contig_runs_unique=True
            )
        )
        assert len(out) == 2
        assert all(sorted(row) == [0, 1, 2] for row in out)

    def test_calls_stream_drops_empty(self):
        idx = {"a": 0}
        vs = [
            _variant("17", 1, calls=[_call("a", (0, 0))]),  # no variation
            _variant("17", 2, calls=[_call("a", (0, 1))]),
        ]
        assert list(calls_stream([vs], idx)) == [[0]]


class TestJsonlRoundTrip:
    def test_jsonl_source(self, tmp_path):
        import json

        src = synthetic_cohort(6, 20)
        (tmp_path / "callsets.json").write_text(
            json.dumps(
                [
                    {"id": c.id, "name": c.name, "variant_set_id": c.variant_set_id}
                    for c in src._callsets
                ]
            )
        )
        with open(tmp_path / "variants.jsonl", "w") as f:
            for rec in src._variants:
                f.write(json.dumps(rec) + "\n")

        jsrc = JsonlSource(str(tmp_path))
        idx = CallsetIndex.from_source(jsrc, [src._callsets[0].variant_set_id])
        assert idx.size == 6
        shard = Shard("17", 41196311, 41277499)
        a = [v.start for v in jsrc.stream_variants("", shard)]
        b = [v.start for v in src.stream_variants("", shard)]
        assert a == b and len(a) == 20


class TestChrPrefixSymmetry:
    def test_shard_spec_with_chr_prefix_matches_bare_records(self):
        src = FixtureSource(
            variants=[
                {"reference_name": "17", "start": 5, "end": 6, "calls": []}
            ]
        )
        (v,) = src.stream_variants("", Shard("chr17", 0, 10))
        assert v.start == 5


class TestGzipCohort:
    def test_gzipped_jsonl_read(self, tmp_path):
        import gzip
        import json
        import shutil

        src = synthetic_cohort(5, 15)
        src.dump(str(tmp_path))
        # Compress variants.jsonl -> variants.jsonl.gz and remove the plain
        # file; JsonlSource must transparently read the gz.
        plain = tmp_path / "variants.jsonl"
        with open(plain, "rb") as fin, gzip.open(
            str(plain) + ".gz", "wb"
        ) as fout:
            shutil.copyfileobj(fin, fout)
        plain.unlink()

        jsrc = JsonlSource(str(tmp_path))
        shard = Shard("17", 41196311, 41277499)
        assert len(list(jsrc.stream_variants("", shard))) == 15


class TestStreamingCohortDump:
    def test_stream_dump_equals_in_memory_dump(self, tmp_path):
        import json

        from spark_examples_tpu.genomics.fixtures import (
            dump_cohort_stream,
            synthetic_cohort,
        )

        synthetic_cohort(6, 40, seed=8).dump(str(tmp_path / "mem"))
        dump_cohort_stream(str(tmp_path / "stream"), 6, 40, seed=8)
        for name in ("callsets.json", "variants.jsonl"):
            a = (tmp_path / "mem" / name).read_text()
            b = (tmp_path / "stream" / name).read_text()
            assert a == b, name

    def test_append_builds_joinable_multiset_cohort(self, tmp_path):
        import numpy as np

        from spark_examples_tpu.genomics.fixtures import dump_cohort_stream
        from spark_examples_tpu.genomics.sources import JsonlSource
        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        root = str(tmp_path / "c")
        dump_cohort_stream(root, 8, 60, variant_set_id="setA", seed=1)
        dump_cohort_stream(
            root, 8, 60, variant_set_id="setB", seed=1, append=True
        )
        conf = PcaConfig(
            variant_set_ids=["setA", "setB"],
            bases_per_partition=20_000,
            block_variants=32,
        )
        result = VariantsPcaDriver(conf, JsonlSource(root)).run()
        assert len(result) == 16
        # Identical cohorts under two set ids: twins coincide.
        by_name = {}
        for cid, pc1, pc2 in result:
            by_name.setdefault(cid.split("-", 1)[1], []).append((pc1, pc2))
        for name, coords in by_name.items():
            np.testing.assert_allclose(
                coords[0], coords[1], atol=1e-6, err_msg=name
            )
