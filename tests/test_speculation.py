"""Speculative execution for straggler shards — Spark speculation analog.

Spark re-launches a task that runs far past its stage's median on another
executor and takes the first finisher (the straggler half of the
elasticity the reference inherits; SURVEY.md §2.10). Here the extraction
unit is a shard, extraction is idempotent and deterministic, and the
duplicate races on a spare thread — so the winner's identity can never
change the output, only the wall-clock. These tests pin:

- a wedged head-of-line item is speculated and the duplicate's result
  unblocks the stream (order + values intact);
- no speculation without opting in, and never before the median-based
  eligibility threshold;
- a failed attempt defers to its survivor (speculation doubles as retry
  for stragglers that die slowly); both failing surfaces the error;
- the driver wires --speculative-ingest through with identical results.
"""

import threading
import time

import numpy as np
import pytest

from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.models.pca import VariantsPcaDriver
from spark_examples_tpu.utils import concurrency
from spark_examples_tpu.utils.concurrency import ordered_parallel_map
from spark_examples_tpu.utils.config import PcaConfig


@pytest.fixture()
def fast_thresholds(monkeypatch):
    """Shrink the eligibility knobs so tests run in milliseconds."""
    monkeypatch.setattr(concurrency, "SPECULATION_MIN_COMPLETED", 3)
    monkeypatch.setattr(concurrency, "SPECULATION_FLOOR_SECONDS", 0.02)
    monkeypatch.setattr(concurrency, "SPECULATION_MULTIPLIER", 3.0)


class TestSpeculation:
    def test_wedged_head_unblocked_by_duplicate(self, fast_thresholds):
        """First attempt at item 7 wedges until released; the speculative
        duplicate completes and the stream finishes correctly."""
        release = threading.Event()
        attempts = {}
        lock = threading.Lock()
        speculated = []

        def fn(i):
            with lock:
                n = attempts[i] = attempts.get(i, 0) + 1
            if i == 7 and n == 1:
                release.wait(30)  # the wedge: far past any threshold
            return i * i

        out = []
        for r in ordered_parallel_map(
            fn,
            range(12),
            workers=4,
            speculate=True,
            on_speculate=speculated.append,
        ):
            out.append(r)
            if r == 49:
                # The duplicate won; release the wedged original so the
                # pool can shut down promptly at stream end.
                release.set()
        assert out == [i * i for i in range(12)]
        assert speculated == [7]
        assert attempts[7] == 2  # exactly one duplicate

    def test_no_speculation_when_disabled(self, fast_thresholds):
        attempts = {}
        lock = threading.Lock()

        def fn(i):
            with lock:
                attempts[i] = attempts.get(i, 0) + 1
            if i == 5:
                time.sleep(0.4)  # slow but finite
            return i

        out = list(ordered_parallel_map(fn, range(10), workers=4))
        assert out == list(range(10))
        assert all(v == 1 for v in attempts.values())

    def test_not_eligible_before_min_completed(self, monkeypatch):
        """With the minimum sample count unmet, even a slow head is
        never speculated."""
        monkeypatch.setattr(concurrency, "SPECULATION_MIN_COMPLETED", 100)
        speculated = []

        def fn(i):
            if i == 0:
                time.sleep(0.3)
            return i

        out = list(
            ordered_parallel_map(
                fn,
                range(8),
                workers=4,
                speculate=True,
                on_speculate=speculated.append,
            )
        )
        assert out == list(range(8))
        assert speculated == []

    def test_failed_original_defers_to_speculative_survivor(
        self, fast_thresholds
    ):
        """The wedged original eventually dies; its duplicate's result is
        used and no error surfaces."""
        blow_up = threading.Event()
        attempts = {}
        lock = threading.Lock()

        def fn(i):
            with lock:
                n = attempts[i] = attempts.get(i, 0) + 1
            if i == 6 and n == 1:
                blow_up.wait(30)
                raise IOError("original died slowly")
            return i + 100

        speculated = []
        results = []
        for r in ordered_parallel_map(
            fn,
            range(10),
            workers=4,
            speculate=True,
            on_speculate=speculated.append,
        ):
            results.append(r)
            if r == 106:
                blow_up.set()  # duplicate already won; let original die
        assert results == [i + 100 for i in range(10)]
        assert speculated == [6]

    def test_both_attempts_failing_surfaces_error(self, fast_thresholds):
        def fn(i):
            if i == 4:
                time.sleep(0.5)
                raise IOError("shard is truly broken")
            return i

        with pytest.raises(IOError, match="truly broken"):
            list(
                ordered_parallel_map(
                    fn, range(10), workers=4, speculate=True
                )
            )

    def test_serial_path_ignores_speculation(self):
        out = list(
            ordered_parallel_map(
                lambda i: i, range(5), workers=1, speculate=True
            )
        )
        assert out == list(range(5))


class TestDriverWiring:
    def test_speculative_ingest_matches_plain(self, fast_thresholds):
        """--speculative-ingest produces a bit-identical Gramian (the
        duplicate's result IS the original's result)."""
        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            bases_per_partition=20_000,
            block_variants=64,
            ingest_workers=4,
            speculative_ingest=True,
        )
        driver = VariantsPcaDriver(conf, synthetic_cohort(12, 100))
        g = np.asarray(
            driver.get_similarity_matrix(driver.get_calls_fused())
        )

        plain = VariantsPcaDriver(
            PcaConfig(
                variant_set_ids=[DEFAULT_VARIANT_SET_ID],
                bases_per_partition=20_000,
                block_variants=64,
            ),
            synthetic_cohort(12, 100),
        )
        data = plain.get_data()
        calls = plain.get_calls([plain.filter_dataset(d) for d in data])
        np.testing.assert_array_equal(
            g, np.asarray(plain.get_similarity_matrix(calls))
        )
