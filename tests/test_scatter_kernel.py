"""Pallas scatter-accumulate kernel suite (ops/scatter_kernel.py).

Pins the one-hot-count outer-product formulation bit-identical to the
chunked-scan scatter across OOB sentinels, duplicate carriers, row
blocking, and k buckets — in interpreter mode, so the contract is
testable on the CPU container — plus the dispatcher's env kill switch /
auto-resolution semantics and the end-to-end sparse-engine integration
(``SPARK_EXAMPLES_TPU_SCATTER_KERNEL=interpret`` matches the dense
reference through both the single-device and mesh-sharded
accumulators).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_examples_tpu.arrays.blocks import csr_windows
from spark_examples_tpu.ops.gramian import gramian
from spark_examples_tpu.ops.scatter_kernel import (
    kernel_block_rows,
    resolve_scatter_path,
    scatter_pairs_kernel,
)
from spark_examples_tpu.ops.sparse import (
    SCATTER_CHUNK_VARIANTS,
    scatter_pairs_chunked,
    sparse_gramian_blockwise,
)
from spark_examples_tpu.parallel.mesh import make_mesh
from spark_examples_tpu.parallel.sharded import (
    _sparse_tile_kernels,
    sparse_sharded_gramian_blockwise,
)

from tests.test_sparse_gramian import cohort_csr


def _random_case(rng, t_r, t_c, v, k, oob_frac=0.2):
    row = rng.integers(0, t_r, size=(v, k)).astype(np.int32)
    col = rng.integers(0, t_c, size=(v, k)).astype(np.int32)
    # Sprinkle OOB sentinels the way the tile re-base does (any index
    # >= the axis size is dropped).
    row[rng.random((v, k)) < oob_frac] = t_r
    col[rng.random((v, k)) < oob_frac] = t_c + 7
    g = rng.integers(0, 9, size=(t_r, t_c)).astype(np.float32)
    return jnp.asarray(g), jnp.asarray(row), jnp.asarray(col)


class TestKernelBitIdentity:
    @pytest.mark.parametrize(
        "t_r,t_c,k",
        [
            (8, 128, 8),
            (64, 128, 16),
            (64, 256, 64),
            (128, 128, 8),
        ],
    )
    def test_matches_scan_across_geometries(self, t_r, t_c, k):
        rng = np.random.default_rng(t_r + t_c + k)
        g, row, col = _random_case(rng, t_r, t_c, SCATTER_CHUNK_VARIANTS * 2, k)
        a = np.asarray(scatter_pairs_chunked(g, row, col))
        b = np.asarray(scatter_pairs_kernel(g, row, col, interpret=True))
        np.testing.assert_array_equal(a, b)

    def test_duplicate_pairs_accumulate_multiply(self):
        # Same (row, col) pair repeated within one variant: scatter-add
        # applies every +1; the one-hot COUNT formulation must too.
        v = SCATTER_CHUNK_VARIANTS
        row = np.full((v, 8), 8, np.int32)  # all OOB (t_r = 8)
        col = np.full((v, 8), 200, np.int32)
        row[0, :4] = 3
        col[0, :4] = 77
        g = jnp.zeros((8, 128), jnp.float32)
        out = np.asarray(
            scatter_pairs_kernel(
                g, jnp.asarray(row), jnp.asarray(col), interpret=True
            )
        )
        assert out[3, 77] == 16.0  # 4 row hits x 4 col hits
        assert out.sum() == 16.0

    def test_all_sentinel_is_inert(self):
        v = SCATTER_CHUNK_VARIANTS
        row = np.full((v, 16), 64, np.int32)
        col = np.full((v, 16), 128, np.int32)
        g0 = np.arange(64 * 128, dtype=np.float32).reshape(64, 128)
        out = np.asarray(
            scatter_pairs_kernel(
                jnp.asarray(g0),
                jnp.asarray(row),
                jnp.asarray(col),
                interpret=True,
            )
        )
        np.testing.assert_array_equal(out, g0)

    def test_row_blocking_covers_tall_tiles(self, monkeypatch):
        # Force a tiny VMEM budget so the kernel must grid over row
        # blocks — the accumulating block is revisited per chunk and
        # the result must not change.
        rng = np.random.default_rng(5)
        g, row, col = _random_case(
            rng, 64, 128, SCATTER_CHUNK_VARIANTS * 2, 16
        )
        want = np.asarray(scatter_pairs_chunked(g, row, col))
        monkeypatch.setenv(
            "SPARK_EXAMPLES_TPU_SCATTER_KERNEL_VMEM",
            str(SCATTER_CHUNK_VARIANTS * 128 * 4 + 2 * 8 * 128 * 4
                + SCATTER_CHUNK_VARIANTS * 8 * 4
                + 2 * SCATTER_CHUNK_VARIANTS * 16 * 4),
        )
        assert kernel_block_rows(64, 128, 16) == 8
        got = np.asarray(
            scatter_pairs_kernel(g, row, col, interpret=True)
        )
        np.testing.assert_array_equal(want, got)

    def test_oversized_carrier_bucket_falls_back_in_dispatch(
        self, monkeypatch
    ):
        """The resolve-time budget check cannot see K (it varies per
        window): a carrier bucket whose (C, K) index blocks blow the
        budget must fall back to the scan body INSIDE the dispatch,
        bit-identically — never a Mosaic staging error mid-stream."""
        rng = np.random.default_rng(9)
        k = 64
        g, row, col = _random_case(
            rng, 64, 128, SCATTER_CHUNK_VARIANTS, k
        )
        # Budget passes the resolve-time check (k unknown → 0) but not
        # the dispatch's real-K check.
        budget = (
            SCATTER_CHUNK_VARIANTS * 128 * 4 + 2 * 8 * 128 * 4
            + SCATTER_CHUNK_VARIANTS * 8 * 4 + 1024
        )
        monkeypatch.setenv(
            "SPARK_EXAMPLES_TPU_SCATTER_KERNEL_VMEM", str(budget)
        )
        monkeypatch.setenv(
            "SPARK_EXAMPLES_TPU_SCATTER_KERNEL", "interpret"
        )
        assert resolve_scatter_path((64, 128)) == "interpret"
        assert kernel_block_rows(64, 128, k) is None
        want = np.asarray(scatter_pairs_chunked(g, row, col))
        got = np.asarray(
            scatter_pairs_kernel(g, row, col, interpret=True)
        )
        np.testing.assert_array_equal(want, got)


class TestDispatcher:
    def test_kill_switch_forces_scan(self, monkeypatch):
        monkeypatch.setenv("SPARK_EXAMPLES_TPU_SCATTER_KERNEL", "0")
        assert resolve_scatter_path((64, 128)) == "scan"

    def test_interpret_mode_forced(self, monkeypatch):
        monkeypatch.setenv(
            "SPARK_EXAMPLES_TPU_SCATTER_KERNEL", "interpret"
        )
        assert resolve_scatter_path((64, 128)) == "interpret"

    def test_auto_on_cpu_is_scan(self, monkeypatch):
        monkeypatch.delenv(
            "SPARK_EXAMPLES_TPU_SCATTER_KERNEL", raising=False
        )
        # No Mosaic backend on the CPU container: the compiled kernel
        # never engages; the exact historical executable does.
        assert resolve_scatter_path((64, 128)) == "scan"

    def test_ineligible_geometry_falls_back(self, monkeypatch):
        monkeypatch.setenv(
            "SPARK_EXAMPLES_TPU_SCATTER_KERNEL", "interpret"
        )
        # Lane-unaligned tile / non-f32 accumulator: scan.
        assert resolve_scatter_path((37, 37)) == "scan"
        assert (
            resolve_scatter_path((64, 128), np.float64) == "scan"
        )

    def test_vmem_budget_guard(self, monkeypatch):
        monkeypatch.setenv(
            "SPARK_EXAMPLES_TPU_SCATTER_KERNEL_VMEM", "1024"
        )
        assert kernel_block_rows(64, 128) is None
        monkeypatch.setenv(
            "SPARK_EXAMPLES_TPU_SCATTER_KERNEL", "interpret"
        )
        assert resolve_scatter_path((64, 128)) == "scan"


class TestEngineIntegration:
    """The kernel through the real accumulators: bit-identical G."""

    def test_single_device_engine_matches_dense(self, monkeypatch):
        n = 128  # lane-aligned so the interpret path engages
        x, pair = cohort_csr(n, 300, density=0.03, seed=11)
        want = np.asarray(gramian(x))
        monkeypatch.setenv(
            "SPARK_EXAMPLES_TPU_SCATTER_KERNEL", "interpret"
        )
        assert resolve_scatter_path((n, n)) == "interpret"
        got = np.asarray(
            sparse_gramian_blockwise(
                csr_windows(iter([pair]), 64), n, block_variants=64
            )
        )
        np.testing.assert_array_equal(got, want)

    def test_mesh_engine_matches_dense(self, monkeypatch):
        n = 256  # 2x2 mesh -> (128, 128) tiles, kernel-eligible
        x, pair = cohort_csr(n, 256, density=0.02, seed=12)
        want = np.asarray(gramian(x))
        mesh = make_mesh("data:2,model:2")
        monkeypatch.setenv(
            "SPARK_EXAMPLES_TPU_SCATTER_KERNEL", "interpret"
        )
        got = np.asarray(
            sparse_sharded_gramian_blockwise(
                csr_windows(iter([pair]), 64),
                n,
                mesh,
                block_variants=64,
            )
        )
        np.testing.assert_array_equal(got, want)

    def test_kernel_path_is_part_of_executable_cache_key(self):
        mesh = make_mesh("data:2,model:2")
        a = _sparse_tile_kernels(
            mesh, "data", "model", 256, 128, 128, "float32",
            "int8", "scan",
        )
        b = _sparse_tile_kernels(
            mesh, "data", "model", 256, 128, 128, "float32",
            "int8", "interpret",
        )
        assert a is not b  # distinct cached kernel sets per path
