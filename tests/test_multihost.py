"""Two-process multi-host integration test over real jax.distributed (gloo).

Spawns two subprocesses that each run the framework's own multi-host path:
``initialize_from_env`` (coordinator env vars), disjoint-shard ingest of the
same deterministic cohort, per-host partial Gramians, ``allreduce_gramian``
over DCN, stats merge via ``allreduce_host_stats``, and coordinator-only
emission — then checks the distributed result equals the single-process
pipeline bit-for-bit.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("SPARK_EXAMPLES_TPU_SKIP_MULTIHOST") == "1",
    reason="multihost tests disabled",
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(
    script_path, argv, env_extra=None, n=2, timeout=240, expected_rcs=None
):
    """Spawn n coordinator-connected worker processes and collect logs.

    A dead peer leaves the other blocked in a gloo collective — never
    leak one past the test (it would hold the port for the session).
    Asserts every worker exits 0, or matches ``expected_rcs`` when the
    test deliberately kills/fail-stops workers.
    """
    port = _free_port()
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ),
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": str(n),
        **(env_extra or {}),
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script_path)] + [str(a) for a in argv],
            env={**env, "JAX_PROCESS_ID": str(i)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(n)
    ]
    try:
        logs = [p.communicate(timeout=timeout)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if expected_rcs is None:
        for p, log in zip(procs, logs):
            assert p.returncode == 0, log[-2000:]
    else:
        rcs = [p.returncode for p in procs]
        assert rcs == list(expected_rcs), (rcs, logs[0][-1500:], logs[1][-1500:])
    return logs


_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from spark_examples_tpu.parallel.distributed import (
        allreduce_gramian,
        allreduce_host_stats,
        initialize_from_env,
        is_coordinator,
    )
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.genomics.callsets import CallsetIndex
    from spark_examples_tpu.genomics.datasets import calls_stream
    from spark_examples_tpu.genomics.shards import shards_for_references
    from spark_examples_tpu.arrays.blocks import blocks_from_calls
    from spark_examples_tpu.ops import gramian_blockwise

    assert initialize_from_env(), "distributed init did not trigger"
    pid = jax.process_index()

    # Same deterministic cohort on every host; disjoint shard slices.
    source = synthetic_cohort(10, 80, seed=5)
    index = CallsetIndex.from_source(source, [DEFAULT_VARIANT_SET_ID])
    shards = shards_for_references("17:41196311:41277499", 20_000)
    mine = shards[pid::2]  # round-robin host assignment

    def variants():
        for s in mine:
            yield from source.stream_variants(DEFAULT_VARIANT_SET_ID, s)

    calls = calls_stream([variants()], index.indexes)
    g_local = gramian_blockwise(
        blocks_from_calls(calls, index.size, 32), index.size
    )
    g = allreduce_gramian(g_local)
    stats = allreduce_host_stats(source.stats)

    # Also drive the FULL driver in multi-host mode: same cohort, the
    # driver slices the manifest per process itself and emits only on the
    # coordinator.
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        output_path=sys.argv[1] + f".driver",
    )
    result = VariantsPcaDriver(conf, synthetic_cohort(10, 80, seed=5)).run()

    if is_coordinator():
        import numpy as np
        out = {
            "g_sum": float(np.asarray(g).sum()),
            "g": np.asarray(g).tolist(),
            "partitions": stats.partitions,
            "variants_read": stats.variants_read,
            "driver_result": [[r[0], r[1], r[2]] for r in result],
        }
        with open(sys.argv[1], "w") as f:
            json.dump(out, f)
    """
)


def test_two_process_pipeline_matches_single(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    out_file = tmp_path / "result.json"
    _run_workers(script, [out_file])
    result = json.loads(out_file.read_text())

    # Single-process golden over the same cohort/manifest.
    from spark_examples_tpu.arrays.blocks import blocks_from_calls
    from spark_examples_tpu.genomics.callsets import CallsetIndex
    from spark_examples_tpu.genomics.datasets import calls_stream
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.genomics.shards import shards_for_references
    from spark_examples_tpu.ops import gramian_blockwise

    source = synthetic_cohort(10, 80, seed=5)
    index = CallsetIndex.from_source(source, [DEFAULT_VARIANT_SET_ID])
    shards = shards_for_references("17:41196311:41277499", 20_000)

    def variants():
        for s in shards:
            yield from source.stream_variants(DEFAULT_VARIANT_SET_ID, s)

    calls = calls_stream([variants()], index.indexes)
    g = np.asarray(
        gramian_blockwise(blocks_from_calls(calls, index.size, 32), index.size)
    )
    np.testing.assert_array_equal(np.asarray(result["g"]), g)
    # Stats merged across both hosts cover the full manifest.
    assert result["partitions"] == len(shards)
    assert result["variants_read"] == 80

    # Full-driver distributed run equals single-process driver run, and
    # only the coordinator wrote the TSV.
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
    )
    single = VariantsPcaDriver(
        conf, synthetic_cohort(10, 80, seed=5)
    ).run()
    dist = result["driver_result"]
    np.testing.assert_allclose(
        np.array([r[1:] for r in dist], dtype=float),
        np.array([r[1:] for r in single]),
        atol=1e-5,
    )
    assert os.path.exists(str(out_file) + ".driver-pca.tsv")


_GLOBAL_MESH_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.parallel.sharded import gramian_blockwise_global

    pid = jax.process_index()
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("host", "data"))
    rng = np.random.default_rng(7)
    all_blocks = [
        (rng.random((24, 32)) < 0.3).astype(np.int8) for _ in range(5)
    ]
    mine = all_blocks[:3] if pid == 0 else all_blocks[3:]  # uneven
    g = gramian_blockwise_global(iter(mine), 24, mesh)
    x = np.concatenate(all_blocks, axis=1).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(g), x @ x.T)

    # Full driver in pod mode: mesh spans both processes; the driver
    # routes to gramian_blockwise_global and skips the host-side merge.
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
    )
    driver = VariantsPcaDriver(
        conf, synthetic_cohort(16, 64, seed=11), mesh=mesh
    )
    assert driver._mesh_spans_processes()
    result = driver.run()

    if pid == 0:
        with open(sys.argv[1], "w") as f:
            json.dump(
                {
                    "ok": True,
                    "driver_result": [[r[0], r[1], r[2]] for r in result],
                },
                f,
            )
    """
)


def test_global_mesh_gramian_two_processes(tmp_path):
    """Multi-controller GSPMD: one mesh over 2 processes x 4 devices;
    uneven per-host block streams; result equals the dense Gramian."""
    script = tmp_path / "worker.py"
    script.write_text(_GLOBAL_MESH_WORKER)
    out_file = tmp_path / "result.json"
    _run_workers(
        script,
        [out_file],
        env_extra={"XLA_FLAGS": "--xla_force_host_platform_device_count=4"},
    )
    result = json.loads(out_file.read_text())
    assert result["ok"]

    # Pod-mode driver result equals the single-process driver run.
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
    )
    single = VariantsPcaDriver(conf, synthetic_cohort(16, 64, seed=11)).run()
    np.testing.assert_allclose(
        np.array([r[1:] for r in result["driver_result"]], dtype=float),
        np.array([r[1:] for r in single]),
        atol=1e-5,
    )


_NETWORK_INGEST_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.genomics.fixtures import DEFAULT_VARIANT_SET_ID
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    pid = jax.process_index()
    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
    )
    # Every process ingests ITS manifest slice from the shared service —
    # the reference's deployment shape (each executor streams its shards
    # from the API over its own channel, VariantsRDD.scala:205-235) —
    # on whichever transport argv selects.
    if sys.argv[3] == "grpc":
        from spark_examples_tpu.genomics.grpc_transport import (
            GrpcVariantSource,
        )
        source = GrpcVariantSource(sys.argv[2])
    else:
        from spark_examples_tpu.genomics.service import HttpVariantSource
        source = HttpVariantSource(sys.argv[2])
    result = VariantsPcaDriver(conf, source).run()
    if pid == 0:
        with open(sys.argv[1], "w") as f:
            json.dump(
                {"driver_result": [[r[0], r[1], r[2]] for r in result],
                 "partitions": source.stats.partitions}, f
            )
    """
)


@pytest.mark.parametrize("transport", ["http", "grpc"])
def test_two_process_network_ingest(tmp_path, transport):
    """DP across hosts with NETWORK ingest on BOTH transports: two
    processes each stream their manifest slice from one served cohort
    (HTTP/1.1 framed streams or gRPC/HTTP-2 server streams) and the
    merged result equals the single-process run over the same service."""
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )

    cohort = synthetic_cohort(10, 80, seed=5)
    if transport == "grpc":
        from spark_examples_tpu.genomics.grpc_transport import (
            GrpcGenomicsServer,
            GrpcVariantSource,
            grpc_available,
        )

        if not grpc_available():
            pytest.skip("grpcio not installed")
        server = GrpcGenomicsServer(cohort).start()
        url = f"grpc://127.0.0.1:{server.port}"
        make_client = lambda: GrpcVariantSource(url)  # noqa: E731
    else:
        from spark_examples_tpu.genomics.service import (
            GenomicsServiceServer,
            HttpVariantSource,
        )

        server = GenomicsServiceServer(cohort).start()
        url = f"http://127.0.0.1:{server.port}"
        make_client = lambda: HttpVariantSource(url)  # noqa: E731
    try:
        script = tmp_path / "worker.py"
        script.write_text(_NETWORK_INGEST_WORKER)
        out_file = tmp_path / "result.json"
        _run_workers(script, [out_file, url, transport])
        result = json.loads(out_file.read_text())

        from spark_examples_tpu.models.pca import VariantsPcaDriver
        from spark_examples_tpu.utils.config import PcaConfig

        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            bases_per_partition=20_000,
            block_variants=32,
        )
        single = VariantsPcaDriver(conf, make_client()).run()
        np.testing.assert_allclose(
            np.array(
                [r[1:] for r in result["driver_result"]], dtype=float
            ),
            np.array([r[1:] for r in single]),
            atol=1e-5,
        )
        # Process 0 streamed exactly ITS round-robin manifest slice.
        from spark_examples_tpu.genomics.shards import (
            shards_for_references,
        )

        assert result["partitions"] == len(
            shards_for_references(conf.references, 20_000)[0::2]
        )
    finally:
        server.stop()


_POD_CHECKPOINT_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.genomics.shards import shards_for_references
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    pid = jax.process_index()
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "model"))
    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        checkpoint_dir=sys.argv[2],
        checkpoint_every=1,
        sample_sharded=False,
    )
    source = synthetic_cohort(10, 80, seed=5)
    phase = sys.argv[3]
    driver = VariantsPcaDriver(conf, source, mesh=mesh)
    assert driver._mesh_spans_processes()
    if phase == "fail":
        # EVERY host's second-round shard fails, so both processes raise
        # before entering that round's collectives (round 1 is already
        # snapshotted on both).
        shards = shards_for_references(conf.references, 20_000)
        mine = shards[pid::2]
        source._fail_once.add(mine[1])
        try:
            driver.get_similarity_matrix_checkpointed()
            ok = False
        except RuntimeError as e:
            # Producer failures surface through the synced pod stream
            # (every process raises together), chaining the original
            # ingest error on the process(es) whose stream failed.
            ok = isinstance(e.__cause__, IOError)
        with open(sys.argv[1] + f".phase1.{pid}", "w") as f:
            json.dump({"ok": ok}, f)
    else:
        g = np.asarray(driver.get_similarity_matrix_checkpointed())
        if pid == 0:
            with open(sys.argv[1], "w") as f:
                json.dump(
                    {"g": g.tolist(),
                     "partitions": source.stats.partitions}, f
                )
    """
)


def test_pod_checkpoint_resume(tmp_path):
    """Pod-mode checkpoint/resume: globally-synced round cursor over a
    2-process global mesh; a mid-run failure on every host resumes from
    the last collective round and matches the single-process Gramian."""
    script = tmp_path / "worker.py"
    script.write_text(_POD_CHECKPOINT_WORKER)
    out_file = tmp_path / "result.json"
    ck_dir = tmp_path / "ck"

    def run_phase(phase):
        return _run_workers(
            script,
            [out_file, ck_dir, phase],
            env_extra={
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4"
            },
        )

    logs = run_phase("fail")
    for i in range(2):
        marker = json.loads((tmp_path / f"result.json.phase1.{i}").read_text())
        assert marker["ok"], logs[i][-2000:]
    assert (ck_dir / "host-0").exists() and (ck_dir / "host-1").exists()

    run_phase("resume")
    result = json.loads(out_file.read_text())
    # Round 1 resumed from its snapshot: the rerun re-streamed fewer
    # shards than the full manifest slice.
    assert result["partitions"] < 3

    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    plain = VariantsPcaDriver(
        PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            bases_per_partition=20_000,
            block_variants=32,
        ),
        synthetic_cohort(10, 80, seed=5),
    )
    data = plain.get_data()
    calls = plain.get_calls([plain.filter_dataset(d) for d in data])
    g_plain = np.asarray(plain.get_similarity_matrix(calls))
    np.testing.assert_array_equal(np.asarray(result["g"]), g_plain)


_SAMPLE_SHARDED_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()

    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    pid = jax.process_index()
    # 2 processes x 4 local devices; rows of the device grid are the
    # process boundary, so the "data" (sample-row) axis of G spans DCN and
    # "model" stays on-host — the stress config's layout at test scale.
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "model"))
    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        sample_sharded=True,
        dense_eigh_limit=8,  # force the randomized sharded-eig path
    )
    driver = VariantsPcaDriver(
        conf, synthetic_cohort(24, 96, seed=3), mesh=mesh
    )
    assert driver._mesh_spans_processes()
    assert driver._sample_sharded()
    result = driver.run()

    if pid == 0:
        with open(sys.argv[1], "w") as f:
            json.dump(
                {"driver_result": [[r[0], r[1], r[2]] for r in result]}, f
            )
    """
)


def test_sample_sharded_pod_two_processes(tmp_path):
    """The 100k-stress path at test scale: G sample-sharded P(data, model)
    over a 2-process x 4-device mesh, randomized sharded eig, full driver —
    matches the single-process sample-sharded run."""
    script = tmp_path / "worker.py"
    script.write_text(_SAMPLE_SHARDED_WORKER)
    out_file = tmp_path / "result.json"
    _run_workers(
        script,
        [out_file],
        env_extra={"XLA_FLAGS": "--xla_force_host_platform_device_count=4"},
    )
    result = json.loads(out_file.read_text())

    # Single-process golden: same config (sample-sharded + randomized eig)
    # on a local data:2,model:2 mesh — same math, different distribution.
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.parallel.mesh import make_mesh
    from spark_examples_tpu.utils.config import PcaConfig

    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        sample_sharded=True,
        dense_eigh_limit=8,
    )
    single = VariantsPcaDriver(
        conf,
        synthetic_cohort(24, 96, seed=3),
        mesh=make_mesh("data:2,model:2"),
    ).run()
    np.testing.assert_allclose(
        np.array([r[1:] for r in result["driver_result"]], dtype=float),
        np.array([r[1:] for r in single]),
        atol=1e-4,
    )


_SAMPLE_SHARDED_CHECKPOINT_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.genomics.shards import shards_for_references
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    pid = jax.process_index()
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "model"))
    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        checkpoint_dir=sys.argv[2],
        checkpoint_every=1,
        sample_sharded=True,
    )
    source = synthetic_cohort(24, 96, seed=3)
    phase = sys.argv[3]
    driver = VariantsPcaDriver(conf, source, mesh=mesh)
    assert driver._mesh_spans_processes()
    assert driver._sample_sharded()
    if phase == "fail":
        # EVERY host's second-round shard fails, so both processes raise
        # before entering that round's collectives (round 1 is already
        # tile-snapshotted on both).
        shards = shards_for_references(conf.references, 20_000)
        mine = shards[pid::2]
        source._fail_once.add(mine[1])
        try:
            driver.get_similarity_matrix_checkpointed()
            ok = False
        except RuntimeError as e:
            # Synced pod-stream failure protocol: RuntimeError on every
            # process, original ingest error chained on the failing one.
            ok = isinstance(e.__cause__, IOError)
        with open(sys.argv[1] + f".phase1.{pid}", "w") as f:
            json.dump({"ok": ok}, f)
    else:
        g = driver.get_similarity_matrix_checkpointed()
        assert not g.is_fully_addressable  # still cross-process sharded
        g_rep = jax.jit(
            lambda a: a, out_shardings=NamedSharding(mesh, P(None, None))
        )(g)
        if pid == 0:
            with open(sys.argv[1], "w") as f:
                json.dump(
                    {"g": np.asarray(g_rep).tolist(),
                     "partitions": source.stats.partitions}, f
                )
    """
)


def test_sample_sharded_pod_checkpoint_resume(tmp_path):
    """The stress-regime resume drill (round-2 verdict weak #6): G stays
    cross-process sample-sharded the whole time, every host snapshots
    only its addressable tiles, a mid-run failure resumes from the last
    collective round, and the result matches the plain Gramian."""
    script = tmp_path / "worker.py"
    script.write_text(_SAMPLE_SHARDED_CHECKPOINT_WORKER)
    out_file = tmp_path / "result.json"
    ck_dir = tmp_path / "ck"

    def run_phase(phase):
        return _run_workers(
            script,
            [out_file, ck_dir, phase],
            env_extra={
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4"
            },
        )

    logs = run_phase("fail")
    for i in range(2):
        marker = json.loads((tmp_path / f"result.json.phase1.{i}").read_text())
        assert marker["ok"], logs[i][-2000:]
    # Tile snapshots, one per host — and no replicated-G snapshot.
    for i in range(2):
        host = ck_dir / f"host-{i}"
        assert (host / "gramian_sharded_snapshot.npz").exists()
        assert not (host / "gramian_snapshot.npz").exists()

    run_phase("resume")
    result = json.loads(out_file.read_text())
    assert result["partitions"] < 3  # resumed, not re-ingested

    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    plain = VariantsPcaDriver(
        PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            bases_per_partition=20_000,
            block_variants=32,
        ),
        synthetic_cohort(24, 96, seed=3),
    )
    data = plain.get_data()
    calls = plain.get_calls([plain.filter_dataset(d) for d in data])
    g_plain = np.asarray(plain.get_similarity_matrix(calls))
    np.testing.assert_array_equal(np.asarray(result["g"]), g_plain)


_CHECKPOINT_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.genomics.shards import shards_for_references
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    pid = jax.process_index()
    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        checkpoint_dir=sys.argv[2],
        checkpoint_every=1,
    )
    source = synthetic_cohort(10, 80, seed=5)
    phase = sys.argv[3]
    if phase == "fail":
        # EVERY host's second shard fails mid-run, so both processes stop
        # before the cross-host merge (a lone survivor would block in the
        # collective against a dead peer).
        shards = shards_for_references(conf.references, 20_000)
        mine = shards[pid::2]
        source._fail_once.add(mine[1])
        driver = VariantsPcaDriver(conf, source)
        try:
            driver.get_similarity_matrix_checkpointed()
            ok = False
        except IOError:
            ok = True
        with open(sys.argv[1] + f".phase1.{pid}", "w") as f:
            json.dump({"ok": ok}, f)
    else:
        driver = VariantsPcaDriver(conf, source)
        g = np.asarray(driver.get_similarity_matrix_checkpointed())
        if pid == 0:
            with open(sys.argv[1], "w") as f:
                json.dump(
                    {"g": g.tolist(),
                     "partitions": source.stats.partitions}, f
                )
    """
)


def test_two_process_checkpoint_resume(tmp_path):
    """Per-host checkpointing: one host fails mid-ingest; the rerun resumes
    both hosts' slices from their own snapshots and matches single-process."""
    script = tmp_path / "worker.py"
    script.write_text(_CHECKPOINT_WORKER)
    out_file = tmp_path / "result.json"
    ck_dir = tmp_path / "ck"

    def run_phase(phase):
        return _run_workers(script, [out_file, ck_dir, phase])

    logs = run_phase("fail")
    for i in range(2):
        marker = json.loads((tmp_path / f"result.json.phase1.{i}").read_text())
        assert marker["ok"], logs[i][-2000:]
    # Both hosts wrote their own snapshots.
    assert (ck_dir / "host-0").exists() and (ck_dir / "host-1").exists()

    run_phase("resume")
    result = json.loads(out_file.read_text())
    # Host 0 re-streamed nothing on resume (its slice was complete) and
    # host 1 only its remaining shards; stats prove partial re-ingest.
    assert result["partitions"] < 3

    # Golden: single-process, uncheckpointed.
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    plain = VariantsPcaDriver(
        PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            bases_per_partition=20_000,
            block_variants=32,
        ),
        synthetic_cohort(10, 80, seed=5),
    )
    data = plain.get_data()
    calls = plain.get_calls([plain.filter_dataset(d) for d in data])
    g_plain = np.asarray(plain.get_similarity_matrix(calls))
    np.testing.assert_array_equal(np.asarray(result["g"]), g_plain)


_PROCESS_LOSS_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.genomics.shards import shards_for_references
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig
    from jax.sharding import Mesh

    pid = jax.process_index()
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "model"))
    conf = PcaConfig(
        variant_set_ids=[DEFAULT_VARIANT_SET_ID],
        bases_per_partition=20_000,
        block_variants=32,
        checkpoint_dir=sys.argv[2],
        checkpoint_every=1,
        sample_sharded=False,
        collective_timeout=10.0,
    )
    source = synthetic_cohort(10, 80, seed=5)
    phase = sys.argv[3]
    driver = VariantsPcaDriver(conf, source, mesh=mesh)
    assert driver._mesh_spans_processes()
    if phase == "wedge":
        # Process 1 WEDGES at its second-round shard (process alive,
        # heartbeats flowing — the stall the coordination service cannot
        # see), after round 1 is snapshotted on both hosts. Its own
        # watchdog fail-stops it mid-stall; process 0, facing a round-2
        # collective no peer will ever join, is fail-stopped by ITS
        # watchdog. Both must exit 77 — never hang.
        shards = shards_for_references(conf.references, 20_000)
        mine = shards[pid::2]
        if pid == 1:
            import time
            orig = source._shard_items
            def wedged(shard):
                if shard == mine[1]:
                    time.sleep(120)  # far past the watchdog deadline
                return orig(shard)
            source._shard_items = wedged
        driver.get_similarity_matrix_checkpointed()
        os._exit(0)  # unreachable for BOTH processes in this phase
    else:
        g = np.asarray(driver.get_similarity_matrix_checkpointed())
        if pid == 0:
            with open(sys.argv[1], "w") as f:
                json.dump(
                    {"g": g.tolist(),
                     "partitions": source.stats.partitions}, f
                )
    """
)


def test_process_loss_fail_stop_and_recovery(tmp_path):
    """The Spark-elasticity analog drill (round-2 verdict missing #1): an
    SPMD pod cannot reschedule a lost peer's work onto survivors, so the
    recovery contract is fail-stop + relaunch-with-resume. True process
    DEATH is already fail-stop — the JAX coordination service's heartbeat
    terminates survivors — so the drill exercises the stall heartbeats
    cannot see: a worker WEDGES mid-ingest, every process's collective
    watchdog exits 77 with an actionable diagnostic instead of hanging,
    and relaunching with the same manifest and checkpoint dir resumes all
    hosts from the last collective round, matching single-process."""
    script = tmp_path / "worker.py"
    script.write_text(_PROCESS_LOSS_WORKER)
    out_file = tmp_path / "result.json"
    ck_dir = tmp_path / "ck"

    def run_phase(phase, expected_rcs=None):
        return _run_workers(
            script,
            [out_file, ck_dir, phase],
            env_extra={
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4"
            },
            expected_rcs=expected_rcs,
        )

    logs = run_phase("wedge", expected_rcs=[77, 77])
    for log in logs:
        assert "FATAL: collective phase" in log
        assert "resume" in log  # the diagnostic tells the operator how
    # Round 1 was snapshotted on both hosts before the loss.
    assert (ck_dir / "host-0").exists() and (ck_dir / "host-1").exists()

    run_phase("resume")
    result = json.loads(out_file.read_text())
    assert result["partitions"] < 3  # resumed from round 1, not round 0

    from spark_examples_tpu.genomics.fixtures import (
        DEFAULT_VARIANT_SET_ID,
        synthetic_cohort,
    )
    from spark_examples_tpu.models.pca import VariantsPcaDriver
    from spark_examples_tpu.utils.config import PcaConfig

    plain = VariantsPcaDriver(
        PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            bases_per_partition=20_000,
            block_variants=32,
        ),
        synthetic_cohort(10, 80, seed=5),
    )
    data = plain.get_data()
    calls = plain.get_calls([plain.filter_dataset(d) for d in data])
    g_plain = np.asarray(plain.get_similarity_matrix(calls))
    np.testing.assert_array_equal(np.asarray(result["g"]), g_plain)


_SYNCED_FAILURE_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.parallel.sharded import gramian_blockwise_global

    pid = jax.process_index()
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("host", "data"))
    rng = np.random.default_rng(7)
    blocks = [
        (rng.random((24, 32)) < 0.3).astype(np.int8) for _ in range(3)
    ]
    scenario = sys.argv[2]
    if scenario == "packed-midstream":
        if pid == 0:
            # Mid-stream invariant violation: a dosage value sneaks into
            # the 0/1 stream. pack_indicator_block's host-side check fires
            # INSIDE the padded-blocks generator, before this process's
            # allgather — the exact one-sided shape that used to deadlock
            # the peer.
            blocks[1][0, 0] = 2
        stream, packed, expect_cause = iter(blocks), True, ValueError
    else:  # unpacked-first-peek: the peek in _accumulate_blocks raises
        def failing_first():
            if pid == 0:
                raise IOError("injected first-block ingest failure")
            yield from blocks
        stream, packed, expect_cause = failing_first(), False, IOError
    try:
        gramian_blockwise_global(stream, 24, mesh, packed=packed)
    except RuntimeError as e:
        ok = "block stream failed on process(es) [0]" in str(e)
        # The failing process chains the original producer exception.
        if pid == 0:
            ok = ok and isinstance(e.__cause__, expect_cause)
        else:
            ok = ok and e.__cause__ is None
        with open(sys.argv[1] + f".{pid}", "w") as f:
            json.dump({"ok": ok, "err": str(e)}, f)
        sys.exit(0 if ok else 3)
    sys.exit(4)  # no raise at all: the invariant check silently vanished
    """
)


@pytest.mark.parametrize(
    "scenario", ["packed-midstream", "unpacked-first-peek"]
)
def test_producer_failure_is_synced_not_one_sided(tmp_path, scenario):
    """A producer-side failure (non-0/1 block under packed=True, or an
    ingest error while peeking the first block's dtype) on ONE process
    must raise on EVERY process together — the healthy peer must not be
    left blocked in a collective forever."""
    script = tmp_path / "worker.py"
    script.write_text(_SYNCED_FAILURE_WORKER)
    out_file = tmp_path / "result.json"
    _run_workers(
        script,
        [out_file, scenario],
        env_extra={"XLA_FLAGS": "--xla_force_host_platform_device_count=4"},
        timeout=120,
    )
    for pid in (0, 1):
        result = json.loads((tmp_path / f"result.json.{pid}").read_text())
        assert result["ok"], result


_PEER_DEATH_WORKER = textwrap.dedent(
    """
    import json, os, signal, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.parallel.podstream import (
        PodSlot,
        PodWindowExchange,
        SlotPipeline,
    )

    pid = jax.process_index()
    world = jax.process_count()
    KILL_STEP = 3
    victim = world - 1  # never the coordinator (process 0)

    # Short deadline so a regression (survivor hanging out the receive
    # instead of converting peer death) fails the harness timeout, not
    # the 30-minute production deadline.
    ex = PodWindowExchange.open(timeout_s=30.0)
    assert ex is not None

    state = {"step": 0}

    def produce():
        step = state["step"]
        state["step"] += 1
        header = np.array([0, 0, 0, 0, 0, step, 0], np.int64)
        if pid == victim and step == KILL_STEP:
            # Die MID-exchange: header posted, confirm never follows —
            # survivors that already drained the buffered header must
            # still converge on the same slot via the confirm phase.
            ex.post_header(step, header)
            os.kill(os.getpid(), signal.SIGKILL)
        ex.post_header(step, header)
        gathered = ex.gather_headers(step, 7)
        failed = [
            i for i, row in enumerate(gathered) if int(row[0]) == -2
        ]
        if failed:
            raise RuntimeError(
                f"peers failed: {failed} at step {step}"
            )
        ex.post_confirm(step, True)
        confirms = ex.gather_confirms(step)
        bad = [i for i, v in enumerate(confirms) if int(v) == -2]
        if bad:
            raise RuntimeError(f"peers failed: {bad} at step {step}")
        return PodSlot(
            step=step,
            route="scatter",
            gathered=None,
            local=None,
            nnz=0,
            variants=0,
            windows=1,
        )

    completed = []
    err = None
    try:
        for slot in SlotPipeline(produce, depth=2):
            completed.append(slot.step)
    except RuntimeError as e:
        err = str(e)
    ok = (
        err is not None
        and f"at step {KILL_STEP}" in err
        and str(victim) in err
        and completed == list(range(KILL_STEP))
    )
    with open(sys.argv[1] + f".{pid}", "w") as f:
        json.dump({"ok": ok, "err": err, "completed": completed}, f)
    # _exit, not sys.exit: the atexit jax.distributed.shutdown would
    # barrier on the DEAD peer until the coordination-service heartbeat
    # aborts this process — the exact hang the conversion just avoided.
    os._exit(0 if ok else 3)
    """
)


@pytest.mark.parametrize("world", [2, 4])
def test_pod_peer_death_fails_everywhere_same_slot(tmp_path, world):
    """Kill -9 one pod process mid-exchange (header posted, confirm
    never follows): every SURVIVOR must raise the synchronized −2
    producer-failure shape at the SAME slot — the kill step — instead
    of hanging out the receive deadline one phase apart. Exercises the
    peer-death conversion (EOF/ECONNRESET → synthesized −2 rows) and
    the mesh-teardown cascade that propagates detection between
    survivors."""
    script = tmp_path / "worker.py"
    script.write_text(_PEER_DEATH_WORKER)
    out_file = tmp_path / "result.json"
    logs = _run_workers(
        script,
        [out_file],
        n=world,
        timeout=120,
        expected_rcs=[0] * (world - 1) + [-9],
    )
    for pid in range(world - 1):
        result = json.loads((tmp_path / f"result.json.{pid}").read_text())
        assert result["ok"], (result, logs[pid][-1500:])
        # All survivors agree: steps before the kill completed, the
        # raise landed exactly at the victim's slot.
        assert result["completed"] == [0, 1, 2]
