"""Reads kernels + the four example drivers (SearchReadsExample parity)."""


import numpy as np
import pytest

from spark_examples_tpu.genomics.fixtures import (
    NORMAL_READSET_ID,
    TUMOR_READSET_ID,
    synthetic_reads,
    synthetic_tumor_normal,
)
from spark_examples_tpu.models.search_reads import (
    Examples,
    average_coverage,
    per_base_depth_example,
    pileup,
    tumor_normal_diff,
)
from spark_examples_tpu.ops.reads_ops import (
    base_frequency_table,
    encode_bases,
    per_base_depth,
)


class TestKernels:
    def test_per_base_depth_vs_scalar(self):
        rng = np.random.default_rng(0)
        region = 500
        starts = rng.integers(-50, region, size=64).astype(np.int32)
        lengths = rng.integers(1, 120, size=64).astype(np.int32)
        lengths[5] = 0  # padding slot
        got = np.asarray(per_base_depth(starts, lengths, region))
        want = np.zeros(region, np.int32)
        for s, l in zip(starts, lengths):
            for p in range(max(0, s), min(region, s + l)):
                want[p] += 1
        np.testing.assert_array_equal(got, want)

    def test_encode_bases(self):
        np.testing.assert_array_equal(
            encode_bases("ACGTNacgtnX"), [0, 1, 2, 3, 4, 0, 1, 2, 3, 4, 4]
        )

    def test_base_frequency_table_vs_scalar(self):
        rng = np.random.default_rng(1)
        region, n, l = 300, 32, 50
        starts = rng.integers(-10, region, size=n).astype(np.int32)
        codes = rng.integers(0, 5, size=(n, l)).astype(np.int8)
        quals = rng.integers(0, 60, size=(n, l)).astype(np.int32)
        quals[3, :] = -1  # absent qualities → all skipped
        got = np.asarray(
            base_frequency_table(starts, codes, quals, 30, region)
        )
        want = np.zeros((region, 5), np.int32)
        for i in range(n):
            for j in range(l):
                p = starts[i] + j
                if 0 <= p < region and quals[i, j] >= 30:
                    want[p, codes[i, j]] += 1
        np.testing.assert_array_equal(got, want)


class TestPileup:
    def test_pileup_format(self):
        snp = Examples.CILANTRO
        src = synthetic_reads(
            200, references=f"11:{snp - 1000}:{snp + 1000}", seed=2
        )
        lines = pileup(src, 'fixture-readset', snp=snp)
        assert len(lines) > 2
        # v/^ markers anchored over the SNP column relative to first read.
        assert lines[0].endswith("v") and lines[-1].endswith("^")
        assert lines[0][:-1].strip() == "" and len(lines[0]) == len(lines[-1])
        # Each read line splices "(qq) " right after the SNP base.
        v_col = len(lines[0]) - 1
        for line in lines[1:-1]:
            assert line[v_col + 1 : v_col + 2] == "("
            assert line[v_col + 4 : v_col + 6] == ") "

    def test_pileup_empty_region(self):
        src = synthetic_reads(10, references="11:100:300", seed=0)
        assert pileup(src, 'fixture-readset', snp=Examples.CILANTRO) == []


class TestCoverage:
    def test_average_coverage(self):
        src = synthetic_reads(100, references="21:0:10000", read_len=100)
        cov = average_coverage(src, 'fixture-readset', contig="21", length=10_000)
        assert cov == pytest.approx(100 * 100 / 10_000)

    def test_depth_file(self, tmp_path):
        src = synthetic_reads(50, references="21:0:5000", read_len=80, seed=3)
        out = per_base_depth_example(
            src, 'fixture-readset', contig="21", length=5000, out_path=str(tmp_path)
        )
        lines = open(out).read().strip().split("\n")
        # Total depth equals total aligned bases (all reads inside region).
        total = sum(
            int(l.split(",")[1].rstrip(")")) for l in lines
        )
        assert total == 50 * 80
        # Ascending positions, "(pos,depth)" format.
        positions = [int(l.split(",")[0][1:]) for l in lines]
        assert positions == sorted(positions)


class TestTumorNormal:
    def test_diff_recovers_somatic_positions(self, tmp_path):
        refs = "1:100000000:100002000"
        src = synthetic_tumor_normal(
            600, references=refs, seed=7, n_somatic=3, somatic_fraction=0.9
        )
        out = tumor_normal_diff(
            src,
            normal_id=NORMAL_READSET_ID,
            tumor_id=TUMOR_READSET_ID,
            references=refs,
            out_path=str(tmp_path),
        )
        lines = open(out).read().strip().split("\n")
        found = {int(l.split(",")[0][1:]) for l in lines if l}
        # Every somatic position with 90% tumor fraction must be found
        # (noise positions may also appear; somatic must be a subset).
        assert set(src.somatic_positions) <= found

    def test_no_diff_for_identical_sets(self, tmp_path):
        refs = "1:100000000:100001000"
        normal = synthetic_reads(
            200, references=refs, read_group_set_id="a", seed=5
        )
        from spark_examples_tpu.genomics.sources import FixtureSource

        both = FixtureSource(
            reads=[
                {**r, "read_group_set_id": rid}
                for r in normal._reads
                for rid in ("a", "b")
            ]
        )
        out = tumor_normal_diff(
            both, "a", "b", references=refs, out_path=str(tmp_path)
        )
        assert open(out).read().strip() == ""


class TestLongReads:
    def test_freq_table_counts_beyond_row_cap(self):
        """Reads longer than the scatter-row width are chunked, not
        truncated — every aligned base counts (SearchReadsExample
        .scala:224-229), so the result is cap-invariant."""
        from spark_examples_tpu.models.search_reads import _freq_strings

        refs = "1:100000000:100003000"
        src = synthetic_reads(60, references=refs, read_len=700, seed=17)
        args = (src, "fixture-readset", refs, 1_000_000, 30, 30, 0.25)
        capped = _freq_strings(*args, read_len_cap=512)
        uncapped = _freq_strings(*args, read_len_cap=4096)
        assert capped == uncapped
        # Sanity: some output position is reachable ONLY through a base at
        # offset >= 512 of a read — the chunked tail really contributed.
        head_cover = set()
        tail_cover = set()
        for r in src._reads:
            pos, n = r["position"], len(r["aligned_sequence"])
            head_cover.update(range(pos, pos + min(n, 512)))
            tail_cover.update(range(pos + 512, pos + n))
        assert (tail_cover - head_cover) & set(capped)


class TestCoverageDenominator:
    def test_explicit_default_region_matches_default_path(self):
        src = synthetic_reads(100, references="21:0:10000", read_len=100)
        default = average_coverage(
            src, "fixture-readset", contig="21", length=10_000
        )
        explicit = average_coverage(
            src, "fixture-readset", references="21:0:10000"
        )
        assert explicit == default


class TestReadsCli:
    def test_cli_examples(self, capsys, tmp_path):
        from spark_examples_tpu.cli.main import main

        snp = Examples.CILANTRO
        rc = main(
            [
                "reads-example",
                "--example",
                "1",
                "--fixture-reads",
                "50",
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "v" in out and "^" in out

        rc = main(
            [
                "reads-example",
                "--example",
                "3",
                "--fixture-reads",
                "30",
                "--references",
                "21:0:4000",
                "--output-path",
                str(tmp_path),
            ]
        )
        assert rc == 0
        assert (tmp_path / "coverage_21" / "part-00000").exists()

        rc = main(
            [
                "reads-example",
                "--example",
                "4",
                "--fixture-reads",
                "200",
                "--references",
                "1:100000000:100001000",
                "--output-path",
                str(tmp_path),
            ]
        )
        assert rc == 0
        assert (tmp_path / "diff_1" / "part-00000").exists()


class TestShardBoundaryCarry:
    def test_depth_independent_of_shard_size(self, tmp_path):
        """A read straddling shard boundaries must contribute every base
        regardless of --bases-per-partition (overhang carry)."""
        src = synthetic_reads(40, references="21:0:5000", read_len=90, seed=11)
        outs = []
        for i, bps in enumerate((5000, 1000, 256)):
            out = per_base_depth_example(
                src,
                "fixture-readset",
                references="21:0:5000",
                out_path=str(tmp_path / str(i)),
                bases_per_shard=bps,
            )
            outs.append(open(out).read())
        assert outs[0] == outs[1] == outs[2]
        total = sum(
            int(l.split(",")[1].rstrip(")"))
            for l in outs[0].strip().split("\n")
        )
        assert total == 40 * 90

    def test_freq_diff_independent_of_shard_size(self, tmp_path):
        refs = "1:100000000:100001500"
        src = synthetic_tumor_normal(
            400, references=refs, seed=13, somatic_fraction=0.9
        )
        contents = []
        for i, bps in enumerate((1_000_000, 300)):
            out = tumor_normal_diff(
                src,
                NORMAL_READSET_ID,
                TUMOR_READSET_ID,
                references=refs,
                out_path=str(tmp_path / str(i)),
                bases_per_shard=bps,
            )
            contents.append(open(out).read())
        assert contents[0] == contents[1]


class TestComputeHarnessEdges:
    """Direct edge-case coverage for the per-shard compute harness in
    models/search_reads.py (`compute(shard, reads, pad)` through
    `_windowed_arrays`) and the `_pad_pow2` bucketing — the paths the
    whole-pipeline tests exercise only in aggregate."""

    def test_pad_pow2_floor_growth_and_exact_powers(self):
        from spark_examples_tpu.models.search_reads import _pad_pow2

        assert _pad_pow2(0) == 256  # the floor, even for nothing
        assert _pad_pow2(1) == 256
        assert _pad_pow2(256) == 256  # exact power stays put
        assert _pad_pow2(257) == 512  # one past doubles
        assert _pad_pow2(5000) == 8192
        assert _pad_pow2(3, floor=64) == 64
        assert _pad_pow2(65, floor=64) == 128

    def test_empty_shard_yields_zero_window_and_no_lines(self, tmp_path):
        """A shard with no reads must flow through the harness as an
        all-zero window (not crash, not emit) — the empty-region case."""
        from spark_examples_tpu.genomics.fixtures import FixtureSource

        src = FixtureSource(reads=[])
        out = per_base_depth_example(
            src,
            "",
            references="21:1000:3000",
            out_path=str(tmp_path),
            bases_per_shard=500,
        )
        assert open(out).read() == ""

    def test_single_read_depth_is_one_over_its_span(self, tmp_path):
        from spark_examples_tpu.genomics.fixtures import FixtureSource

        src = FixtureSource(
            reads=[
                {
                    "reference_name": "21",
                    "position": 1500,
                    "aligned_sequence": "ACGT" * 10,
                    "aligned_quality": [30] * 40,
                    "cigar_ops": [("ALIGNMENT_MATCH", 40)],
                    "mapping_quality": 50,
                    "fragment_name": "only-read",
                    "read_group_set_id": "rg",
                }
            ]
        )
        out = per_base_depth_example(
            src,
            "rg",
            references="21:1000:3000",
            out_path=str(tmp_path),
            bases_per_shard=500,
        )
        lines = open(out).read().strip().splitlines()
        assert lines == [f"({p},1)" for p in range(1500, 1540)]

    def test_pad_growth_read_longer_than_shard_carries_over(self, tmp_path):
        """A read LONGER than its whole shard forces the compute
        window's pad to grow past the shard range; the overhang must
        carry into the next window, independent of shard size."""
        from spark_examples_tpu.genomics.fixtures import FixtureSource

        read_len = 700  # > bases_per_shard below
        rec = {
            "reference_name": "21",
            "position": 1100,
            "aligned_sequence": "A" * read_len,
            "aligned_quality": [30] * read_len,
            "cigar_ops": [("ALIGNMENT_MATCH", read_len)],
            "mapping_quality": 50,
            "fragment_name": "long-read",
            "read_group_set_id": "rg",
        }
        expected = [
            f"({p},1)" for p in range(1100, 1100 + read_len)
        ]
        for shard_size in (200, 500, 5000):
            out = per_base_depth_example(
                FixtureSource(reads=[rec]),
                "rg",
                references="21:1000:4000",
                out_path=str(tmp_path / f"s{shard_size}"),
                bases_per_shard=shard_size,
            )
            assert open(out).read().strip().splitlines() == expected
