"""OAuth refresh-token exchange — CredentialFactory analog (Client.scala:42).

Round-2 verdict missing #3: the reference exchanges client secrets for a
user credential through the OAuth flow; this framework only accepted
pre-exchanged tokens. These tests pin the refresh-token grant against a
local fixture token endpoint (zero-egress environments cannot reach a
real one, exactly as the retired Genomics API is replaced by the
self-hosted service): grant validation, RFC 6749 §5.2 error surfacing,
both credential-file shapes on both resolution paths, and the
end-to-end proof — a served cohort streamed with a token minted by the
exchange.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from urllib.parse import parse_qs

import pytest

from spark_examples_tpu.genomics.auth import (
    ADC_ENV,
    AuthError,
    get_access_token,
)
from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.genomics.oauth import exchange_refresh_token
from spark_examples_tpu.genomics.service import (
    GenomicsServiceServer,
    HttpVariantSource,
)
from spark_examples_tpu.genomics.shards import shards_for_references


class _TokenEndpoint:
    """Minimal OAuth token endpoint: one registered refresh credential.

    Validates the POSTed grant exactly (grant_type + the full triple) and
    answers RFC 6749-shaped JSON: 200 {access_token} on a match,
    400 {error: invalid_grant} on a wrong refresh token,
    401 {error: invalid_client} on wrong client credentials.
    """

    def __init__(
        self,
        client_id="cid",
        client_secret="csec",
        refresh_token="rtok",
        access_token="minted-token",
        mode="ok",  # ok | no-token | not-json
    ):
        ep = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                form = {
                    k: v[0]
                    for k, v in parse_qs(
                        self.rfile.read(n).decode()
                    ).items()
                }
                ep.requests.append(form)
                if ep.mode == "not-json":
                    self._reply(200, b"<html>proxy error</html>")
                    return
                if form.get("grant_type") != "refresh_token":
                    self._reply_json(
                        400, {"error": "unsupported_grant_type"}
                    )
                elif (
                    form.get("client_id") != ep.client_id
                    or form.get("client_secret") != ep.client_secret
                ):
                    self._reply_json(401, {"error": "invalid_client"})
                elif form.get("refresh_token") != ep.refresh_token:
                    self._reply_json(
                        400,
                        {
                            "error": "invalid_grant",
                            "error_description": "token revoked",
                        },
                    )
                elif ep.mode == "no-token":
                    self._reply_json(200, {"token_type": "Bearer"})
                else:
                    self._reply_json(
                        200,
                        {
                            "access_token": ep.access_token,
                            "expires_in": 3599,
                            "token_type": "Bearer",
                        },
                    )

            def _reply_json(self, code, obj):
                self._reply(code, json.dumps(obj).encode())

            def _reply(self, code, body):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.client_id = client_id
        self.client_secret = client_secret
        self.refresh_token = refresh_token
        self.access_token = access_token
        self.mode = mode
        self.requests = []
        self._server = HTTPServer(("127.0.0.1", 0), Handler)
        self.uri = f"http://127.0.0.1:{self._server.server_port}/token"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


@pytest.fixture()
def endpoint():
    ep = _TokenEndpoint()
    try:
        yield ep
    finally:
        ep.stop()


class TestExchange:
    def test_success(self, endpoint):
        tok = exchange_refresh_token(
            "cid", "csec", "rtok", token_uri=endpoint.uri
        )
        assert tok == "minted-token"
        assert endpoint.requests[0]["grant_type"] == "refresh_token"

    def test_invalid_client_surfaced(self, endpoint):
        with pytest.raises(AuthError, match="invalid_client"):
            exchange_refresh_token(
                "cid", "WRONG", "rtok", token_uri=endpoint.uri
            )

    def test_invalid_grant_description_surfaced(self, endpoint):
        with pytest.raises(AuthError, match="token revoked"):
            exchange_refresh_token(
                "cid", "csec", "STALE", token_uri=endpoint.uri
            )

    def test_missing_access_token_rejected(self, endpoint):
        endpoint.mode = "no-token"
        with pytest.raises(AuthError, match="no access_token"):
            exchange_refresh_token(
                "cid", "csec", "rtok", token_uri=endpoint.uri
            )

    def test_non_json_response_rejected(self, endpoint):
        endpoint.mode = "not-json"
        with pytest.raises(AuthError, match="malformed JSON"):
            exchange_refresh_token(
                "cid", "csec", "rtok", token_uri=endpoint.uri
            )

    def test_unreachable_endpoint(self):
        with pytest.raises(AuthError, match="cannot reach"):
            exchange_refresh_token(
                "cid",
                "csec",
                "rtok",
                token_uri="http://127.0.0.1:1/token",
                timeout=2,
            )


def _authorized_user(endpoint, **extra):
    return {
        "type": "authorized_user",
        "client_id": endpoint.client_id,
        "client_secret": endpoint.client_secret,
        "refresh_token": endpoint.refresh_token,
        "token_uri": endpoint.uri,
        **extra,
    }


class TestGetAccessTokenExchange:
    def test_client_secrets_triple_after_confirmation(
        self, tmp_path, endpoint
    ):
        f = tmp_path / "secrets.json"
        f.write_text(json.dumps(_authorized_user(endpoint)))
        prompts = []
        creds = get_access_token(
            str(f),
            interactive=True,
            _input=lambda p: prompts.append(p) or "y",
        )
        assert creds.token == "minted-token"
        assert creds.source == "client-secrets"
        assert len(prompts) == 1  # warned BEFORE exchanging

    def test_declined_secrets_never_exchange(self, tmp_path, endpoint):
        f = tmp_path / "secrets.json"
        f.write_text(json.dumps(_authorized_user(endpoint)))
        with pytest.raises(AuthError, match="declined"):
            get_access_token(
                str(f), interactive=True, _input=lambda p: "n"
            )
        assert endpoint.requests == []  # no network before consent

    def test_installed_nesting(self, tmp_path, endpoint):
        f = tmp_path / "secrets.json"
        f.write_text(
            json.dumps({"installed": _authorized_user(endpoint)})
        )
        creds = get_access_token(
            str(f), interactive=True, _input=lambda p: "y"
        )
        assert creds.token == "minted-token"

    def test_adc_authorized_user_no_prompt(
        self, tmp_path, endpoint, monkeypatch
    ):
        """The gcloud ADC file shape exchanges without confirmation —
        Client.scala:44's ambient-credential path."""
        f = tmp_path / "adc.json"
        f.write_text(json.dumps(_authorized_user(endpoint)))
        monkeypatch.setenv(ADC_ENV, str(f))

        def no_input(prompt):  # pragma: no cover - must never run
            raise AssertionError("ADC path must not prompt")

        creds = get_access_token(_input=no_input)
        assert creds.token == "minted-token"
        assert creds.source == "application-default"

    def test_adc_revoked_token_fails_loud(
        self, tmp_path, endpoint, monkeypatch
    ):
        f = tmp_path / "adc.json"
        f.write_text(
            json.dumps(_authorized_user(endpoint, refresh_token="STALE"))
        )
        monkeypatch.setenv(ADC_ENV, str(f))
        with pytest.raises(AuthError, match="invalid_grant"):
            get_access_token()


class TestEndToEnd:
    def test_served_cohort_with_exchanged_token(
        self, tmp_path, endpoint, monkeypatch
    ):
        """The full credential path: the genomics service requires a
        Bearer token; the client's ADC file holds only a refresh
        credential; the exchange mints the exact token the server
        expects and ingest streams successfully."""
        endpoint.access_token = "sekrit"
        src = synthetic_cohort(6, 40, seed=3)
        server = GenomicsServiceServer(src, token="sekrit").start()
        try:
            f = tmp_path / "adc.json"
            f.write_text(json.dumps(_authorized_user(endpoint)))
            monkeypatch.setenv(ADC_ENV, str(f))
            creds = get_access_token()
            http = HttpVariantSource(
                f"http://127.0.0.1:{server.port}", credentials=creds
            )
            shard = shards_for_references(
                "17:41196311:41277499", 100_000
            )[0]
            got = list(
                http.stream_variants(DEFAULT_VARIANT_SET_ID, shard)
            )
            assert len(got) == 40
            assert http.stats.unsuccessful_responses == 0
        finally:
            server.stop()
