"""Cold-path streaming ingest: overlap fetch → decode → build → put.

Pins the cold-stream tier end to end (ISSUE 9 / ROADMAP item 3):

- mirror durability: every mirror file commits tmp → fsync → atomic
  rename (the ``mirror.write`` torn seam proves a kill -9 mid-write can
  never land a torn file at a committed name), and the deterministic
  staging dir means a restarted cold run REUSES a killed run's partial
  mirror instead of re-downloading it;
- the cold-stream tier itself: with an empty ``--cache-dir`` the source
  streams wire frames immediately (no mirror barrier) while the mirror
  downloads write-through in the background; ``--no-cold-stream``
  restores the phased path; G is bit-identical across cold-stream vs
  phased, worker counts, and shard arrival orders;
- the ``ingest.stream`` fault seam: mid-pipeline stall/error/truncate
  retries per ``--shard-retries`` to a bit-identical G, and fails
  loudly with retries off (GL005 discipline);
- observability: ``ingest.fetch``/``ingest.stream`` spans and the
  ``cold_stream_shards_total{stage}`` counter, schema-checked by
  ``scripts/validate_trace.py`` (closed sets, both directions);
- the loopback cold acceptance: against a latency-shaped server the
  streaming cold path beats the phased cold path >= 2x, and the first
  ``gramian.accumulate`` span begins before the last ``ingest.fetch``
  span ends — the device really does start before the last shard is
  off the wire.
"""

import importlib.util
import json
import os
import shutil
import time

import numpy as np
import pytest

from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.genomics.mirror import ColdStreamMirror
from spark_examples_tpu.genomics.service import (
    GenomicsServiceServer,
    HttpVariantSource,
)
from spark_examples_tpu.genomics.shards import shards_for_references
from spark_examples_tpu.genomics.sources import (
    MIRROR_COMPLETE_MARKER,
    SIDECAR_BASENAME,
    JsonlSource,
)
from spark_examples_tpu.models.pca import VariantsPcaDriver
from spark_examples_tpu.resilience import (
    FaultPlan,
    FaultRule,
    faults,
)
from spark_examples_tpu.utils.config import PcaConfig

REFS = "17:41196311:41277499"
VSID = DEFAULT_VARIANT_SET_ID


def _load_validate_trace():
    spec = importlib.util.spec_from_file_location(
        "validate_trace",
        os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "scripts",
            "validate_trace.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def cohort_dir(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("coldstream") / "cohort")
    synthetic_cohort(50, 400, references=REFS, seed=21).dump(root)
    src = JsonlSource(root)
    src.ensure_serving_index()  # sidecar + line index warm for serving
    return root


@pytest.fixture()
def served(cohort_dir):
    server = GenomicsServiceServer(JsonlSource(cohort_dir)).start()
    try:
        yield cohort_dir, f"http://127.0.0.1:{server.port}"
    finally:
        server.stop()


def _driver(source, **overrides):
    overrides.setdefault("ingest_workers", 2)
    conf = PcaConfig(
        references=REFS,
        variant_set_ids=[VSID],
        bases_per_partition=15_000,
        block_variants=64,
        **overrides,
    )
    return VariantsPcaDriver(conf, source)


def _gramian(source, **overrides):
    drv = _driver(source, **overrides)
    return np.asarray(drv.get_similarity_matrix_csr(drv.get_csr_fused()))


def _staging_dir(cache, mode="full"):
    entries = [
        e for e in os.listdir(cache) if e.startswith(".staging-cohort-")
    ]
    assert len(entries) <= 1, entries
    return os.path.join(cache, entries[0]) if entries else None


def _mirror_root(cache):
    entries = [e for e in os.listdir(cache) if e.startswith("cohort-")]
    return os.path.join(cache, entries[0]) if entries else None


class TestMirrorDurability:
    """Satellite: tmp-then-atomic-rename with fsync at every mirror
    write, pinned with the mirror.write torn seam, plus the
    restart-reuses-partial-mirror contract."""

    def test_torn_write_never_lands_and_restart_heals(
        self, served, tmp_path
    ):
        root, url = served
        cache = str(tmp_path / "cache")
        plan = FaultPlan(
            seed=1,
            rules=[
                FaultRule(
                    site="mirror.write",
                    kind="torn",
                    match="variants.jsonl",
                    times=1,
                )
            ],
        )
        src = HttpVariantSource(url, cache_dir=cache, cold_stream=False)
        with faults.active_plan(plan):
            with pytest.raises(IOError):
                src.list_callsets(VSID)
        assert plan.fired_total == 1
        # The torn write landed nowhere a reader trusts: no completed
        # mirror, no committed variants.jsonl — only a *.tmp-* partial
        # in the staging dir (exactly what a kill -9 mid-write leaves).
        assert _mirror_root(cache) is None
        staging = _staging_dir(cache)
        assert staging is not None
        assert not os.path.exists(os.path.join(staging, "variants.jsonl"))
        assert any(".tmp-" in e for e in os.listdir(staging))
        # callsets.json committed BEFORE the fault is whole and kept.
        assert os.path.exists(os.path.join(staging, "callsets.json"))
        # Restart (same cache, no plan): the download completes and the
        # mirror is byte-identical to one downloaded with no fault.
        src2 = HttpVariantSource(url, cache_dir=cache, cold_stream=False)
        src2.list_callsets(VSID)
        healed = _mirror_root(cache)
        assert healed is not None
        assert os.path.exists(
            os.path.join(healed, MIRROR_COMPLETE_MARKER)
        )
        clean_cache = str(tmp_path / "clean")
        HttpVariantSource(
            url, cache_dir=clean_cache, cold_stream=False
        ).list_callsets(VSID)
        clean = _mirror_root(clean_cache)
        for name in ("callsets.json", "variants.jsonl", SIDECAR_BASENAME):
            with open(os.path.join(healed, name), "rb") as a, open(
                os.path.join(clean, name), "rb"
            ) as b:
                assert a.read() == b.read(), name

    def test_restart_reuses_partial_mirror(self, cohort_dir, tmp_path):
        class _CountingExports:
            def __init__(self, inner):
                self._inner = inner
                self.exports = {}

            def export_lines(self, name):
                self.exports[name] = self.exports.get(name, 0) + 1
                return self._inner.export_lines(name)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        counting = _CountingExports(JsonlSource(cohort_dir))
        server = GenomicsServiceServer(counting).start()
        cache = str(tmp_path / "cache")
        try:
            url = f"http://127.0.0.1:{server.port}"
            # Run 1 dies mid-download: callsets.json commits, then the
            # variants.jsonl write errors (a worker death / kill).
            plan = FaultPlan(
                seed=1,
                rules=[
                    FaultRule(
                        site="mirror.write",
                        kind="error",
                        match="variants.jsonl",
                        times=1,
                    )
                ],
            )
            with faults.active_plan(plan):
                with pytest.raises(IOError):
                    HttpVariantSource(
                        url, cache_dir=cache, cold_stream=False
                    ).list_callsets(VSID)
            assert counting.exports.get("callsets.json") == 1
            # Run 2 reuses the staged callsets.json: the export is NOT
            # re-fetched; only the missing files are.
            src = HttpVariantSource(
                url, cache_dir=cache, cold_stream=False
            )
            callsets = src.list_callsets(VSID)
            assert counting.exports.get("callsets.json") == 1  # reused
            assert counting.exports.get("variants.jsonl") == 2
            mirror = _mirror_root(cache)
            assert mirror is not None and os.path.exists(
                os.path.join(mirror, MIRROR_COMPLETE_MARKER)
            )
            # The healed mirror actually serves (parity with the truth).
            want = [c.id for c in JsonlSource(cohort_dir).list_callsets(VSID)]
            assert [c.id for c in callsets] == want
        finally:
            server.stop()

    def test_concurrent_populate_never_touches_live_peers_staging(
        self, served, tmp_path
    ):
        """Two processes cold on the same cache/identity: the shared
        deterministic staging is serialized by a pid lock, and a LIVE
        peer's lock routes this populate into an isolated one-shot dir
        — the peer's in-flight files are never swept, and losing the
        populate race is still success."""
        root, url = served
        cache = str(tmp_path / "cache")
        os.makedirs(cache)
        ident = JsonlSource(root).cohort_identity()
        lock = os.path.join(cache, f".lock-cohort-{ident}-full")
        with open(lock, "w") as f:
            f.write(str(os.getpid()))  # a LIVE peer holds the lock
        shared = os.path.join(cache, f".staging-cohort-{ident}-full")
        os.makedirs(shared)
        peer_tmp = os.path.join(shared, f"variants.jsonl.tmp-{os.getpid()}")
        with open(peer_tmp, "w") as f:
            f.write("peer in-flight bytes")
        src = HttpVariantSource(url, cache_dir=cache, cold_stream=False)
        got = [c.id for c in src.list_callsets(VSID)]
        want = [c.id for c in JsonlSource(root).list_callsets(VSID)]
        assert got == want  # populated via the isolated path
        assert os.path.exists(
            os.path.join(_mirror_root(cache), MIRROR_COMPLETE_MARKER)
        )
        # The live peer's staging and in-flight tmp were never touched.
        assert os.path.exists(peer_tmp)
        assert os.path.exists(lock)
        os.unlink(lock)

    def test_prune_spares_live_foreign_staging_reaps_dead_one(
        self, served, tmp_path
    ):
        """Post-download pruning of OTHER identities' staging dirs must
        consult their pid locks: in a shared cache_dir two different
        cohorts may mirror concurrently (HTTP and gRPC sources share
        caches), and a live peer's in-flight staging must survive a
        sibling's successful download — while a dead run's foreign
        staging is still reaped so cache_dir stays bounded."""
        root, url = served
        cache = str(tmp_path / "cache")
        os.makedirs(cache)
        live = os.path.join(cache, ".staging-cohort-otherlive-full")
        os.makedirs(live)
        with open(
            os.path.join(cache, ".lock-cohort-otherlive-full"), "w"
        ) as f:
            f.write(str(os.getpid()))  # that cohort's populate is LIVE
        dead = os.path.join(cache, ".staging-cohort-otherdead-full")
        os.makedirs(dead)
        with open(
            os.path.join(cache, ".lock-cohort-otherdead-full"), "w"
        ) as f:
            f.write("999999999")  # owner is gone
        src = HttpVariantSource(url, cache_dir=cache, cold_stream=False)
        assert [c.id for c in src.list_callsets(VSID)]
        assert os.path.isdir(live)  # live peer untouched
        assert not os.path.exists(dead)  # dead run's staging reaped
        os.unlink(os.path.join(cache, ".lock-cohort-otherlive-full"))

    def test_prune_spares_peer_mid_acquisition_before_pid_lands(
        self, served, tmp_path
    ):
        """The in-acquisition window: a peer has opened + flocked its
        lock file but not yet written its pid (the file is EMPTY — or
        still holds a dead run's stale pid). The prune loop must probe
        with flock, not trust the file content: classifying that lock
        as stale would unlink it and rmtree the peer's staging while
        the peer legitimately holds the flock, letting a third
        populator sweep the peer's in-flight files (the TOCTOU the
        shared-staging lock exists to prevent)."""
        import fcntl

        root, url = served
        cache = str(tmp_path / "cache")
        os.makedirs(cache)
        staging = os.path.join(cache, ".staging-cohort-acquiring-full")
        os.makedirs(staging)
        lock = os.path.join(cache, ".lock-cohort-acquiring-full")
        fd = os.open(lock, os.O_CREAT | os.O_RDWR)
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        try:  # pid NOT yet written: content is empty, flock is held
            src = HttpVariantSource(
                url, cache_dir=cache, cold_stream=False
            )
            assert [c.id for c in src.list_callsets(VSID)]
            assert os.path.isdir(staging)  # spared: flock says LIVE
            assert os.path.exists(lock)
        finally:
            os.close(fd)
        os.unlink(lock)

    def test_dead_lock_holder_is_broken_and_staging_reused(
        self, served, tmp_path
    ):
        root, url = served
        cache = str(tmp_path / "cache")
        os.makedirs(cache)
        ident = JsonlSource(root).cohort_identity()
        lock = os.path.join(cache, f".lock-cohort-{ident}-full")
        with open(lock, "w") as f:
            f.write("999999999")  # a pid that cannot be alive
        src = HttpVariantSource(url, cache_dir=cache, cold_stream=False)
        assert [c.id for c in src.list_callsets(VSID)]
        assert not os.path.exists(lock)  # broken, then released

    def test_foreign_host_owner_is_never_judged_dead(
        self, served, tmp_path
    ):
        """On a shared cache mount the lock records ``pid@host``, and a
        FOREIGN host's owner must always count as alive: os.kill probes
        only the local pid table, so a remote peer's pid number is
        meaningless here — judging it 'dead' would reap a live remote
        populate's staging (the exact mount-without-flock-propagation
        case the recorded owner exists for). A dead-LOOKING foreign
        owner therefore routes this populate to the isolated one-shot
        path and spares the foreign staging from the prune loop."""
        root, url = served
        cache = str(tmp_path / "cache")
        os.makedirs(cache)
        ident = JsonlSource(root).cohort_identity()
        # Same identity: a foreign host is mid-populate on the shared
        # staging. Its pid would read as dead in OUR pid table.
        lock = os.path.join(cache, f".lock-cohort-{ident}-full")
        with open(lock, "w") as f:
            f.write("999999999@some.other.host")
        shared = os.path.join(cache, f".staging-cohort-{ident}-full")
        os.makedirs(shared)
        probe = os.path.join(shared, "foreign-in-flight")
        with open(probe, "w") as f:
            f.write("remote peer bytes")
        # A different identity's foreign staging, also dead-by-pid.
        other = os.path.join(cache, ".staging-cohort-otherhost-full")
        os.makedirs(other)
        with open(
            os.path.join(cache, ".lock-cohort-otherhost-full"), "w"
        ) as f:
            f.write("999999999@some.other.host")
        src = HttpVariantSource(url, cache_dir=cache, cold_stream=False)
        got = [c.id for c in src.list_callsets(VSID)]
        want = [c.id for c in JsonlSource(root).list_callsets(VSID)]
        assert got == want  # populated via the isolated one-shot path
        assert os.path.exists(
            os.path.join(_mirror_root(cache), MIRROR_COMPLETE_MARKER)
        )
        # Neither foreign staging (nor lock) was touched.
        assert os.path.exists(probe)
        assert os.path.exists(lock)
        assert os.path.isdir(other)
        os.unlink(lock)
        os.unlink(os.path.join(cache, ".lock-cohort-otherhost-full"))

    def test_failed_upgrade_leaves_no_partials_in_mirror_root(
        self, served, tmp_path
    ):
        """A torn commit during a light→full upgrade must not leak
        ``.partial-*`` / ``*.tmp-*`` files into the COMPLETED mirror
        root: unlike staging dirs, the trusted root is never swept, so
        a leftover would accumulate forever (one per crashed upgrade)."""
        root, url = served
        cache = str(tmp_path / "cache")
        light = HttpVariantSource(
            url, cache_dir=cache, mirror_mode="light", cold_stream=False
        )
        assert [c.id for c in light.list_callsets(VSID)]
        mirror_root = _mirror_root(cache)
        assert mirror_root is not None
        plan = FaultPlan(
            seed=1,
            rules=[
                FaultRule(
                    site="mirror.write",
                    kind="torn",
                    match="variants.jsonl",
                    times=1,
                )
            ],
        )
        full = HttpVariantSource(
            url, cache_dir=cache, mirror_mode="full", cold_stream=False
        )
        shard = shards_for_references(REFS, 20_000)[0]
        with faults.active_plan(plan):
            with pytest.raises(IOError):
                list(full.stream_variants(VSID, shard))
        assert plan.fired_total == 1
        leftovers = [
            e
            for e in os.listdir(mirror_root)
            if e.startswith(".partial-") or ".tmp-" in e
        ]
        assert leftovers == []
        # And the upgrade gate re-fires: a fresh full-mode consumer
        # completes the upgrade and serves records with parity.
        full2 = HttpVariantSource(
            url, cache_dir=cache, mirror_mode="full", cold_stream=False
        )
        got = list(full2.stream_variants(VSID, shard))
        want = list(JsonlSource(root).stream_variants(VSID, shard))
        assert got == want
        assert os.path.exists(
            os.path.join(mirror_root, "variants.jsonl")
        )

    def test_tolerated_sidecar_failure_publishes_no_tmp(
        self, served, tmp_path
    ):
        """In full mode a failed sidecar export is tolerated (the
        mirror parses locally) — but the tolerated failure still
        publishes the staging as the COMPLETED mirror root, so the
        cleanup must also remove the sidecar's *.tmp-* partial or a
        sidecar-sized leftover leaks into the trusted root forever."""
        root, url = served
        cache = str(tmp_path / "cache")
        plan = FaultPlan(
            seed=1,
            rules=[
                FaultRule(
                    site="mirror.write",
                    kind="torn",
                    match=SIDECAR_BASENAME,
                    times=1,
                )
            ],
        )
        src = HttpVariantSource(url, cache_dir=cache, cold_stream=False)
        with faults.active_plan(plan):
            got = [c.id for c in src.list_callsets(VSID)]
        assert plan.fired_total == 1
        want = [c.id for c in JsonlSource(root).list_callsets(VSID)]
        assert got == want
        mirror_root = _mirror_root(cache)
        assert mirror_root is not None
        assert os.path.exists(
            os.path.join(mirror_root, MIRROR_COMPLETE_MARKER)
        )
        assert not os.path.exists(
            os.path.join(mirror_root, SIDECAR_BASENAME)
        )
        leftovers = [
            e for e in os.listdir(mirror_root) if ".tmp-" in e
        ]
        assert leftovers == []

    def test_probe_resolve_failure_defers_to_ingest_seam(self):
        """A transient failure inside cold_stream_active's resolve (the
        /identity round-trip, or a synchronous light→full upgrade) must
        answer 'not cold-streaming' — not kill the run from the driver
        thread. The resolve then happens lazily at the first shard
        fetch, inside the per-shard --shard-retries seam that has
        always covered it."""
        import threading

        from spark_examples_tpu.genomics.mirror import (
            refresh_cold_stream,
        )

        class _FlakySource:
            _cold_stream = True
            _mirror = None
            _mirror_lock = threading.Lock()

            def _resolve_mirror(self):
                raise IOError("transient: identity fetch failed")

        assert refresh_cold_stream(_FlakySource()) is False

    def test_corrupt_sidecar_member_falls_back_and_rebuilds(
        self, cohort_dir, tmp_path
    ):
        """mmap fast path keeps np.load's corruption detection: a
        bit-flipped committed sidecar must fail its CRC and trigger the
        rebuild, never serve garbage ordinals."""
        import shutil as _shutil

        from spark_examples_tpu.genomics import sources as S

        root = str(tmp_path / "cohort")
        _shutil.copytree(cohort_dir, root)
        side = os.path.join(root, SIDECAR_BASENAME)
        want = [
            c.id for c in JsonlSource(cohort_dir).list_callsets(VSID)
        ]
        blob = bytearray(open(side, "rb").read())
        blob[len(blob) // 2] ^= 0xFF  # flip a payload bit
        with open(side, "wb") as f:
            f.write(blob)
        assert S._load_sidecar_mmap(side) is None  # CRC catches it
        src = JsonlSource(root)
        indexes = {c.id: i for i, c in enumerate(src.list_callsets(VSID))}
        shard = shards_for_references(REFS, 100_000)[0]
        got = src.stream_carrying_csr(VSID, shard, indexes)
        ref = JsonlSource(cohort_dir).stream_carrying_csr(
            VSID, shard, indexes
        )
        np.testing.assert_array_equal(ref[0], got[0])  # rebuilt, correct
        np.testing.assert_array_equal(ref[1], got[1])
        assert [c.id for c in src.list_callsets(VSID)] == want

    def test_stale_staging_for_other_identity_discarded(
        self, served, tmp_path
    ):
        root, url = served
        cache = str(tmp_path / "cache")
        ident = JsonlSource(root).cohort_identity()
        staging = os.path.join(cache, f".staging-cohort-{ident}-full")
        os.makedirs(staging)
        # A stale staging pinned to ANOTHER identity holding a poisoned
        # file that must never be donated to the new mirror.
        with open(os.path.join(staging, ".identity"), "w") as f:
            f.write("some-older-cohort")
        with open(os.path.join(staging, "callsets.json"), "w") as f:
            f.write("[]")  # poison: would break list_callsets if reused
        src = HttpVariantSource(url, cache_dir=cache, cold_stream=False)
        got = [c.id for c in src.list_callsets(VSID)]
        want = [c.id for c in JsonlSource(root).list_callsets(VSID)]
        assert got == want  # the poisoned file was discarded, not reused


class TestColdStreamTier:
    def test_cold_stream_serves_wire_and_writes_through(
        self, served, tmp_path
    ):
        root, url = served
        cache = str(tmp_path / "cache")
        src = HttpVariantSource(url, cache_dir=cache)  # cold_stream on
        assert src.cold_stream_active() is True
        local = JsonlSource(root)
        indexes = {
            c.id: i for i, c in enumerate(local.list_callsets(VSID))
        }
        # Shard CSR pairs ride the wire immediately, with parity.
        checked = 0
        for shard in shards_for_references(REFS, 15_000):
            want = local.stream_carrying_csr(VSID, shard, indexes)
            got = src.stream_carrying_csr(VSID, shard, indexes)
            if want is None:
                assert got is None
                continue
            np.testing.assert_array_equal(want[0], got[0])
            np.testing.assert_array_equal(want[1], got[1])
            checked += 1
        assert checked > 0
        # The write-through mirror completes as a SIDE EFFECT.
        mirror = src._resolve_mirror()
        assert isinstance(mirror, ColdStreamMirror) and not mirror
        assert mirror.join(timeout=60)
        mirror_root = _mirror_root(cache)
        assert mirror_root is not None
        assert os.path.exists(
            os.path.join(mirror_root, MIRROR_COMPLETE_MARKER)
        )
        # The next run is WARM: same cache, mirror served locally.
        warm = HttpVariantSource(url, cache_dir=cache)
        assert warm.cold_stream_active() is False
        shard = shards_for_references(REFS, 15_000)[0]
        want = local.stream_carrying_csr(VSID, shard, indexes)
        got = warm.stream_carrying_csr(VSID, shard, indexes)
        np.testing.assert_array_equal(want[0], got[0])
        np.testing.assert_array_equal(want[1], got[1])

    def test_resident_source_upgrades_to_mirror_at_run_boundary(
        self, served, tmp_path
    ):
        """A LONG-LIVED source (the serving engine runs every job
        against one resident instance) must not stay pinned to the
        wire tier forever after one cold resolve: once the write-
        through download has finished, the next run's
        ``cold_stream_active`` consultation drops the cached sentinel
        and re-resolves — reading the completed mirror from disk, with
        parity."""
        root, url = served
        cache = str(tmp_path / "cache")
        src = HttpVariantSource(url, cache_dir=cache)  # cold_stream on
        assert src.cold_stream_active() is True  # run 1: cold, wire
        mirror = src._resolve_mirror()
        assert isinstance(mirror, ColdStreamMirror)
        assert mirror.join(timeout=60)  # write-through lands
        # Run 2 on the SAME instance: the boundary consultation flips.
        assert src.cold_stream_active() is False
        upgraded = src._resolve_mirror()
        assert isinstance(upgraded, JsonlSource)
        local = JsonlSource(root)
        indexes = {
            c.id: i for i, c in enumerate(local.list_callsets(VSID))
        }
        shard = shards_for_references(REFS, 15_000)[0]
        want = local.stream_carrying_csr(VSID, shard, indexes)
        got = src.stream_carrying_csr(VSID, shard, indexes)
        np.testing.assert_array_equal(want[0], got[0])
        np.testing.assert_array_equal(want[1], got[1])

    def test_no_cold_stream_is_phased(self, served, tmp_path):
        root, url = served
        cache = str(tmp_path / "cache")
        src = HttpVariantSource(url, cache_dir=cache, cold_stream=False)
        # With the flag off this is a PURE probe: the phased mirror
        # download must not run here (the driver consults this before
        # ingest, and an eager download would sit OUTSIDE the per-shard
        # retry seam that has always covered the phased path's lazy
        # first-fetch resolve).
        assert src.cold_stream_active() is False
        assert not os.path.isdir(cache) or _mirror_root(cache) is None
        # First data access downloads the whole mirror before serving.
        assert [c.id for c in src.list_callsets(VSID)]
        mirror_root = _mirror_root(cache)
        assert mirror_root is not None
        assert os.path.exists(
            os.path.join(mirror_root, MIRROR_COMPLETE_MARKER)
        )

    def test_cold_stream_inactive_without_cache_dir(self, served):
        _, url = served
        assert HttpVariantSource(url).cold_stream_active() is False

    def test_g_bit_identical_cold_vs_phased_across_workers(
        self, served, tmp_path
    ):
        """The acceptance bit-identity pin: G from the cold-stream path
        equals the phased path's and the local sidecar's, bit for bit,
        at any worker count and either shard arrival order (cold-stream
        defaults to completion order; integer-exact accumulation makes
        arrival order irrelevant — same argument PR 3 pinned)."""
        root, url = served
        g_local = _gramian(JsonlSource(root))
        g_phased = _gramian(
            HttpVariantSource(
                url,
                cache_dir=str(tmp_path / "phased"),
                cold_stream=False,
            )
        )
        assert np.array_equal(g_local, g_phased)
        for workers in (1, 3):
            for order in ("manifest", "completion"):
                cache = str(
                    tmp_path / f"cold-{workers}-{order}"
                )
                g_cold = _gramian(
                    HttpVariantSource(url, cache_dir=cache),
                    ingest_workers=workers,
                    ingest_order=order,
                )
                assert np.array_equal(g_local, g_cold), (
                    workers,
                    order,
                )

    def test_cold_stream_telemetry_schema_valid(self, served, tmp_path):
        from spark_examples_tpu.obs.session import TelemetrySession

        root, url = served
        trace = str(tmp_path / "run.trace.json")
        metrics = str(tmp_path / "run.metrics.prom")
        with TelemetrySession(
            trace_out=trace, metrics_out=metrics
        ) as session:
            g = _gramian(
                HttpVariantSource(
                    url, cache_dir=str(tmp_path / "cache")
                )
            )
            assert g.shape[0] == 50
            snap = session.registry.snapshot()
        counters = snap["counters"]
        n_shards = len(shards_for_references(REFS, 15_000))
        fetched = sum(
            v
            for k, v in counters.items()
            if k.startswith("cold_stream_shards_total")
            and 'stage="fetched"' in k
        )
        accumulated = sum(
            v
            for k, v in counters.items()
            if k.startswith("cold_stream_shards_total")
            and 'stage="accumulated"' in k
        )
        assert fetched == accumulated == n_shards
        validate = _load_validate_trace()
        assert validate.validate_trace(trace) == []
        assert validate.validate_metrics(metrics) == []
        # The new spans really are on the timeline.
        events = json.load(open(trace))["traceEvents"]
        names = {e.get("name") for e in events}
        assert "ingest.fetch" in names
        assert "ingest.stream" in names

    def test_validate_metrics_rejects_unlabeled_cold_counter(
        self, tmp_path
    ):
        path = tmp_path / "bad.prom"
        path.write_text(
            "# HELP cold_stream_shards_total x\n"
            "# TYPE cold_stream_shards_total counter\n"
            "cold_stream_shards_total 3\n"
        )
        validate = _load_validate_trace()
        errs = validate.validate_metrics(str(path))
        assert errs and "stage" in errs[0]

    def test_validate_trace_rejects_unknown_ingest_span(self, tmp_path):
        path = tmp_path / "bad.trace.json"
        path.write_text(
            json.dumps(
                {
                    "traceEvents": [
                        {
                            "ph": "X",
                            "name": "ingest.fetchh",
                            "pid": 1,
                            "ts": 0,
                            "dur": 1,
                        }
                    ]
                }
            )
        )
        validate = _load_validate_trace()
        errs = validate.validate_trace(str(path))
        assert errs and "ingest.fetchh" in errs[0]


class TestIngestStreamSeam:
    """Satellite: the deterministic ``ingest.stream`` fault seam rides
    the per-shard RetryPolicy loop — chaos runs pin fault-free-identical
    results, and with retries off the failure is LOUD (GL005: no silent
    degradation, no ad-hoc sleeps)."""

    @pytest.mark.parametrize("kind", ["error", "stall", "truncate"])
    def test_fault_retries_to_identical_g(self, cohort_dir, kind):
        g_ref = _gramian(JsonlSource(cohort_dir))
        plan = FaultPlan(
            seed=7,
            rules=[FaultRule(site="ingest.stream", kind=kind, times=2)],
        )
        with faults.active_plan(plan):
            g = _gramian(JsonlSource(cohort_dir), shard_retries=3)
        assert plan.fired_total == 2
        assert np.array_equal(g_ref, g)

    @pytest.mark.parametrize("kind", ["error", "truncate"])
    def test_fault_without_retries_is_loud(self, cohort_dir, kind):
        plan = FaultPlan(
            seed=7,
            rules=[FaultRule(site="ingest.stream", kind=kind, times=1)],
        )
        with faults.active_plan(plan):
            with pytest.raises(IOError):
                _gramian(JsonlSource(cohort_dir), shard_retries=1)
        assert plan.fired_total == 1


class _SlowCohort:
    """Loopback cohort with simulated wire latency, so the acceptance
    measures PIPELINE STRUCTURE (parallel fetch + fetch/compute
    overlap), not loopback noise: a fixed RTT per shard frame request,
    throughput-shaped delays on the whole-file exports, and a cold
    sidecar response delay. Both paths pay the same per-byte prices —
    the streaming win comes from overlap, fewer bytes, and completion
    order, exactly the tentpole claim."""

    def __init__(self, inner):
        self._inner = inner
        self.frame_delay = 0.2
        self.line_delay = 0.1
        self.line_every = 20
        self.sidecar_delay = 0.5

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def stream_carrying_frame(self, *args, **kwargs):
        time.sleep(self.frame_delay)  # per-shard RTT
        return self._inner.stream_carrying_frame(*args, **kwargs)

    def export_lines(self, name):
        lines = self._inner.export_lines(name)

        def gen():
            for i, line in enumerate(lines):
                if i % self.line_every == 0:
                    time.sleep(self.line_delay)
                yield line

        return gen()

    def ensure_sidecar(self):
        time.sleep(self.sidecar_delay)
        return self._inner.ensure_sidecar()


class TestColdAcceptance:
    """The loopback cold acceptance (ISSUE 9): with an empty
    --cache-dir, streaming cold ingest beats the phased cold path by
    >= 2x wall time, the first gramian.accumulate span begins before
    the last ingest.fetch span ends, and G is bit-identical between the
    two paths."""

    def test_streaming_beats_phased_and_overlaps_device(
        self, cohort_dir, tmp_path
    ):
        from spark_examples_tpu.obs.session import TelemetrySession

        # Warm the accumulate executables on the run's exact shapes: the
        # acceptance measures INGEST structure, and a first-call XLA
        # compile inside the timed window would both skew the ratio and
        # push the first accumulate dispatch past the fetch tail.
        _gramian(JsonlSource(cohort_dir))
        server = GenomicsServiceServer(
            _SlowCohort(JsonlSource(cohort_dir))
        ).start()
        trace = str(tmp_path / "cold.trace.json")
        try:
            url = f"http://127.0.0.1:{server.port}"

            def timed(cold_stream, tag, trace_out=None):
                cache = str(tmp_path / f"cache-{tag}")
                shutil.rmtree(cache, ignore_errors=True)  # EMPTY cache
                src = HttpVariantSource(
                    url, cache_dir=cache, cold_stream=cold_stream
                )
                t0 = time.perf_counter()
                if trace_out is None:
                    g = _gramian(src, ingest_workers=4)
                else:
                    with TelemetrySession(trace_out=trace_out):
                        g = _gramian(src, ingest_workers=4)
                return time.perf_counter() - t0, g

            t_stream, g_stream = timed(True, "stream", trace_out=trace)
            t_phased, g_phased = timed(False, "phased")
            assert np.array_equal(g_stream, g_phased)
            ratio = t_phased / t_stream
            assert ratio >= 2.0, (
                f"streaming cold ingest only {ratio:.2f}x faster than "
                f"phased ({t_stream:.2f}s vs {t_phased:.2f}s)"
            )
            # Span-overlap criterion: the device accumulator started
            # while later shards were still inside their fetch spans.
            events = json.load(open(trace))["traceEvents"]
            fetch = [
                e
                for e in events
                if e.get("name") == "ingest.fetch" and e.get("ph") == "X"
            ]
            acc = [
                e
                for e in events
                if e.get("name") == "gramian.accumulate"
                and e.get("ph") == "X"
            ]
            assert fetch and acc
            first_acc = min(e["ts"] for e in acc)
            last_fetch_end = max(e["ts"] + e["dur"] for e in fetch)
            assert first_acc < last_fetch_end, (
                "first gramian.accumulate began only after the last "
                "ingest.fetch ended — no fetch/compute overlap"
            )
            validate = _load_validate_trace()
            assert validate.validate_trace(trace) == []
        finally:
            server.stop()
