"""The flow-sensitive graftlint layer (PR 7): CFG construction,
reaching-locks dataflow, the GL007/GL008/GL009 rules beyond their
golden fixtures, and the docs/CONCURRENCY.md lock-hierarchy drift gate.

The golden fixtures in tests/test_graftlint.py prove each rule's
headline behavior; this file drills the ENGINE — the CFG shapes
(try/finally, early return, nested with, loops, with-unwind on
exceptions) whose mis-modeling would make every rule silently wrong
in exactly the code most worth checking.
"""

import ast
import json
import os
import re
import textwrap

from tools.graftlint.cfg import build_cfg
from tools.graftlint.dataflow import (
    held_at_nodes,
    is_lock_name,
    make_resolver,
    node_scan_roots,
)
from tools.graftlint.engine import Project, load_config, run_lint
from tools.graftlint.rules.deadlock_order import lock_graph

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fn(src: str) -> ast.AST:
    tree = ast.parse(textwrap.dedent(src))
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            return node
    raise AssertionError("no function in snippet")


def _held_at_line(src: str, lineno: int, must=True, seed=frozenset()):
    """Locks held entering the statement at ``lineno`` (1-based within
    the dedented snippet)."""
    fn = _fn(src)
    resolve = make_resolver("C", "mod")
    cfg = build_cfg(fn, resolve)
    states = held_at_nodes(cfg, resolve, seed=seed, must=must)
    hits = []
    for node, held in states.items():
        if node.kind == "stmt" and node.line == lineno:
            hits.append(held)
    assert hits, f"no stmt node at line {lineno}"
    # Several nodes can share a line (e.g. compound headers); for these
    # tests the meet over them is the honest answer.
    out = hits[0]
    for h in hits[1:]:
        out = out & h if must else out | h
    return out


class TestLockNames:
    def test_word_matching_not_substring(self):
        assert is_lock_name("_lock")
        assert is_lock_name("_flush_lock")
        assert is_lock_name("_cv")
        assert is_lock_name("device_lock")
        assert not is_lock_name("blocks")  # 'lock' as substring only
        assert not is_lock_name("_by_key")
        assert not is_lock_name("clockwise")


class TestDataflow:
    def test_with_block_holds_and_releases(self):
        src = """
        def f(self):
            a = 1
            with self._lock:
                b = 2
            c = 3
        """
        assert _held_at_line(src, 3) == frozenset()  # a = 1
        assert _held_at_line(src, 5) == {"C._lock"}  # b = 2
        assert _held_at_line(src, 6) == frozenset()  # c = 3

    def test_branch_join_is_intersection(self):
        src = """
        def f(self, flag):
            if flag:
                self._lock.acquire()
            touch()
        """
        assert _held_at_line(src, 5) == frozenset()  # touch()

    def test_branch_join_is_union_in_may_mode(self):
        src = """
        def f(self, flag):
            if flag:
                self._lock.acquire()
            touch()
        """
        assert _held_at_line(src, 5, must=False) == {"C._lock"}

    def test_bounded_acquire_try_finally(self):
        """The serving/jobs.py journal-flush shape: the lock is held
        inside the try and provably released after the finally."""
        src = """
        def f(self):
            if not self._lock.acquire(timeout=2.0):
                return
            try:
                work()
            finally:
                self._lock.release()
            after()
        """
        assert _held_at_line(src, 6) == {"C._lock"}  # work()
        assert _held_at_line(src, 9) == frozenset()  # after()

    def test_exception_into_handler_unwinds_the_with(self):
        """A raise inside `with lock:` reaches the handler AFTER the
        lock is released — the handler must not believe it is held."""
        src = """
        def f(self):
            try:
                with self._lock:
                    work()
            except ValueError:
                cleanup()
            done()
        """
        assert _held_at_line(src, 5) == {"C._lock"}  # work()
        assert _held_at_line(src, 7) == frozenset()  # cleanup()
        assert _held_at_line(src, 8) == frozenset()  # done()

    def test_nested_with_stacks(self):
        src = """
        def f(self):
            with self._a_lock:
                with self._b_lock:
                    work()
                mid()
            out()
        """
        assert _held_at_line(src, 5) == {"C._a_lock", "C._b_lock"}
        assert _held_at_line(src, 6) == {"C._a_lock"}  # mid()
        assert _held_at_line(src, 7) == frozenset()  # out()

    def test_loop_back_edge_and_break(self):
        """Lock taken per-iteration: not held at the loop head meet,
        nor after a break that exits from inside the with."""
        src = """
        def f(self, items):
            for it in items:
                with self._lock:
                    if bad(it):
                        break
                    work(it)
            after()
        """
        assert _held_at_line(src, 5) == {"C._lock"}  # if bad(it)
        assert _held_at_line(src, 7) == {"C._lock"}  # work(it)
        assert _held_at_line(src, 8) == frozenset()  # after()

    def test_early_return_unreachable_tail(self):
        src = """
        def f(self):
            with self._lock:
                return 1
            tail()
        """
        fn = _fn(src)
        resolve = make_resolver("C", "mod")
        cfg = build_cfg(fn, resolve)
        states = held_at_nodes(cfg, resolve)
        lines = {
            n.line for n in states if n.kind == "stmt" and n.line
        }
        assert 4 in lines  # the return is reachable
        assert 5 not in lines  # tail() is unreachable, never analyzed

    def test_return_keeps_enclosing_with_lock_through_finally(self):
        """A return inside try/finally INSIDE a `with`: the runtime
        still holds the lock while the finally body runs (`__exit__`
        fires after) — the model must agree, or guarded cleanup in a
        finally gets falsely flagged."""
        src = """
        def f(self):
            with self._lock:
                try:
                    return work()
                finally:
                    cleanup()
        """
        assert _held_at_line(src, 7) == {"C._lock"}  # cleanup()

    def test_return_releases_with_entered_inside_try(self):
        """The converse: the `with` sits INSIDE the try, so its lock is
        released before the finally body runs."""
        src = """
        def f(self):
            try:
                with self._lock:
                    return work()
            finally:
                cleanup()
        """
        assert _held_at_line(src, 7) == frozenset()  # cleanup()

    def test_seed_models_the_locked_convention(self):
        src = """
        def _drain_locked(self):
            touch()
        """
        assert _held_at_line(
            src, 3, seed=frozenset({"C._lock"})
        ) == {"C._lock"}

    def test_compound_headers_scan_only_their_own_exprs(self):
        """An acquire inside an if BODY must not leak into the test
        node's transfer — the header owns only its own expressions."""
        src = """
        def f(self, flag):
            if flag:
                self._lock.acquire()
                inside()
            touch()
        """
        fn = _fn(src)
        resolve = make_resolver("C", "mod")
        cfg = build_cfg(fn, resolve)
        states = held_at_nodes(cfg, resolve)
        for node in cfg.nodes:
            if node.kind == "stmt" and node.line == 3:  # the if header
                roots = node_scan_roots(node)
                assert len(roots) == 1 and not isinstance(
                    roots[0], ast.If
                )
        assert _held_at_line(src, 5) == {"C._lock"}  # inside()
        assert _held_at_line(src, 6) == frozenset()  # touch(): join


def _mini(tmp_path, rule_name, files):
    """One-rule project over inline sources (mirrors test_graftlint's
    golden-fixture harness, but for generated cases)."""
    from tools.graftlint.rules import ALL_RULES

    lines = ["[tool.graftlint]", "exclude = []"]
    for r in ALL_RULES:
        lines.append(f'[tool.graftlint.rules."{r.name}"]')
        lines.append(
            f"enabled = {'true' if r.name == rule_name else 'false'}"
        )
        if r.name == rule_name:
            lines.append('paths = ["."]')
    (tmp_path / "pyproject.toml").write_text("\n".join(lines) + "\n")
    for name, src in files.items():
        (tmp_path / name).write_text(textwrap.dedent(src))
    return str(tmp_path)


class TestLockDisciplineRule:
    def test_interprocedural_edge_case_cross_object(self, tmp_path):
        root = _mini(
            tmp_path,
            "lock-discipline",
            {
                "m.py": """
                import threading

                class Q:
                    def __init__(self):
                        self._cv = threading.Condition()

                    def _push_locked(self, item):
                        pass

                    def push(self, item):
                        with self._cv:
                            self._push_locked(item)

                class T:
                    def __init__(self):
                        self._q = Q()

                    def leak(self, item):
                        self._q._push_locked(item)
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 1
        assert "another object's *_locked" in findings[0].message

    def test_release_outside_finally_is_flagged(self, tmp_path):
        root = _mini(
            tmp_path,
            "lock-discipline",
            {
                "m.py": """
                import threading

                _io_lock = threading.Lock()

                def risky():
                    _io_lock.acquire()
                    work()
                    _io_lock.release()
                """
            },
        )
        findings, _ = run_lint(root, [])
        msgs = "\n".join(f.message for f in findings)
        assert "without a matching release() in a finally" in msgs
        assert "outside a finally" in msgs


class TestDeadlockOrderRule:
    def test_interprocedural_cycle_through_typed_attr(self, tmp_path):
        """A cycle only visible through a call: T holds T._lock and
        calls J.append (which takes J._lock); J.flush holds J._lock
        and calls back into a T method that takes T._lock."""
        root = _mini(
            tmp_path,
            "deadlock-order",
            {
                "m.py": """
                import threading

                class J:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._t = T()  # forward ref: index is whole-scope

                    def append(self, e):
                        with self._lock:
                            return e

                    def flush(self):
                        with self._lock:
                            self._t.note()

                class T:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._j = J()

                    def note(self):
                        with self._lock:
                            pass

                    def submit(self, e):
                        with self._lock:
                            self._j.append(e)
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert findings, "interprocedural ABBA cycle missed"
        assert all("lock-order cycle" in f.message for f in findings)

    def test_one_way_nesting_is_clean(self, tmp_path):
        root = _mini(
            tmp_path,
            "deadlock-order",
            {
                "m.py": """
                import threading

                class J:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def append(self, e):
                        with self._lock:
                            return e

                class T:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._j = J()

                    def submit(self, e):
                        with self._lock:
                            return self._j.append(e)
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert findings == []

    def test_lock_graph_shape(self, tmp_path):
        root = _mini(
            tmp_path,
            "deadlock-order",
            {
                "m.py": """
                import threading

                _a_lock = threading.Lock()
                _b_lock = threading.Lock()

                def f():
                    with _a_lock:
                        with _b_lock:
                            pass
                """
            },
        )
        graph = lock_graph(Project(root, load_config(root)))
        assert graph["edges"] == [["m._a_lock", "m._b_lock"]]
        assert set(graph["locks"]) == {"m._a_lock", "m._b_lock"}


class TestGuardedFieldsRule:
    def test_flow_sensitive_not_method_granular(self, tmp_path):
        """The SAME method both reads guarded and (later, after the
        with block) reads unguarded — only the second line fires."""
        root = _mini(
            tmp_path,
            "guarded-fields",
            {
                "m.py": """
                import threading

                class C:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._n = 0

                    def bump(self):
                        with self._lock:
                            self._n += 1
                        return self._n
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert len(findings) == 1
        assert findings[0].line == 12  # the post-with read, only
        assert "unguarded read" in findings[0].message

    def test_internally_synchronized_attr_exempt(self, tmp_path):
        root = _mini(
            tmp_path,
            "guarded-fields",
            {
                "m.py": """
                import threading

                class Q:
                    def __init__(self):
                        self._cv = threading.Condition()
                        self._items = []

                    def pop(self):
                        with self._cv:
                            return self._items.pop()

                class T:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._q = Q()
                        self._n = 0

                    def use(self):
                        with self._lock:
                            self._q = self._q  # guarded write of _q
                            self._n += 1

                    def fast(self):
                        return self._q.pop()  # Q locks itself: exempt
                """
            },
        )
        findings, _ = run_lint(root, [])
        assert findings == []


class TestLockHierarchyDrift:
    """docs/CONCURRENCY.md embeds the GL008-derived hierarchy as JSON;
    the doc and the derivation must never disagree."""

    def _doc_graph(self):
        path = os.path.join(REPO_ROOT, "docs", "CONCURRENCY.md")
        with open(path, encoding="utf-8") as f:
            text = f.read()
        m = re.search(r"```json\n(.*?)```", text, re.S)
        assert m, "docs/CONCURRENCY.md lost its lock-graph JSON block"
        return json.loads(m.group(1))

    def test_documented_hierarchy_matches_derivation(self):
        derived = lock_graph(
            Project(REPO_ROOT, load_config(REPO_ROOT))
        )
        assert self._doc_graph() == derived, (
            "docs/CONCURRENCY.md and the GL008 derivation diverged — "
            "re-run `python -m tools.graftlint --lock-graph` and "
            "update the doc in the same PR"
        )

    def test_derived_graph_is_acyclic_on_the_real_tree(self):
        from tools.graftlint.rules.deadlock_order import RULE

        project = Project(REPO_ROOT, load_config(REPO_ROOT))
        assert list(RULE.check(project)) == []
