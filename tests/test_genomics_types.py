"""Unit tests for the messy-bit semantics (SURVEY.md §7 hard-parts #4)."""


from spark_examples_tpu.genomics import (
    Call,
    Read,
    Variant,
    normalize_contig,
    has_variation,
    murmur3_x64_128,
    variant_identity,
)
from spark_examples_tpu.genomics.shards import (
    SexChromosomeFilter,
    manifest_digest,
    parse_references,
    shards_for_all_references,
    shards_for_references,
    HUMAN_CHROMOSOMES,
)


class TestContigNormalization:
    """VariantsRDD.scala:103-110 — regex ([a-z]*)?([0-9]*), full match."""

    def test_chr_prefix_stripped(self):
        assert normalize_contig("chr17") == "17"

    def test_bare_numeric_kept(self):
        assert normalize_contig("17") == "17"

    def test_uppercase_x_dropped(self):
        assert normalize_contig("chrX") is None
        assert normalize_contig("chrY") is None
        assert normalize_contig("chrM") is None

    def test_alt_contigs_dropped(self):
        assert normalize_contig("HLA-DRB1*15:01:01:01") is None
        assert normalize_contig("GL000207.1") is None
        assert normalize_contig("chr17_ctg5_hap1") is None

    def test_builder_drops_bad_contig(self):
        assert Variant.build("chrUn_gl000211", 5, 6, "A") is None
        v = Variant.build("chr13", 5, 6, "A")
        assert v is not None and v.contig == "13"


class TestHasVariation:
    def test_hom_ref_false(self):
        assert not has_variation(Call("c", "n", (0, 0)))

    def test_het_true(self):
        assert has_variation(Call("c", "n", (0, 1)))

    def test_no_call_false(self):
        assert not has_variation(Call("c", "n", (-1, -1)))

    def test_empty_genotype_false(self):
        assert not has_variation(Call("c", "n", ()))


class TestMurmur3:
    def test_known_vectors(self):
        # Public MurmurHash3 x64-128 test vectors (smhasher / guava).
        assert murmur3_x64_128(b"").hex() == "00000000000000000000000000000000"
        # Self-consistency: same input → same output, distinct inputs differ.
        a = murmur3_x64_128(b"The quick brown fox")
        b = murmur3_x64_128(b"The quick brown fox.")
        assert a != b and len(a) == 16

    def test_block_boundaries(self):
        # Exercise tail lengths 0..16 around the 16-byte block edge.
        seen = set()
        for n in range(33):
            seen.add(murmur3_x64_128(bytes(range(n))))
        assert len(seen) == 33

    def test_variant_identity_fields_matter(self):
        base = variant_identity("17", 100, 101, "A", ("G",))
        assert variant_identity("17", 100, 101, "A", ("T",)) != base
        assert variant_identity("17", 101, 102, "A", ("G",)) != base
        assert variant_identity("13", 100, 101, "A", ("G",)) != base
        # None handling: null referenceBases → "" (VariantsPca.scala:66).
        assert variant_identity("17", 100, 101, None, None) == variant_identity(
            "17", 100, 101, "", ()
        )


class TestShards:
    def test_parse_references(self):
        assert parse_references("17:41196311:41277499,13:1:10") == [
            ("17", 41196311, 41277499),
            ("13", 1, 10),
        ]

    def test_fixed_windows(self):
        shards = shards_for_references("1:0:2500000", 1_000_000)
        assert [(s.start, s.end) for s in shards] == [
            (0, 1000000),
            (1000000, 2000000),
            (2000000, 2500000),
        ]

    def test_all_references_excludes_xy_for_variants(self):
        shards = shards_for_all_references(SexChromosomeFilter.EXCLUDE_XY)
        contigs = {s.contig for s in shards}
        assert "X" not in contigs and "Y" not in contigs
        assert contigs == {str(i) for i in range(1, 23)}

    def test_all_references_includes_xy_for_reads(self):
        contigs = {
            s.contig
            for s in shards_for_all_references(SexChromosomeFilter.INCLUDE_XY)
        }
        assert "X" in contigs and "Y" in contigs

    def test_total_coverage(self):
        shards = shards_for_all_references(SexChromosomeFilter.INCLUDE_XY)
        total = sum(s.range for s in shards)
        assert total == sum(HUMAN_CHROMOSOMES.values())

    def test_manifest_digest_stable(self):
        a = shards_for_references("17:0:5000000")
        b = shards_for_references("17:0:5000000")
        assert manifest_digest(a) == manifest_digest(b)
        assert manifest_digest(a) != manifest_digest(a[:-1])


class TestReadBuild:
    def test_cigar_assembly(self):
        r = Read.build(
            "21",
            1000,
            "ACGT",
            cigar_ops=[("CLIP_SOFT", 2), ("ALIGNMENT_MATCH", 98), ("SKIP", 5)],
        )
        assert r.cigar == "2S98M5N"
        assert r.key() == ("21", 1000)


class TestBatchIdentities:
    def test_batch_matches_single(self):
        from spark_examples_tpu.genomics.hashing import (
            variant_identities,
            variant_identity,
        )
        from spark_examples_tpu.genomics.types import Variant

        vs = [
            Variant.build("chr17", 100 + i, 101 + i, "ACGT"[i % 4],
                          alternate_bases=["T", "G"][: 1 + i % 2])
            for i in range(20)
        ]
        batch = variant_identities(vs)
        singles = [
            variant_identity(v.contig, v.start, v.end,
                             v.reference_bases, v.alternate_bases)
            for v in vs
        ]
        assert batch == singles

    def test_batch_matches_fallback(self, monkeypatch):
        import spark_examples_tpu.genomics.hashing as H
        from spark_examples_tpu.genomics.types import Variant

        vs = [Variant.build("13", i, i + 1, "A") for i in range(7)]
        native = H.variant_identities(vs)
        monkeypatch.setattr(H, "_native_lib", None)
        fallback = H.variant_identities(vs)
        assert native == fallback
