"""Gramian-free sketch PCA suite (``--pca-mode sketch``).

Pins the fourth PCA engine end to end: tolerance-pinned spectrum
goldens vs the exact eigendecomposition at small N across mesh shapes
(1×1, 2×1, 2×2), shuffled window orders, and density edge cases;
seeded-Ω reproducibility (bit-identical per seed, tolerance-equal
across seeds); the O(N·(k+p)) footprint bound that replaces the N²
tile; the PCA_MODES registry/flag/error-message three-way sync; the
serving JobSpec surface (sketch keys join every sketch knob, exact
keys stay historical); the telemetry closed sets in BOTH rejection
directions; and the 2-process pod-sim protocol leg.
"""

import argparse
import json
import os
import sys
import textwrap

import numpy as np
import pytest

from spark_examples_tpu.arrays.blocks import csr_windows
from spark_examples_tpu.genomics.fixtures import (
    DEFAULT_VARIANT_SET_ID,
    synthetic_cohort,
)
from spark_examples_tpu.models.pca import (
    PCA_MODES as DRIVER_PCA_MODES,
    VariantsPcaDriver,
)
from spark_examples_tpu.ops.pcoa import (
    normalize_eigvec_signs,
    randomized_panel_width,
)
from spark_examples_tpu.ops.sketch import (
    SKETCH_FULLRANK_ATOL,
    SKETCH_FULLRANK_RTOL,
    SKETCH_TOPK_ATOL,
    SKETCH_TOPK_RTOL,
    gaussian_test_matrix,
    sketch_eig,
    sketch_host_bytes,
    sketch_panel_blockwise,
)
from spark_examples_tpu.parallel.mesh import make_mesh
from spark_examples_tpu.parallel.sharded import sharded_sketch_panel
from spark_examples_tpu.serving.jobs import (
    JobSpec,
    cohort_key,
    job_config,
    resolve_spec,
)
from spark_examples_tpu.utils.config import (
    PCA_MODES,
    PcaConfig,
    add_pca_flags,
)

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(__file__)), "scripts"),
)
import validate_trace as validate  # noqa: E402

import jax  # noqa: E402  (after conftest has pinned the platform)

MESH_SPECS = tuple(
    spec
    for spec, need in (
        ("data:1", 1),
        ("data:2", 2),
        ("data:2,model:2", 4),
    )
    if need <= jax.device_count()
)

K = 2
N, V = 36, 240


def structured_csr(n=N, v=V, seed=0, pops=3):
    """Dense X + CSR twin with ``pops`` well-separated populations:
    population-aligned common variants give the centered Gramian a
    clean top-(pops−1) spectrum (gap far from the 0.95 warning bar) —
    the regime the sketch tolerance contract is pinned in."""
    rng = np.random.default_rng(seed)
    labels = np.arange(n) % pops
    x = np.zeros((n, v), np.int8)
    for j in range(v):
        p_carry = np.where(labels == (j % pops), 0.85, 0.05)
        x[:, j] = rng.random(n) < p_carry
    cols, rows = np.nonzero(x.T)
    lens = np.bincount(cols, minlength=v)
    offsets = np.zeros(v + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    return x, (rows.astype(np.int64), offsets)


def exact_eig(x, k=K):
    """The exact reference surface: sign-normalized unit eigenvectors
    and eigenvalues of the centered Gramian C = H·XXᵀ·H in f64."""
    xf = np.asarray(x, np.float64)
    g = xf @ xf.T
    n = g.shape[0]
    h = np.eye(n) - 1.0 / n
    c = h @ g @ h
    w, u = np.linalg.eigh(c)
    order = np.argsort(w)[::-1][:k]
    return normalize_eigvec_signs(u[:, order]), w[order]


def _panel(pair, n=N, k=K, mesh=None, power_iters=2, block=32, **kw):
    factory = lambda: csr_windows(iter([pair]), block)  # noqa: E731
    if mesh is not None:
        return sharded_sketch_panel(
            factory,
            n,
            k,
            mesh,
            power_iters=power_iters,
            block_variants=block,
            **kw,
        )
    return sketch_panel_blockwise(
        factory,
        n,
        k,
        power_iters=power_iters,
        block_variants=block,
        **kw,
    )


def assert_spectrum(coords, vals, ref_coords, ref_vals, rtol, atol):
    np.testing.assert_allclose(vals, ref_vals, rtol=rtol)
    assert np.abs(coords - ref_coords).max() <= atol


class TestSpectrumGoldens:
    """Tolerance-pinned goldens vs the exact path at small N — the
    module-docstring contract (full-rank and top-k regimes)."""

    def test_fixture_has_a_clean_gap(self):
        # The tolerance contract only holds past a clear spectral gap;
        # pin the fixture itself so a regression in it can't silently
        # relax every golden below.
        x, _ = structured_csr()
        _, vals = exact_eig(x, k=K + 1)
        assert vals[K] / vals[K - 1] < 0.5

    def test_meshless_topk_matches_exact(self):
        x, pair = structured_csr()
        coords, vals = sketch_eig(_panel(pair), K)
        ref_c, ref_v = exact_eig(x)
        assert_spectrum(
            coords, vals, ref_c, ref_v, SKETCH_TOPK_RTOL, SKETCH_TOPK_ATOL
        )

    @pytest.mark.parametrize("spec", MESH_SPECS)
    def test_mesh_topk_matches_exact(self, spec):
        x, pair = structured_csr()
        mesh = make_mesh(spec)
        coords, vals = sketch_eig(_panel(pair, mesh=mesh), K)
        ref_c, ref_v = exact_eig(x)
        assert_spectrum(
            coords, vals, ref_c, ref_v, SKETCH_TOPK_RTOL, SKETCH_TOPK_ATOL
        )

    def test_full_rank_matches_exact_tightly(self):
        # l = n: the Nyström reconstruction is exact up to roundoff.
        x, pair = structured_csr()
        panel = _panel(pair, power_iters=0, oversample=N)
        assert panel.l == N
        coords, vals = sketch_eig(panel, K)
        ref_c, ref_v = exact_eig(x)
        assert_spectrum(
            coords,
            vals,
            ref_c,
            ref_v,
            SKETCH_FULLRANK_RTOL,
            SKETCH_FULLRANK_ATOL,
        )

    def test_shuffled_window_order_within_tolerance(self):
        # The accumulation is a sum over windows — arrival order can
        # only move f32 roundoff, never the result.
        x, pair = structured_csr()
        windows = list(csr_windows(iter([pair]), 16))
        shuffled = [
            windows[i]
            for i in np.random.default_rng(7).permutation(len(windows))
        ]
        a, av = sketch_eig(
            sketch_panel_blockwise(
                lambda: iter(windows), N, K, power_iters=2
            ),
            K,
        )
        b, bv = sketch_eig(
            sketch_panel_blockwise(
                lambda: iter(shuffled), N, K, power_iters=2
            ),
            K,
        )
        assert np.abs(a - b).max() <= 1e-4
        np.testing.assert_allclose(av, bv, rtol=1e-5)
        ref_c, ref_v = exact_eig(x)
        assert_spectrum(
            a, av, ref_c, ref_v, SKETCH_TOPK_RTOL, SKETCH_TOPK_ATOL
        )

    def test_density_edge_windows_and_route_mix(self):
        # All-zero window, single-nnz window, and an all-carrier dense
        # window: both kernel routes feed one panel, and the route
        # counter records the split.
        from spark_examples_tpu import obs

        # n = 64 keeps the single-carrier window under BOTH scatter
        # gates (mean density and max per-variant fraction: 1/64 <
        # 0.02) while the all-carrier window routes dense.
        n = 64
        # Window C mixes a half-carrier variant (keeps the centered
        # Gramian rank 2 — all-carrier columns center away to zero)
        # with two all-carrier ones; its max carrier fraction routes
        # it dense either way.
        windows = [
            (np.empty(0, np.int64), np.zeros(3, np.int64)),
            (np.array([5], np.int64), np.array([1], np.int64)),
            (
                np.concatenate(
                    [
                        np.arange(32, dtype=np.int64),
                        np.arange(n, dtype=np.int64),
                        np.arange(n, dtype=np.int64),
                    ]
                ),
                np.array([32, n, n], np.int64),
            ),
        ]
        x = np.zeros((n, 7), np.int8)
        x[5, 3] = 1
        x[:32, 4] = 1
        x[:, 5] = 1
        x[:, 6] = 1
        counter = obs.get_registry().counter(
            "sketch_windows_total",
            "CSR windows applied to the randomized sketch panel",
        )
        before = {
            r: counter.labels(route=r).value for r in ("scatter", "dense")
        }
        panel = sketch_panel_blockwise(
            lambda: iter(windows), n, K, power_iters=0
        )
        after = {
            r: counter.labels(route=r).value for r in ("scatter", "dense")
        }
        assert after["scatter"] - before["scatter"] == 2
        assert after["dense"] - before["dense"] == 1
        coords, vals = sketch_eig(panel, K)
        ref_c, ref_v = exact_eig(x)
        # The centered signal is rank 2 and l = 2+8 = 10 covers it
        # completely: the top-k contract applies without power
        # iterations.
        np.testing.assert_allclose(vals, ref_v, rtol=SKETCH_TOPK_RTOL)
        assert np.abs(coords - ref_c).max() <= SKETCH_TOPK_ATOL
        np.testing.assert_array_equal(
            panel.row_sums, (x.astype(np.float64) @ x.sum(0)).ravel()
        )

    def test_all_zero_cohort_yields_zero_coords(self):
        windows = [(np.empty(0, np.int64), np.zeros(4, np.int64))]
        panel = sketch_panel_blockwise(
            lambda: iter(windows), 9, K, power_iters=0
        )
        coords, vals = sketch_eig(panel, K)
        np.testing.assert_array_equal(coords, np.zeros((9, K)))
        np.testing.assert_array_equal(vals, np.zeros(K))


class TestReproducibility:
    """Seeded-Ω contract: same seed → bit-identical; different seeds →
    different panels that agree within the tolerance bars."""

    def test_same_seed_bit_identical(self):
        _, pair = structured_csr()
        a, av = sketch_eig(_panel(pair, seed=3), K)
        b, bv = sketch_eig(_panel(pair, seed=3), K)
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(av, bv)

    def test_omega_is_seed_deterministic(self):
        np.testing.assert_array_equal(
            gaussian_test_matrix(20, 5, 11), gaussian_test_matrix(20, 5, 11)
        )
        assert (
            np.abs(
                gaussian_test_matrix(20, 5, 11)
                - gaussian_test_matrix(20, 5, 12)
            ).max()
            > 0
        )

    @pytest.mark.skipif(
        jax.device_count() < 2, reason="needs >= 2 devices"
    )
    def test_mesh_matches_meshless_same_seed(self):
        _, pair = structured_csr()
        a, av = sketch_eig(_panel(pair, seed=1), K)
        b, bv = sketch_eig(
            _panel(pair, mesh=make_mesh("data:2"), seed=1), K
        )
        assert np.abs(a - b).max() <= 1e-4
        np.testing.assert_allclose(av, bv, rtol=1e-5)

    def test_different_seeds_differ_within_bars(self):
        x, pair = structured_csr()
        a, av = sketch_eig(_panel(pair, seed=0), K)
        b, bv = sketch_eig(_panel(pair, seed=1), K)
        assert np.abs(a - b).max() > 0  # reproducible, NOT identical
        ref_c, ref_v = exact_eig(x)
        for coords, vals in ((a, av), (b, bv)):
            assert_spectrum(
                coords,
                vals,
                ref_c,
                ref_v,
                SKETCH_TOPK_RTOL,
                SKETCH_TOPK_ATOL,
            )


class TestFootprintBound:
    """The whole point of the engine: O(N·(k+p)) host bytes, never N²."""

    def test_bound_is_linear_not_quadratic(self):
        n = 1 << 20
        l = randomized_panel_width(n, 10)
        assert sketch_host_bytes(n, l) < (4 * n * n) // 1000
        assert sketch_host_bytes(2 * n, l) == pytest.approx(
            2 * sketch_host_bytes(n, l), rel=1e-6
        )

    def test_panel_arrays_within_documented_bound(self):
        _, pair = structured_csr()
        panel = _panel(pair)
        assert panel.host_peak_bytes == sketch_host_bytes(N, panel.l)
        assert panel.y.nbytes + panel.omega.nbytes <= panel.host_peak_bytes
        assert panel.y.shape == (N, panel.l)

    @pytest.mark.skipif(
        jax.device_count() < 2, reason="needs >= 2 devices"
    )
    def test_mesh_panel_bound_covers_padded_rows(self):
        _, pair = structured_csr()
        panel = _panel(pair, mesh=make_mesh("data:2"))
        n_padded = panel.y.shape[0]
        assert n_padded >= N
        assert panel.host_peak_bytes == sketch_host_bytes(
            n_padded, panel.l
        )
        # Padding rows carry no signal (C's padded block is zero).
        np.testing.assert_array_equal(panel.y[N:], 0.0)


class TestPcaModesRegistry:
    """Satellite: the ONE mode registry — argparse choices, driver
    validation message, and serving validation can never drift."""

    def test_registry_contents(self):
        assert PCA_MODES == ("auto", "fused", "stream", "sparse", "sketch")

    def test_models_reexports_the_same_registry(self):
        assert DRIVER_PCA_MODES is PCA_MODES

    def test_driver_error_lists_every_registered_mode(self):
        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID], pca_mode="bogus"
        )
        src = synthetic_cohort(6, 12)
        with pytest.raises(ValueError) as err:
            VariantsPcaDriver(conf, src)
        for mode in PCA_MODES:
            assert repr(mode) in str(err.value)
        assert "'bogus'" in str(err.value)

    def test_cli_choices_are_the_registry(self):
        p = argparse.ArgumentParser()
        add_pca_flags(p)
        actions = {a.option_strings[0]: a for a in p._actions if a.option_strings}
        assert tuple(actions["--pca-mode"].choices) == PCA_MODES
        assert actions["--sketch-oversample"].default == (
            PcaConfig.sketch_oversample
        )
        assert actions["--sketch-seed"].default == PcaConfig.sketch_seed
        assert actions["--sketch-power-iters"].default == (
            PcaConfig.sketch_power_iters
        )

    def test_serving_validates_against_the_registry(self):
        with pytest.raises(ValueError, match="unknown pca_mode"):
            JobSpec.from_record({"pca_mode": "bogus"})
        for mode in PCA_MODES:
            assert JobSpec.from_record({"pca_mode": mode}).pca_mode == mode


class TestDriverSketchMode:
    def _driver(self, mode="sketch", mesh_spec=None, n=N, v=V, **kw):
        conf = PcaConfig(
            variant_set_ids=[DEFAULT_VARIANT_SET_ID],
            block_variants=64,
            pca_mode=mode,
            **kw,
        )
        mesh = make_mesh(mesh_spec) if mesh_spec else None
        source = synthetic_cohort(n, v, population_structure=3, seed=3)
        return VariantsPcaDriver(conf, source, mesh=mesh)

    def test_sketch_mode_matches_stream_coordinates(self):
        sketch = self._driver("sketch", sketch_power_iters=2).run()
        stream = self._driver("stream").run()
        a = np.array([r[1:] for r in sketch])
        b = np.array([r[1:] for r in stream])
        assert np.abs(a - b).max() <= SKETCH_TOPK_ATOL
        assert [r[0] for r in sketch] == [r[0] for r in stream]

    @pytest.mark.skipif(
        jax.device_count() < 4, reason="needs >= 4 devices"
    )
    def test_sketch_on_mesh_matches_meshless(self):
        a = np.array(
            [
                r[1:]
                for r in self._driver(
                    "sketch", "data:2,model:2", sketch_power_iters=2
                ).run()
            ]
        )
        b = np.array(
            [
                r[1:]
                for r in self._driver(
                    "sketch", sketch_power_iters=2
                ).run()
            ]
        )
        assert np.abs(a - b).max() <= 1e-4

    def test_nonzero_rows_parity_print_survives_without_g(self, capsys):
        self._driver("sketch").run()
        out_sketch = capsys.readouterr().out
        self._driver("stream").run()
        out_stream = capsys.readouterr().out
        line = [
            ln
            for ln in out_sketch.splitlines()
            if ln.startswith("Non zero rows in matrix:")
        ]
        assert line and line[0] in out_stream.splitlines()

    def test_sketch_selection(self, monkeypatch):
        assert self._driver("sketch").sketch_selected()  # forced
        assert not self._driver("stream").sketch_selected()
        # Auto stays exact at small N...
        auto = self._driver("auto")
        assert not auto.sketch_selected()
        # ...and flips to sketch exactly when the exact footprint bound
        # would refuse (the same 4 GiB line).
        monkeypatch.setattr(
            auto, "_sparse_host_g_bytes", lambda: (4 << 30) + 1
        )
        assert auto.sketch_selected()

    def test_sketch_rejects_checkpointing(self):
        with pytest.raises(ValueError, match="sketch"):
            self._driver("sketch", checkpoint_dir="/tmp/nope")

    def test_sketch_rejects_precise(self):
        with pytest.raises(ValueError, match="precise"):
            self._driver("sketch", precise=True)

    def test_bad_oversample_rejected(self):
        with pytest.raises(ValueError, match="sketch-oversample"):
            self._driver("sketch", sketch_oversample=0)

    def test_negative_power_iters_rejected(self):
        with pytest.raises(ValueError, match="sketch-power-iters"):
            self._driver("sketch", sketch_power_iters=-1)


class TestServingSketchSurface:
    """Key discipline: every exact engine is bit-identical, so exact
    keys never carry pca_mode (historical caches/journals keep their
    keys); a sketch job is a different artifact, so ALL its knobs join
    the key."""

    def _base(self, **kw):
        kw.setdefault("variant_set_ids", [DEFAULT_VARIANT_SET_ID])
        return PcaConfig(**kw)

    def test_exact_modes_share_historical_keys(self):
        base = self._base()
        plain = JobSpec.from_record({})
        assert cohort_key(plain, base) == cohort_key(
            JobSpec.from_record({"pca_mode": "sparse"}), base
        )
        assert "pca_mode" not in resolve_spec(plain, base)
        assert "sketch_seed" not in resolve_spec(plain, base)

    def test_sketch_key_is_distinct_and_seeded(self):
        base = self._base()
        sketch = JobSpec.from_record({"pca_mode": "sketch"})
        assert cohort_key(sketch, base) != cohort_key(
            JobSpec.from_record({}), base
        )
        resolved = resolve_spec(sketch, base)
        assert resolved["pca_mode"] == "sketch"
        assert resolved["sketch_oversample"] == base.sketch_oversample
        assert resolved["sketch_seed"] == base.sketch_seed
        assert resolved["sketch_power_iters"] == base.sketch_power_iters
        reseeded = self._base(sketch_seed=1)
        assert cohort_key(sketch, reseeded) != cohort_key(sketch, base)

    def test_spec_round_trip_and_journal_shape(self):
        spec = JobSpec.from_record({"pca_mode": "sketch"})
        rec = spec.to_record()
        assert rec["pca_mode"] == "sketch"
        assert JobSpec.from_record(rec) == spec
        # Pre-sketch journal records replay byte-identically: no key
        # appears on specs that never set one.
        assert "pca_mode" not in JobSpec.from_record({}).to_record()

    def test_pairhmm_rejects_pca_mode(self):
        with pytest.raises(ValueError, match="do not apply"):
            JobSpec.from_record(
                {"kind": "pairhmm", "pca_mode": "sketch"}
            )

    def test_job_config_strips_checkpoint_for_sketch(self):
        base = self._base(pca_mode="auto")
        conf = job_config(
            JobSpec.from_record({"pca_mode": "sketch"}),
            base,
            checkpoint_dir="/tmp/ckpt",
        )
        assert conf.pca_mode == "sketch"
        assert conf.checkpoint_dir is None
        exact = job_config(
            JobSpec.from_record({}), base, checkpoint_dir="/tmp/ckpt"
        )
        assert exact.checkpoint_dir == "/tmp/ckpt"

    def test_sketch_job_serves_end_to_end_and_never_gangs(self):
        from spark_examples_tpu.serving import (
            AnalysisEngine,
            AnalysisJobTier,
        )

        src = synthetic_cohort(12, 60, population_structure=3, seed=9)
        base = self._base(
            references="17:41196311:41277499",
            block_variants=16,
            sketch_power_iters=2,
        )
        tier = AnalysisJobTier(
            AnalysisEngine(src), base, workers=0, gang_max_samples=256
        )
        try:
            exact_job, _ = tier.submit(JobSpec.from_record({}))
            sketch_job, created = tier.submit(
                JobSpec.from_record({"pca_mode": "sketch"})
            )
            assert created and sketch_job.key != exact_job.key
            while tier.step(timeout=0.0):
                pass
            assert exact_job.state == "done", exact_job.error
            assert sketch_job.state == "done", sketch_job.error
            a = np.array([r[1:3] for r in exact_job.result], float)
            b = np.array([r[1:3] for r in sketch_job.result], float)
            assert np.abs(a - b).max() <= SKETCH_TOPK_ATOL
        finally:
            tier.close()


class TestSchemaDrift:
    """Both rejection directions for the sketch obs surface — the
    closed sets GL003 cross-checks statically."""

    def _trace(self, tmp_path, name):
        trace = tmp_path / "t.json"
        trace.write_text(
            json.dumps(
                {
                    "traceEvents": [
                        {
                            "ph": "X",
                            "name": name,
                            "pid": 1,
                            "ts": 0,
                            "dur": 1,
                        }
                    ]
                }
            )
        )
        return str(trace)

    @pytest.mark.parametrize(
        "name",
        [
            "gramian.sketch.accumulate",
            "gramian.sketch.window",
            "gramian.sketch.finish",
        ],
    )
    def test_sketch_spans_are_schema_known(self, tmp_path, name):
        assert validate.validate_trace(self._trace(tmp_path, name)) == []

    def test_unknown_sketch_span_rejected(self, tmp_path):
        errs = validate.validate_trace(
            self._trace(tmp_path, "gramian.sketch.carrier_sync")
        )
        assert errs and "gramian.sketch.carrier_sync" in errs[0]

    def test_windows_counter_requires_route_label(self, tmp_path):
        good = tmp_path / "good.prom"
        good.write_text('sketch_windows_total{route="scatter"} 3\n')
        assert validate.validate_metrics(str(good)) == []
        bad = tmp_path / "bad.prom"
        bad.write_text("sketch_windows_total 3\n")
        errs = validate.validate_metrics(str(bad))
        assert errs and "route" in errs[0]

    def test_schema_closed_set_is_the_emitted_set(self):
        assert validate._SKETCH_SPANS == {
            "gramian.sketch.accumulate",
            "gramian.sketch.window",
            "gramian.sketch.finish",
        }
        assert validate._LABELED_COUNTERS["sketch_windows_total"] == "route"

    def test_real_sketch_run_emits_schema_valid_artifacts(self, tmp_path):
        from spark_examples_tpu.obs import telemetry_session

        _, pair = structured_csr()
        trace = str(tmp_path / "sketch.trace.json")
        metrics = str(tmp_path / "sketch.prom")
        with telemetry_session(trace_out=trace, metrics_out=metrics):
            sketch_eig(_panel(pair, power_iters=1), K)
        assert validate.validate_trace(trace) == []
        assert validate.validate_metrics(metrics) == []
        evs = json.load(open(trace))["traceEvents"]
        emitted = {e.get("name") for e in evs if e.get("ph") == "X"}
        assert "gramian.sketch.accumulate" in emitted
        assert "gramian.sketch.window" in emitted
        assert "gramian.sketch.finish" in emitted


# ---------------------------------------------------------------- pod sim

import socket  # noqa: E402
import subprocess  # noqa: E402

pod_skip = pytest.mark.skipif(
    os.environ.get("SPARK_EXAMPLES_TPU_SKIP_MULTIHOST") == "1",
    reason="multihost tests disabled",
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_pod_workers(script_path, argv, n=2, timeout=300):
    port = _free_port()
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ),
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": str(n),
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "SPARK_EXAMPLES_TPU_COLLECTIVE_CHECK": "1",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script_path)] + [str(a) for a in argv],
            env={**env, "JAX_PROCESS_ID": str(i)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(n)
    ]
    try:
        logs = [p.communicate(timeout=timeout)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-3000:]
    return logs


_POD_SKETCH_WORKER = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh

    from spark_examples_tpu.parallel.distributed import initialize_from_env
    assert initialize_from_env()
    from spark_examples_tpu.arrays.blocks import csr_windows
    from spark_examples_tpu.ops.sketch import sketch_eig
    from spark_examples_tpu.parallel.sharded import sharded_sketch_panel
    from spark_examples_tpu import obs

    pid, world = jax.process_index(), jax.process_count()
    mesh = Mesh(np.array(jax.devices()).reshape(world, 2), ("data", "model"))

    # The SAME structured 3-population cohort the host test derives
    # (structured_csr(36, 240, seed=0, pops=3), bit for bit).
    n, v, pops = 36, 240, 3
    rng = np.random.default_rng(0)
    labels = np.arange(n) % pops
    x = np.zeros((n, v), np.int8)
    for j in range(v):
        p_carry = np.where(labels == (j % pops), 0.85, 0.05)
        x[:, j] = rng.random(n) < p_carry
    cols, rows = np.nonzero(x.T)
    lens = np.bincount(cols, minlength=v)
    offsets = np.zeros(v + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    pair = (rows.astype(np.int64), offsets)

    windows = list(csr_windows(iter([pair]), 32))
    mine = windows[pid::world]

    counter = obs.get_registry().counter(
        "sketch_windows_total",
        "CSR windows applied to the randomized sketch panel",
    )
    before = {
        r: counter.labels(route=r).value for r in ("scatter", "dense")
    }
    panel = sharded_sketch_panel(
        lambda: iter(mine), n, 2, mesh, power_iters=2, block_variants=32,
    )
    coords, vals = sketch_eig(panel, 2)
    after = {
        r: counter.labels(route=r).value for r in ("scatter", "dense")
    }
    if pid == 0:
        with open(sys.argv[1], "w") as f:
            json.dump(
                {
                    "coords": np.asarray(coords).tolist(),
                    "vals": np.asarray(vals).tolist(),
                    "row_sums": np.asarray(panel.row_sums).tolist(),
                    "n_padded": int(panel.y.shape[0]),
                    "host_peak_bytes": int(panel.host_peak_bytes),
                    "windows_delta": {
                        r: after[r] - before[r]
                        for r in ("scatter", "dense")
                    },
                    "my_windows": len(mine),
                },
                f,
            )
    """
)


@pod_skip
class TestPodSketchProtocol:
    """The sketch panel on a REAL 2-process ``jax.distributed`` CPU
    mesh: the collective accumulation over per-process window slices
    matches the meshless same-seed run and the exact spectrum."""

    def test_pod_sketch_matches_meshless_and_exact(self, tmp_path):
        nprocs = 2
        if nprocs * 2 > (os.cpu_count() or 1) * 4:
            pytest.skip("not enough cores to host the pod-sim")
        script = tmp_path / "worker.py"
        script.write_text(_POD_SKETCH_WORKER)
        out_file = tmp_path / "result.json"
        _run_pod_workers(script, [out_file], n=nprocs)
        result = json.loads(out_file.read_text())

        x, pair = structured_csr()
        got = np.asarray(result["coords"])
        got_vals = np.asarray(result["vals"])
        ref_c, ref_v = exact_eig(x)
        assert_spectrum(
            got, got_vals, ref_c, ref_v, SKETCH_TOPK_RTOL, SKETCH_TOPK_ATOL
        )
        single, single_vals = sketch_eig(_panel(pair), K)
        assert np.abs(got - single).max() <= 1e-4
        np.testing.assert_allclose(got_vals, single_vals, rtol=1e-5)
        # G's row sums survived the pod accumulation (parity print).
        np.testing.assert_allclose(
            np.asarray(result["row_sums"])[:N],
            (x.astype(np.float64) @ x.sum(0)).ravel(),
            rtol=1e-6,
        )
        # Every local window entered the protocol exactly once per
        # pass (3 passes: first + 2 power iterations), counted on the
        # lead process.
        assert (
            result["windows_delta"]["scatter"]
            + result["windows_delta"]["dense"]
            == 3 * result["my_windows"]
        )
        # Footprint: the pod panel is padded rows at the documented
        # O(N·l) bound (N² only loses at real scale, so pin the
        # formula, not an inequality that flips at toy N).
        assert result["n_padded"] % (nprocs * 2) == 0
        assert result["host_peak_bytes"] == sketch_host_bytes(
            result["n_padded"], randomized_panel_width(N, K)
        )
