"""Golden tests for the core PCoA math (SURVEY.md §4 strategy).

Every transform is tested against a hand-rolled numpy-f64 emulation of the
reference semantics (the Spark/Breeze driver math, ``VariantsPca.scala``),
including the O(k²) per-variant scalar-loop Gramian, double-centering, and
the MLlib principal-components composition.
"""

import numpy as np
import pytest

from spark_examples_tpu.ops import (
    double_center,
    gramian,
    gramian_accumulate,
    gramian_blockwise,
    mllib_principal_components_reference,
    normalize_eigvec_signs,
    pcoa,
    principal_components,
)


def reference_gramian_scalar(calls_per_variant, n):
    """The reference's literal hot loop: per variant, for each pair of
    carrying samples, matrix[c1, c2] += 1 (VariantsPca.scala:184-189)."""
    g = np.zeros((n, n), dtype=np.int64)
    for calls in calls_per_variant:
        for c1 in calls:
            for c2 in calls:
                g[c1, c2] += 1
    return g


def densify(calls_per_variant, n):
    x = np.zeros((n, len(calls_per_variant)), dtype=np.int8)
    for v, calls in enumerate(calls_per_variant):
        for c in calls:
            x[c, v] = 1
    return x


@pytest.fixture
def random_calls():
    rng = np.random.default_rng(42)
    n, v = 23, 197
    calls = []
    for _ in range(v):
        k = rng.integers(0, n + 1)
        calls.append(list(rng.choice(n, size=k, replace=False)))
    return calls, n


def test_gramian_matches_scalar_loop(random_calls):
    calls, n = random_calls
    x = densify(calls, n)
    g_ref = reference_gramian_scalar(calls, n)
    g = np.asarray(gramian(x))
    np.testing.assert_array_equal(g, g_ref.astype(np.float32))


def test_gramian_blockwise_matches_full(random_calls):
    calls, n = random_calls
    x = densify(calls, n)
    blocks = [x[:, i : i + 32] for i in range(0, x.shape[1], 32)]
    g_full = np.asarray(gramian(x))
    g_blk = np.asarray(gramian_blockwise(blocks, n))
    np.testing.assert_allclose(g_blk, g_full, rtol=0, atol=0)


def test_gramian_accumulate_step():
    rng = np.random.default_rng(0)
    x1 = (rng.random((7, 11)) < 0.4).astype(np.int8)
    x2 = (rng.random((7, 5)) < 0.4).astype(np.int8)
    import jax.numpy as jnp

    g = jnp.zeros((7, 7), jnp.float32)
    g = gramian_accumulate(g, jnp.asarray(x1))
    g = gramian_accumulate(g, jnp.asarray(x2))
    expected = x1 @ x1.T + x2 @ x2.T
    np.testing.assert_array_equal(np.asarray(g), expected.astype(np.float32))


def test_double_center_semantics():
    rng = np.random.default_rng(1)
    g = rng.random((9, 9))
    g = g + g.T  # symmetric
    c = np.asarray(double_center(g))
    # Reference formula entry-by-entry (VariantsPca.scala:212-223).
    expected = g - g.mean(1, keepdims=True) - g.mean(0, keepdims=True) + g.mean()
    np.testing.assert_allclose(c, expected, atol=1e-5)
    # Centered matrix has (near-)zero row and column means.
    np.testing.assert_allclose(c.mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(c.mean(1), 0.0, atol=1e-5)


def test_principal_components_match_mllib_golden(random_calls):
    """The BASELINE 1e-4 parity bar: fast path vs literal MLlib emulation."""
    calls, n = random_calls
    x = densify(calls, n)
    g = x.astype(np.float64) @ x.T.astype(np.float64)
    golden, _ = mllib_principal_components_reference(g, 2)

    coords, _ = pcoa(np.asarray(gramian(x)), 2)
    coords = np.asarray(coords)
    np.testing.assert_allclose(coords, golden, atol=1e-4)


def test_principal_components_ordering_and_signs():
    # Construct a matrix with known spectrum, incl. a dominant NEGATIVE
    # eigenvalue: MLlib orders by covariance eigenvalue = λ² so |λ| ordering
    # must pick the negative one first.
    rng = np.random.default_rng(3)
    q, _ = np.linalg.qr(rng.random((6, 6)))
    w = np.array([-10.0, 6.0, 3.0, 1.0, 0.5, 0.1])
    m = q @ np.diag(w) @ q.T
    # Double-center to make it a valid centered input (changes spectrum, so
    # compare directly against the golden instead of w).
    golden, _ = mllib_principal_components_reference(
        m + 100.0, 3
    )  # +100 offset removed by centering
    vecs, _ = principal_components(np.asarray(double_center(m + 100.0)), 3)
    np.testing.assert_allclose(np.asarray(vecs), golden, atol=1e-4)


def test_sign_normalization_deterministic():
    v = np.array([[0.9, -0.1], [-0.2, -0.8]])
    out = normalize_eigvec_signs(v)
    assert out[0, 0] > 0 and out[1, 1] > 0


def test_pcoa_scaled_coordinates_recover_distances():
    """Classical-MDS property: scaled coords from a Gram matrix of points
    reproduce centered inner products."""
    rng = np.random.default_rng(7)
    pts = rng.random((12, 3))
    pts -= pts.mean(0)
    g = pts @ pts.T
    coords, w = pcoa(g, 3, scale=True)
    coords = np.asarray(coords, dtype=np.float64)
    np.testing.assert_allclose(coords @ coords.T, g, atol=1e-3)


def test_gap_check_unsquares_covariance_eigenvalues():
    """The --precise path feeds MLlib-literal COVARIANCE eigenvalues
    (λ(C)²/(n−1)): a C-scale gap ratio of 0.96 is 0.9216 squared, which
    would sail under the 0.95 threshold without the sqrt."""
    import pytest

    from spark_examples_tpu.ops.pcoa import (
        SpectralGapWarning,
        topk_with_gap_check,
    )

    coords = np.zeros((2, 2))
    sq_vals = np.array([25.0, 23.04])  # λ = 5, 4.8 → true ratio 0.96

    with pytest.warns(SpectralGapWarning):
        topk_with_gap_check(
            lambda kk: (coords[:, :kk], sq_vals[:kk]),
            1,
            2,
            vals_are_squared=True,
        )

    # Un-sqrt'd, the same values stay (wrongly) silent — the scale gap
    # this test pins.
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error", SpectralGapWarning)
        topk_with_gap_check(
            lambda kk: (coords[:, :kk], sq_vals[:kk]), 1, 2
        )


class TestFusedPcoa:
    """ops/fused.py: the single-dispatch packed path must match the dense
    pipeline (gramian → pcoa) at the 1e-4 parity bar on structured
    cohorts, including ragged/padded packed widths."""

    def _structured_indicators(self, n, v, seed=0):
        rng = np.random.default_rng(seed)
        pop = rng.integers(0, 3, n)
        base = rng.random(v) * 0.12
        shift = (rng.random((3, v)) < 0.2) * rng.random((3, v)) * 0.5
        prob = np.clip(base[None, :] + shift[pop], 0, 0.9)
        return (rng.random((n, v)) < prob).astype(np.int8)

    def test_fused_matches_dense_pcoa(self):
        from spark_examples_tpu.ops.fused import pcoa_fused_packed
        from spark_examples_tpu.ops.gramian import (
            gramian,
            pack_indicator_block,
        )
        from spark_examples_tpu.ops.pcoa import pcoa

        x = self._structured_indicators(96, 500)
        coords_ref, vals_ref = pcoa(gramian(x), 2)
        coords, vals = pcoa_fused_packed(
            pack_indicator_block(x), 500, 2, chunk_bits=128, iters=40
        )
        assert coords.shape == (96, 2)
        np.testing.assert_allclose(
            coords, np.asarray(coords_ref), atol=1e-4
        )
        np.testing.assert_allclose(
            vals, np.asarray(vals_ref), rtol=1e-4
        )

    def test_fused_matches_mllib_f64_golden(self):
        from spark_examples_tpu.ops.fused import pcoa_fused_packed
        from spark_examples_tpu.ops.gramian import pack_indicator_block
        from spark_examples_tpu.ops.pcoa import (
            mllib_principal_components_reference,
        )

        x = self._structured_indicators(64, 320, seed=3)
        g64 = x.astype(np.int64) @ x.T.astype(np.int64)
        ref, _ = mllib_principal_components_reference(
            g64.astype(np.float64), 2
        )
        coords, _ = pcoa_fused_packed(
            pack_indicator_block(x), 320, 2, chunk_bits=64, iters=40
        )
        assert np.abs(coords - ref).max() <= 1e-4

    def test_fused_ragged_width_and_single_chunk(self):
        from spark_examples_tpu.ops.fused import pcoa_fused_packed
        from spark_examples_tpu.ops.gramian import (
            gramian,
            pack_indicator_block,
        )
        from spark_examples_tpu.ops.pcoa import pcoa

        # V=101: not a multiple of 8 (packbits pad bits) nor of the chunk
        # (zero-byte padding); chunk_bits larger than V collapses to one
        # padded chunk.
        x = self._structured_indicators(40, 101, seed=7)
        coords_ref, _ = pcoa(gramian(x), 2)
        for chunk in (48, 4096):
            coords, _ = pcoa_fused_packed(
                pack_indicator_block(x), 101, 2, chunk_bits=chunk, iters=40
            )
            np.testing.assert_allclose(
                coords, np.asarray(coords_ref), atol=1e-4
            )


class TestFusedFinishConvergence:
    def test_eig_tol_retries_with_doubled_iterations(self, recwarn):
        """resid_warn is a convergence TARGET: an under-iterated first
        sweep must retry doubled (one extra dispatch) rather than warn
        straight away."""
        import jax.numpy as jnp

        from spark_examples_tpu.ops.fused import (
            EigResidualWarning,
            fused_finish,
        )
        from spark_examples_tpu.ops.gramian import gramian
        from spark_examples_tpu.ops.pcoa import pcoa
        from spark_examples_tpu.utils.tracing import StageTimer

        rng = np.random.default_rng(3)
        pop = rng.integers(0, 3, 96)
        base = rng.random(500) * 0.12
        shift = (rng.random((3, 500)) < 0.2) * rng.random((3, 500)) * 0.5
        prob = np.clip(base[None, :] + shift[pop], 0, 0.9)
        x = (rng.random((96, 500)) < prob).astype(np.int8)
        g = gramian(x)
        timer = StageTimer()
        with timer.stage("t"):
            coords, _, _ = fused_finish(
                jnp.asarray(g), 2, iters=8, resid_warn=1e-5, timer=timer
            )
        report = timer.report()
        assert "retrying doubled" in report
        # The retried sweep converged: no residual warning fired.
        assert not [
            w for w in recwarn.list if w.category is EigResidualWarning
        ]
        ref, _ = pcoa(jnp.asarray(g).astype(jnp.float32), 2)
        assert np.abs(coords - np.asarray(ref)).max() <= 1e-4

    def test_unconverged_after_retries_warns(self):
        import jax.numpy as jnp
        import pytest as _pytest

        from spark_examples_tpu.ops.fused import (
            EigResidualWarning,
            fused_finish,
        )
        from spark_examples_tpu.ops.gramian import gramian

        rng = np.random.default_rng(5)
        x = (rng.random((64, 300)) < 0.2).astype(np.int8)
        g = gramian(x)
        with _pytest.warns(EigResidualWarning):
            fused_finish(
                jnp.asarray(g), 2, iters=1, resid_warn=1e-12,
                max_retries=1,
            )
